"""Config keys that change training semantics: forced splits,
feature_fraction_bynode, CEGB, snapshot_freq, pred_early_stop.

These were VERDICT round-2's "silent no-op" keys; each now either works
(tested here) or raises loudly (lazy CEGB).
"""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # e2e trainings

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 8))
    y = (X[:, 0] + 0.4 * X[:, 1] - 0.3 * X[:, 3] > 0.2).astype(np.float64)
    return X, y


def _tree_features(bst):
    used = set()

    def walk(nd):
        if "split_feature" in nd:
            used.add(nd["split_feature"])
            walk(nd["left_child"])
            walk(nd["right_child"])
    for t in bst.dump_model()["tree_info"]:
        if "split_feature" in t["tree_structure"]:
            walk(t["tree_structure"])
    return used


class TestForcedSplits:
    def test_forced_root_and_child(self, xy, tmp_path):
        X, y = xy
        fs = {"feature": 7, "threshold": 0.0,
              "right": {"feature": 6, "threshold": 0.5}}
        p = tmp_path / "forced.json"
        p.write_text(json.dumps(fs))
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "forcedsplits_filename": str(p)},
                        ds, num_boost_round=3, verbose_eval=False)
        for t in bst.dump_model()["tree_info"]:
            root = t["tree_structure"]
            assert root["split_feature"] == 7
            assert root["right_child"]["split_feature"] == 6

    def test_forced_matches_oracle_structure(self, xy, tmp_path):
        from .conftest import ORACLE_BIN, has_oracle
        if not has_oracle():
            pytest.skip("reference oracle not built")
        import subprocess
        X, y = xy
        fs = {"feature": 7, "threshold": 0.0}
        fjson = tmp_path / "forced.json"
        fjson.write_text(json.dumps(fs))
        data = tmp_path / "train.csv"
        np.savetxt(data, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
        subprocess.run(
            [ORACLE_BIN, "task=train", f"data={data}", "objective=binary",
             "num_trees=1", "num_leaves=15", "min_data_in_leaf=20",
             f"forcedsplits_filename={fjson}", "verbosity=-1",
             f"output_model={tmp_path}/ref.txt"],
            check=True, capture_output=True, cwd=str(tmp_path))
        ref = (tmp_path / "ref.txt").read_text()
        ref_kv = dict(l.split("=", 1) for l in ref.splitlines()
                      if "=" in l and not l.startswith("["))
        ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "min_data_in_leaf": 20, "tpu_split_batch": 1,
                         "forcedsplits_filename": str(fjson)},
                        ds, num_boost_round=1, verbose_eval=False)
        root = bst.dump_model()["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == \
            int(ref_kv["split_feature"].split()[0])


class TestFeatureFractionByNode:
    def test_learns_with_diverse_features(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "feature_fraction_bynode": 0.4, "seed": 3},
                        ds, num_boost_round=15, verbose_eval=False)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(X)) > 0.8
        assert len(_tree_features(bst)) >= 5


class TestCEGB:
    def test_coupled_penalty_avoids_feature(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "cegb_penalty_feature_coupled":
                             [1e6] + [0.0] * 7},
                        ds, num_boost_round=5, verbose_eval=False)
        assert 0 not in _tree_features(bst)

    def test_split_penalty_prunes(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        free = lgb.train({"objective": "binary", "num_leaves": 63},
                         ds, num_boost_round=3, verbose_eval=False)
        ds2 = lgb.Dataset(X, label=y, params={"max_bin": 63})
        taxed = lgb.train({"objective": "binary", "num_leaves": 63,
                           "cegb_penalty_split": 50.0},
                          ds2, num_boost_round=3, verbose_eval=False)
        n_free = sum(t["num_leaves"] for t in free.dump_model()["tree_info"])
        n_taxed = sum(t["num_leaves"] for t in taxed.dump_model()["tree_info"])
        assert n_taxed < n_free

    def test_lazy_penalty_avoids_feature(self, xy):
        """cegb_penalty_feature_lazy charges per UNPAID ROW (reference
        CalculateOndemandCosts, cost_effective_gradient_boosting.hpp:
        88-107): a huge lazy cost on feature 0 prices it out."""
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "cegb_penalty_feature_lazy":
                             [1e6] + [0.0] * 7},
                        ds, num_boost_round=5, verbose_eval=False)
        assert 0 not in _tree_features(bst)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(X)) > 0.7

    def test_lazy_rows_pay_once(self, xy):
        """Once rows pay a feature's lazy cost, later trees re-split it
        freely — the paid matrix persists across trees (reference
        feature_used_in_data_ lives for the learner's lifetime)."""
        X, y = xy
        # moderate uniform lazy cost: the learner should concentrate on
        # few features (re-splitting paid rows is free) instead of
        # spreading across all 8
        ds1 = lgb.Dataset(X, label=y, params={"max_bin": 63})
        taxed = lgb.train({"objective": "binary", "num_leaves": 15,
                           "cegb_tradeoff": 1.0,
                           "cegb_penalty_feature_lazy": [0.01] * 8},
                          ds1, num_boost_round=8, verbose_eval=False,
                          keep_training_booster=True)
        ds2 = lgb.Dataset(X, label=y, params={"max_bin": 63})
        free = lgb.train({"objective": "binary", "num_leaves": 15},
                         ds2, num_boost_round=8, verbose_eval=False)
        assert len(_tree_features(taxed)) <= len(_tree_features(free))
        # white box: the paid matrix is nonzero and bounded by F x n
        learner = taxed._driver.learner
        paid = np.asarray(learner._cegb_paid)
        assert paid.max() == 1.0 and paid.min() == 0.0

    def test_coupled_used_state_persists_across_trees(self, xy):
        """is_feature_used_in_split_ persists for the learner's lifetime
        (reference Init() runs once): features paid for by tree 1 are
        free for every later tree."""
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "cegb_penalty_feature_coupled": [5.0] * 8},
                        ds, num_boost_round=5, verbose_eval=False,
                        keep_training_booster=True)
        learner = bst._driver.learner
        used_state = set(np.nonzero(np.asarray(learner._cegb_used))[0])
        assert used_state == _tree_features(bst)

    def test_cegb_matches_oracle(self, xy, tmp_path):
        """Split-penalty CEGB parity vs the compiled reference: identical
        tree SIZE trajectory under strict best-first order (the penalty
        is cnt-scaled — DetlaGain, cost_effective_gradient_boosting.
        hpp:50 — so a mis-scaled charge prunes at different depths)."""
        from .conftest import ORACLE_BIN, has_oracle
        if not has_oracle():
            pytest.skip("reference oracle not built")
        import subprocess
        X, y = xy
        data = tmp_path / "train.csv"
        np.savetxt(data, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
        subprocess.run(
            [ORACLE_BIN, "task=train", f"data={data}", "objective=binary",
             "num_trees=3", "num_leaves=63", "min_data_in_leaf=20",
             "cegb_tradeoff=1.0", "cegb_penalty_split=0.05",
             "verbosity=-1", f"output_model={tmp_path}/ref.txt"],
            check=True, capture_output=True, cwd=str(tmp_path))
        ref_kv = [l for l in (tmp_path / "ref.txt").read_text().splitlines()
                  if l.startswith("num_leaves=")]
        ref_leaves = [int(l.split("=")[1]) for l in ref_kv]
        ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
        bst = lgb.train({"objective": "binary", "num_leaves": 63,
                         "min_data_in_leaf": 20, "tpu_split_batch": 1,
                         "cegb_tradeoff": 1.0, "cegb_penalty_split": 0.05},
                        ds, num_boost_round=3, verbose_eval=False)
        my_leaves = [t["num_leaves"]
                     for t in bst.dump_model()["tree_info"]]
        assert my_leaves == ref_leaves, (my_leaves, ref_leaves)

    def test_lazy_parallel_rejected(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y)
        with pytest.raises(NotImplementedError, match="serial"):
            lgb.train({"objective": "binary", "tree_learner": "data",
                       "num_machines": 8,
                       "cegb_penalty_feature_lazy": [1.0] * 8},
                      ds, num_boost_round=1, verbose_eval=False)

    def test_cegb_goss_rejected(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y)
        with pytest.raises(NotImplementedError, match="GOSS"):
            lgb.train({"objective": "binary", "boosting": "goss",
                       "cegb_penalty_split": 1.0},
                      ds, num_boost_round=1, verbose_eval=False)



    def test_cegb_coupled_recredit_drift(self, xy, tmp_path):
        """Quantified bound for the documented coupled-penalty
        divergence (ops/grower.py): on acquisition of a feature the TPU
        learner re-credits only each leaf's single STORED best split,
        while the reference re-evaluates per-(leaf, feature) candidates
        (UpdateLeafBestSplits) — a runner-up split on the newly-freed
        feature can be promoted there but not here.  The drift must stay
        small: identical feature-acquisition SET and a per-tree leaf
        trajectory within 20%."""
        from .conftest import ORACLE_BIN, has_oracle
        if not has_oracle():
            pytest.skip("reference oracle not built")
        import subprocess
        X, y = xy
        data = tmp_path / "train.csv"
        np.savetxt(data, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
        pen = ",".join(["0.5"] * X.shape[1])
        subprocess.run(
            [ORACLE_BIN, "task=train", f"data={data}", "objective=binary",
             "num_trees=4", "num_leaves=31", "min_data_in_leaf=20",
             "cegb_tradeoff=1.0",
             f"cegb_penalty_feature_coupled={pen}",
             "verbosity=-1", f"output_model={tmp_path}/ref.txt"],
            check=True, capture_output=True, cwd=str(tmp_path))
        ref_model = (tmp_path / "ref.txt").read_text()
        ref_leaves = [int(l.split("=")[1])
                      for l in ref_model.splitlines()
                      if l.startswith("num_leaves=")]
        ref_feats = set()
        for l in ref_model.splitlines():
            if l.startswith("split_feature="):
                ref_feats.update(int(v) for v in l.split("=")[1].split())
        ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "min_data_in_leaf": 20, "tpu_split_batch": 1,
                         "cegb_tradeoff": 1.0,
                         "cegb_penalty_feature_coupled": [0.5] * X.shape[1]},
                        ds, num_boost_round=4, verbose_eval=False)
        my_leaves = [t["num_leaves"] for t in bst.dump_model()["tree_info"]]
        my_feats = _tree_features(bst)
        assert my_feats == ref_feats, (my_feats, ref_feats)
        # a short/empty parse must not pass the bound vacuously
        assert len(my_leaves) == len(ref_leaves) == 4, \
            (my_leaves, ref_leaves)
        for mine, ref in zip(my_leaves, ref_leaves):
            assert abs(mine - ref) <= max(2, 0.2 * ref), \
                (my_leaves, ref_leaves)


class TestSnapshots:
    def test_snapshot_files_written(self, xy, tmp_path):
        X, y = xy
        ds = lgb.Dataset(X, label=y)
        out = tmp_path / "model.txt"
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "snapshot_freq": 2, "output_model": str(out)},
                  ds, num_boost_round=5, verbose_eval=False)
        snaps = sorted(p.name for p in tmp_path.glob("*.snapshot_iter_*"))
        assert snaps == ["model.txt.snapshot_iter_2",
                        "model.txt.snapshot_iter_4"]
        snap = lgb.Booster(model_file=str(tmp_path / snaps[0]))
        assert snap.num_trees() == 2


class TestPredEarlyStop:
    def test_confident_rows_stop_early(self, xy):
        X, y = xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "learning_rate": 0.3},
                        ds, num_boost_round=40, verbose_eval=False)
        p_full = bst.predict(X, raw_score=True)
        p_es = bst.predict(X, raw_score=True, pred_early_stop=True,
                           pred_early_stop_freq=5,
                           pred_early_stop_margin=3.0)
        stopped = np.abs(p_es - p_full) > 1e-12
        assert stopped.any()
        # stopped rows are on the right side with margin already reached
        assert (np.sign(p_es[stopped]) == np.sign(p_full[stopped])).all()
        assert (np.abs(p_es[stopped]) >= 3.0).all()


class TestParamConflicts:
    """Config._check_conflicts mirrors reference CheckParamConflict
    (src/io/config.cpp:248)."""

    def test_multiclass_needs_num_class(self):
        import pytest as _pt
        from lightgbm_tpu.config import Config
        with _pt.raises(ValueError, match="num_class"):
            Config({"objective": "multiclass"})

    def test_nonmulticlass_rejects_num_class(self):
        import pytest as _pt
        from lightgbm_tpu.config import Config
        with _pt.raises(ValueError, match="num_class"):
            Config({"objective": "binary", "num_class": 3})

    def test_metric_objective_mismatch(self):
        import pytest as _pt
        from lightgbm_tpu.config import Config
        with _pt.raises(ValueError, match="don't match"):
            Config({"objective": "binary", "metric": "multi_logloss"})
        with _pt.raises(ValueError, match="don't match"):
            Config({"objective": "multiclass", "num_class": 3,
                    "metric": "auc"})

    def test_max_depth_caps_num_leaves(self):
        from lightgbm_tpu.config import Config
        c = Config({"max_depth": 3, "num_leaves": 100})
        assert int(c.num_leaves) == 8

    def test_goss_rejects_bagging(self):
        import pytest as _pt
        from lightgbm_tpu.config import Config
        with _pt.raises(ValueError, match="bagging"):
            Config({"boosting": "goss", "bagging_fraction": 0.5,
                    "bagging_freq": 1})

    def test_disabled_metric_matches_any_objective(self):
        from lightgbm_tpu.config import Config
        # "None" disables built-in metrics (custom feval training) and
        # must not trip the multiclass consistency check
        c = Config({"objective": "multiclass", "num_class": 3,
                    "metric": "None"})
        assert int(c.num_class) == 3
        Config({"objective": "binary", "metric": "na"})
