"""sklearn-wrapper tests (reference tests/python_package_test/test_sklearn.py
surface, scaled down)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

from lightgbm_tpu.sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                                  LGBMRegressor)


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1200, 6))
    y = (X[:, 0] + X[:, 1] ** 2 > 1.0).astype(int)
    return X, y


class TestRegressor:
    def test_fit_predict(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(800, 5))
        y = X[:, 0] * 2 + X[:, 1] + rng.normal(size=800) * 0.1
        m = LGBMRegressor(n_estimators=20, num_leaves=15)
        m.fit(X, y)
        pred = m.predict(X)
        assert np.corrcoef(pred, y)[0, 1] > 0.95
        assert m.n_features_ == 5
        assert m.feature_importances_.shape == (5,)
        assert m.feature_importances_[:2].sum() > 0

    def test_eval_set_and_early_stopping(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(1000, 5))
        y = X[:, 0] + rng.normal(size=1000) * 0.1
        m = LGBMRegressor(n_estimators=200, num_leaves=7, learning_rate=0.3)
        m.fit(X[:800], y[:800], eval_set=[(X[800:], y[800:])],
              eval_metric="l2", early_stopping_rounds=5, verbose=False)
        assert m.best_iteration_ is not None and m.best_iteration_ >= 1
        assert m.evals_result_ is not None

    def test_custom_objective(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(500, 4))
        y = X[:, 0] + rng.normal(size=500) * 0.1

        def l2_obj(y_true, y_pred):
            return y_pred - y_true, np.ones_like(y_true)

        m = LGBMRegressor(n_estimators=15, num_leaves=7, objective=l2_obj)
        m.fit(X, y)
        pred = m.predict(X, raw_score=True)
        assert np.corrcoef(pred, y)[0, 1] > 0.9


class TestClassifier:
    def test_binary(self, clf_data):
        X, y = clf_data
        m = LGBMClassifier(n_estimators=20, num_leaves=15)
        m.fit(X, y)
        assert set(m.classes_) == {0, 1}
        assert m.n_classes_ == 2
        proba = m.predict_proba(X)
        assert proba.shape == (len(y), 2)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-6)
        acc = (m.predict(X) == y).mean()
        assert acc > 0.9

    def test_string_labels(self, clf_data):
        X, y = clf_data
        ys = np.where(y > 0, "pos", "neg")
        m = LGBMClassifier(n_estimators=10, num_leaves=15)
        m.fit(X, ys)
        pred = m.predict(X)
        assert set(np.unique(pred)) <= {"pos", "neg"}
        assert (pred == ys).mean() > 0.85

    def test_multiclass_auto(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(900, 5))
        y = np.digitize(X[:, 0], [-0.5, 0.5])
        m = LGBMClassifier(n_estimators=15, num_leaves=15)
        m.fit(X, y)
        assert m.n_classes_ == 3
        proba = m.predict_proba(X)
        assert proba.shape == (900, 3)
        assert (m.predict(X) == y).mean() > 0.8

    def test_class_weight_balanced(self, clf_data):
        X, y = clf_data
        m = LGBMClassifier(n_estimators=10, num_leaves=7,
                           class_weight="balanced")
        m.fit(X, y)
        assert (m.predict(X) == y).mean() > 0.8


class TestRanker:
    def test_fit_predict(self, rank_example):
        m = LGBMRanker(n_estimators=15, num_leaves=15,
                       min_child_samples=1)
        m.fit(rank_example["X_train"], rank_example["y_train"],
              group=rank_example["q_train"])
        pred = m.predict(rank_example["X_test"])
        assert pred.shape == (len(rank_example["y_test"]),)

    def test_requires_group(self, rank_example):
        m = LGBMRanker(n_estimators=2)
        with pytest.raises(ValueError, match="group"):
            m.fit(rank_example["X_train"], rank_example["y_train"])


class TestSklearnProtocol:
    def test_get_set_params(self):
        m = LGBMRegressor(num_leaves=63, learning_rate=0.05)
        p = m.get_params()
        assert p["num_leaves"] == 63
        m.set_params(num_leaves=31)
        assert m.get_params()["num_leaves"] == 31

    def test_clone_compatible(self):
        from sklearn.base import clone
        m = LGBMRegressor(num_leaves=63)
        try:
            m2 = clone(m)
            assert m2.get_params()["num_leaves"] == 63
        except Exception:
            pytest.skip("sklearn clone needs full estimator protocol")


class TestDatasetSetterParity:
    """Reference basic.py Dataset setter surface (set_categorical_feature /
    set_reference / set_feature_name) and Booster.free_dataset."""

    def test_setters_before_construct(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(0)
        Xc = rng.integers(0, 5, size=500).astype(np.float64)
        X = np.column_stack([Xc, rng.normal(size=500)])
        y = (Xc % 2).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
        ds.set_categorical_feature([0])
        ds.set_feature_name(["cat", "num"])
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "min_data_in_leaf": 5}, ds,
                        num_boost_round=3, verbose_eval=False)
        assert bst.feature_name() == ["cat", "num"]
        # the categorical split must materialize as a bitset decision
        # ("==" decision_type) somewhere in the dumped forest
        import json as _json
        d = _json.dumps(bst.dump_model())
        assert '"decision_type": "=="' in d

    def test_set_reference_aligns_bins(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(1)
        X = rng.normal(size=(400, 3))
        y = (X[:, 0] > 0).astype(np.float64)
        train = lgb.Dataset(X, label=y)
        train.construct()
        valid = lgb.Dataset(X[:100], label=y[:100])
        valid.set_reference(train)
        valid.construct()
        assert valid._inner.mappers is train._inner.mappers

    def test_setters_after_construct_raise(self):
        import lightgbm_tpu as lgb
        import pytest as _pt
        rng = np.random.default_rng(2)
        X = rng.normal(size=(300, 2))
        ds = lgb.Dataset(X, label=(X[:, 0] > 0).astype(float))
        ds.construct()
        with _pt.raises(RuntimeError):
            ds.set_categorical_feature([1])
        other = lgb.Dataset(X, label=(X[:, 0] > 0).astype(float))
        with _pt.raises(RuntimeError):
            ds.set_reference(other)

    def test_free_dataset_keeps_model_usable(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(3)
        X = rng.normal(size=(400, 3))
        y = (X[:, 0] > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "num_leaves": 7}, ds,
                        num_boost_round=3, verbose_eval=False)
        bst.free_dataset()
        p = bst.predict(X)
        assert p.shape == (400,)
