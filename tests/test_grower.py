"""Device compute core: histogram kernel + split search + grower.

Validates the TPU formulation against straightforward numpy oracles
(histograms) and against brute-force split enumeration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import build_histogram, pack_stats
from lightgbm_tpu.ops.split import find_best_split_all_features, leaf_output


def _np_histogram(bins, grad, hess, mask, B):
    n, F = bins.shape
    out = np.zeros((F, B, 3))
    for f in range(F):
        for r in range(n):
            if mask[r] > 0:
                b = bins[r, f]
                out[f, b, 0] += grad[r]
                out[f, b, 1] += hess[r]
                out[f, b, 2] += 1
    return out


class TestHistogram:
    @pytest.mark.parametrize("precision", ["hilo", "f32"])
    def test_matches_numpy(self, precision):
        rng = np.random.default_rng(0)
        n, F, B = 1000, 5, 16
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        grad = rng.normal(size=n).astype(np.float32)
        hess = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
        mask = (rng.random(n) < 0.8).astype(np.float32)
        ref = _np_histogram(bins, grad, hess, mask, B)
        stats = pack_stats(jnp.asarray(grad * mask), jnp.asarray(hess * mask),
                           jnp.asarray(mask), precision)
        hist = np.asarray(build_histogram(jnp.asarray(bins), stats, B,
                                          block_rows=256, precision=precision))
        tol = 1e-3 if precision == "hilo" else 1e-4
        np.testing.assert_allclose(hist[..., 0], ref[..., 0], atol=tol, rtol=tol)
        np.testing.assert_allclose(hist[..., 1], ref[..., 1], atol=tol, rtol=tol)
        np.testing.assert_allclose(hist[..., 2], ref[..., 2], atol=0.5)

    def test_hilo_much_better_than_bf16(self):
        rng = np.random.default_rng(1)
        n, B = 20000, 4
        bins = np.zeros((n, 1), np.int32)  # all rows -> one bin: stress summation
        grad = rng.normal(size=n).astype(np.float32)
        ones = np.ones(n, np.float32)
        exact = grad.astype(np.float64).sum()
        errs = {}
        for prec in ("hilo", "bf16"):
            stats = pack_stats(jnp.asarray(grad), jnp.asarray(ones),
                               jnp.asarray(ones), prec)
            hist = np.asarray(build_histogram(jnp.asarray(bins), stats, B,
                                              block_rows=4096, precision=prec))
            errs[prec] = abs(hist[0, 0, 0] - exact)
        assert errs["hilo"] < errs["bf16"] / 10


def _brute_force_best_split(hist, sum_g, sum_h, num_data, min_data, min_hess,
                            l1=0.0, l2=0.0):
    """Enumerate all (feature, threshold) splits; missing_type=None."""
    F, B, _ = hist.shape
    best = (-np.inf, -1, -1)
    for f in range(F):
        for t in range(B - 1):
            lg = hist[f, :t + 1, 0].sum()
            lh = hist[f, :t + 1, 1].sum()
            lc = hist[f, :t + 1, 2].sum()
            rg, rh, rc = sum_g - lg, sum_h - lh, num_data - lc
            if lc < min_data or rc < min_data or lh < min_hess or rh < min_hess:
                continue
            gain = lg * lg / (lh + l2 + 1e-38) + rg * rg / (rh + l2 + 1e-38)
            if gain > best[0]:
                best = (gain, f, t)
    return best


class TestSplitSearch:
    def test_matches_brute_force(self):
        rng = np.random.default_rng(2)
        F, B = 6, 16
        hist = np.zeros((F, B, 3), np.float32)
        hist[..., 0] = rng.normal(size=(F, B))
        hist[..., 1] = rng.uniform(0.5, 2.0, size=(F, B))
        hist[..., 2] = rng.integers(5, 50, size=(F, B))
        # make all features consistent: same totals
        sum_g = float(hist[0, :, 0].sum())
        sum_h = float(hist[0, :, 1].sum())
        cnt = float(hist[0, :, 2].sum())
        for f in range(1, F):
            scale_g = sum_g / hist[f, :, 0].sum()
            hist[f, :, 0] *= scale_g
            hist[f, :, 1] *= sum_h / hist[f, :, 1].sum()
            hist[f, :, 2] = hist[f, :, 2] * cnt / hist[f, :, 2].sum()
        cnt = float(hist[0, :, 2].sum())

        res = find_best_split_all_features(
            jnp.asarray(hist), jnp.float32(sum_g), jnp.float32(sum_h),
            jnp.float32(cnt),
            num_bin=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            default_bin=jnp.zeros(F, jnp.int32),
            monotone=jnp.zeros(F, jnp.int32),
            penalty=jnp.ones(F, jnp.float32),
            feature_mask=jnp.ones(F, jnp.float32),
            l1=0.0, l2=0.0, max_delta_step=0.0,
            min_data_in_leaf=5.0, min_sum_hessian=1e-3, min_gain_to_split=0.0)
        bf_gain, bf_f, bf_t = _brute_force_best_split(
            hist, sum_g, sum_h, cnt, 5, 1e-3)
        assert int(res.feature) == bf_f
        assert int(res.threshold) == bf_t

    def test_min_data_respected(self):
        F, B = 2, 8
        hist = np.zeros((F, B, 3), np.float32)
        # all mass in bins 0 and 7; only split 0..6 feasible but leaves tiny
        hist[:, 0] = [10.0, 5.0, 3.0]
        hist[:, 7] = [-10.0, 5.0, 100.0]
        res = find_best_split_all_features(
            jnp.asarray(hist), jnp.float32(0.0), jnp.float32(10.0),
            jnp.float32(103.0),
            num_bin=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            default_bin=jnp.zeros(F, jnp.int32),
            monotone=jnp.zeros(F, jnp.int32),
            penalty=jnp.ones(F, jnp.float32),
            feature_mask=jnp.ones(F, jnp.float32),
            l1=0.0, l2=0.0, max_delta_step=0.0,
            min_data_in_leaf=20.0, min_sum_hessian=1e-3, min_gain_to_split=0.0)
        assert float(res.gain) <= 0.0  # 3-row leaf violates min_data=20


class TestEndToEnd:
    def test_perfect_split_found(self):
        """A single feature perfectly separates labels -> tree must find it."""
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(3)
        n = 500
        X = rng.normal(size=(n, 3))
        y = (X[:, 1] > 0.3).astype(np.float64)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train({"objective": "binary", "num_leaves": 4,
                         "min_data_in_leaf": 5, "learning_rate": 0.5},
                        ds, num_boost_round=10, verbose_eval=False)
        pred = bst.predict(X)
        acc = ((pred > 0.5) == (y > 0)).mean()
        assert acc > 0.99
        # the first tree's root split must be on feature 1 near 0.3
        d = bst.dump_model()
        root = d["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 1
        assert abs(root["threshold"] - 0.3) < 0.2

    def test_monotone_constraints(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(4)
        n = 2000
        X = rng.uniform(-1, 1, size=(n, 2))
        y = 2 * X[:, 0] + 0.3 * np.sin(6 * X[:, 1]) + 0.1 * rng.normal(size=n)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train({"objective": "regression", "num_leaves": 31,
                         "monotone_constraints": [1, 0],
                         "min_data_in_leaf": 20},
                        ds, num_boost_round=20, verbose_eval=False)
        # predictions must be monotone nondecreasing in feature 0
        xs = np.linspace(-0.95, 0.95, 50)
        for x1 in (-0.5, 0.0, 0.5):
            grid = np.column_stack([xs, np.full(50, x1)])
            p = bst.predict(grid)
            assert np.all(np.diff(p) >= -1e-9)
