"""Device compute core: histogram kernel + split search + grower.

Validates the TPU formulation against straightforward numpy oracles
(histograms) and against brute-force split enumeration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import build_histogram, pack_stats
from lightgbm_tpu.ops.split import find_best_split_all_features, leaf_output


def _np_histogram(bins, grad, hess, mask, B):
    n, F = bins.shape
    out = np.zeros((F, B, 3))
    for f in range(F):
        for r in range(n):
            if mask[r] > 0:
                b = bins[r, f]
                out[f, b, 0] += grad[r]
                out[f, b, 1] += hess[r]
                out[f, b, 2] += 1
    return out


class TestHistogram:
    @pytest.mark.parametrize("precision", ["hilo", "f32"])
    def test_matches_numpy(self, precision):
        rng = np.random.default_rng(0)
        n, F, B = 1000, 5, 16
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        grad = rng.normal(size=n).astype(np.float32)
        hess = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
        mask = (rng.random(n) < 0.8).astype(np.float32)
        ref = _np_histogram(bins, grad, hess, mask, B)
        stats = pack_stats(jnp.asarray(grad * mask), jnp.asarray(hess * mask),
                           jnp.asarray(mask), precision)
        hist = np.asarray(build_histogram(jnp.asarray(bins), stats, B,
                                          block_rows=256, precision=precision))
        tol = 1e-3 if precision == "hilo" else 1e-4
        np.testing.assert_allclose(hist[..., 0], ref[..., 0], atol=tol, rtol=tol)
        np.testing.assert_allclose(hist[..., 1], ref[..., 1], atol=tol, rtol=tol)
        np.testing.assert_allclose(hist[..., 2], ref[..., 2], atol=0.5)

    def test_hilo_much_better_than_bf16(self):
        rng = np.random.default_rng(1)
        n, B = 20000, 4
        bins = np.zeros((n, 1), np.int32)  # all rows -> one bin: stress summation
        grad = rng.normal(size=n).astype(np.float32)
        ones = np.ones(n, np.float32)
        exact = grad.astype(np.float64).sum()
        errs = {}
        for prec in ("hilo", "bf16"):
            stats = pack_stats(jnp.asarray(grad), jnp.asarray(ones),
                               jnp.asarray(ones), prec)
            hist = np.asarray(build_histogram(jnp.asarray(bins), stats, B,
                                              block_rows=4096, precision=prec))
            errs[prec] = abs(hist[0, 0, 0] - exact)
        assert errs["hilo"] < errs["bf16"] / 10


def _brute_force_best_split(hist, sum_g, sum_h, num_data, min_data, min_hess,
                            l1=0.0, l2=0.0):
    """Enumerate all (feature, threshold) splits; missing_type=None."""
    F, B, _ = hist.shape
    best = (-np.inf, -1, -1)
    for f in range(F):
        for t in range(B - 1):
            lg = hist[f, :t + 1, 0].sum()
            lh = hist[f, :t + 1, 1].sum()
            lc = hist[f, :t + 1, 2].sum()
            rg, rh, rc = sum_g - lg, sum_h - lh, num_data - lc
            if lc < min_data or rc < min_data or lh < min_hess or rh < min_hess:
                continue
            gain = lg * lg / (lh + l2 + 1e-38) + rg * rg / (rh + l2 + 1e-38)
            if gain > best[0]:
                best = (gain, f, t)
    return best


class TestSplitSearch:
    def test_matches_brute_force(self):
        rng = np.random.default_rng(2)
        F, B = 6, 16
        hist = np.zeros((F, B, 3), np.float32)
        hist[..., 0] = rng.normal(size=(F, B))
        hist[..., 1] = rng.uniform(0.5, 2.0, size=(F, B))
        hist[..., 2] = rng.integers(5, 50, size=(F, B))
        # make all features consistent: same totals
        sum_g = float(hist[0, :, 0].sum())
        sum_h = float(hist[0, :, 1].sum())
        cnt = float(hist[0, :, 2].sum())
        for f in range(1, F):
            scale_g = sum_g / hist[f, :, 0].sum()
            hist[f, :, 0] *= scale_g
            hist[f, :, 1] *= sum_h / hist[f, :, 1].sum()
            hist[f, :, 2] = hist[f, :, 2] * cnt / hist[f, :, 2].sum()
        cnt = float(hist[0, :, 2].sum())

        res = find_best_split_all_features(
            jnp.asarray(hist), jnp.float32(sum_g), jnp.float32(sum_h),
            jnp.float32(cnt),
            num_bin=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            default_bin=jnp.zeros(F, jnp.int32),
            monotone=jnp.zeros(F, jnp.int32),
            penalty=jnp.ones(F, jnp.float32),
            feature_mask=jnp.ones(F, jnp.float32),
            l1=0.0, l2=0.0, max_delta_step=0.0,
            min_data_in_leaf=5.0, min_sum_hessian=1e-3, min_gain_to_split=0.0)
        bf_gain, bf_f, bf_t = _brute_force_best_split(
            hist, sum_g, sum_h, cnt, 5, 1e-3)
        assert int(res.feature) == bf_f
        assert int(res.threshold) == bf_t

    def test_min_data_respected(self):
        F, B = 2, 8
        hist = np.zeros((F, B, 3), np.float32)
        # all mass in bins 0 and 7; only split 0..6 feasible but leaves tiny
        hist[:, 0] = [10.0, 5.0, 3.0]
        hist[:, 7] = [-10.0, 5.0, 100.0]
        res = find_best_split_all_features(
            jnp.asarray(hist), jnp.float32(0.0), jnp.float32(10.0),
            jnp.float32(103.0),
            num_bin=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            default_bin=jnp.zeros(F, jnp.int32),
            monotone=jnp.zeros(F, jnp.int32),
            penalty=jnp.ones(F, jnp.float32),
            feature_mask=jnp.ones(F, jnp.float32),
            l1=0.0, l2=0.0, max_delta_step=0.0,
            min_data_in_leaf=20.0, min_sum_hessian=1e-3, min_gain_to_split=0.0)
        assert float(res.gain) <= 0.0  # 3-row leaf violates min_data=20


class TestEndToEnd:
    def test_perfect_split_found(self):
        """A single feature perfectly separates labels -> tree must find it."""
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(3)
        n = 500
        X = rng.normal(size=(n, 3))
        y = (X[:, 1] > 0.3).astype(np.float64)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train({"objective": "binary", "num_leaves": 4,
                         "min_data_in_leaf": 5, "learning_rate": 0.5},
                        ds, num_boost_round=10, verbose_eval=False)
        pred = bst.predict(X)
        acc = ((pred > 0.5) == (y > 0)).mean()
        assert acc > 0.99
        # the first tree's root split must be on feature 1 near 0.3
        d = bst.dump_model()
        root = d["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 1
        assert abs(root["threshold"] - 0.3) < 0.2

    def test_monotone_constraints(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(4)
        n = 2000
        X = rng.uniform(-1, 1, size=(n, 2))
        y = 2 * X[:, 0] + 0.3 * np.sin(6 * X[:, 1]) + 0.1 * rng.normal(size=n)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train({"objective": "regression", "num_leaves": 31,
                         "monotone_constraints": [1, 0],
                         "min_data_in_leaf": 20},
                        ds, num_boost_round=20, verbose_eval=False)
        # predictions must be monotone nondecreasing in feature 0
        xs = np.linspace(-0.95, 0.95, 50)
        for x1 in (-0.5, 0.0, 0.5):
            grid = np.column_stack([xs, np.full(50, x1)])
            p = bst.predict(grid)
            assert np.all(np.diff(p) >= -1e-9)


class TestPartitionImpls:
    """select- and gather-lowered partitions must grow identical trees."""

    def _train_dump(self, X, y, extra, impl):
        import lightgbm_tpu as lgb
        params = {"objective": "regression", "num_leaves": 31,
                  "min_data_in_leaf": 5, "max_bin": 64,
                  "tpu_partition_impl": impl, **extra}
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train(params, ds, num_boost_round=8, verbose_eval=False)
        # trees only: the parameters section embeds tpu_partition_impl
        # itself and must differ between the two runs
        return bst.model_to_string().split("parameters", 1)[0]

    def test_numerical_identical(self):
        rng = np.random.default_rng(7)
        X = rng.normal(size=(3000, 6))
        y = X[:, 0] ** 2 + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=3000)
        a = self._train_dump(X, y, {}, "select")
        b = self._train_dump(X, y, {}, "gather")
        assert a == b

    def test_categorical_and_missing_identical(self):
        rng = np.random.default_rng(8)
        n = 3000
        Xc = rng.integers(0, 8, size=n).astype(np.float64)
        Xn = rng.normal(size=n)
        Xn[rng.random(n) < 0.2] = np.nan  # exercise the missing path
        X = np.column_stack([Xc, Xn])
        y = (Xc % 3 == 1).astype(float) * 2 + np.nan_to_num(Xn) + \
            0.1 * rng.normal(size=n)
        extra = {"categorical_feature": [0]}
        a = self._train_dump(X, y, extra, "select")
        b = self._train_dump(X, y, extra, "gather")
        assert a == b

    def test_bundled_identical(self):
        rng = np.random.default_rng(9)
        n = 4000
        # sparse one-hot-ish columns so EFB actually bundles
        X = np.zeros((n, 6))
        grp = rng.integers(0, 3, size=n)
        for g in range(3):
            X[grp == g, g] = rng.uniform(1, 2, size=(grp == g).sum())
        X[:, 3:] = rng.normal(size=(n, 3))
        y = X[:, 0] + 2 * X[:, 1] - X[:, 2] + X[:, 3] + \
            0.1 * rng.normal(size=n)
        extra = {"enable_bundle": True}
        a = self._train_dump(X, y, extra, "select")
        b = self._train_dump(X, y, extra, "gather")
        assert a == b


class TestBatchedHistogramImpls:
    """xla and pallas backends of the batched kernel must agree bit-for-bit
    (pallas runs in interpret mode on CPU)."""

    def test_grower_pallas_matches_xla_end_to_end(self):
        """Whole-tree growth (root pass + every batched round) through the
        pallas backend must reproduce the xla backend's model exactly."""
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(11)
        X = rng.normal(size=(1024, 5))
        y = X[:, 0] - 2 * X[:, 1] + 0.1 * rng.normal(size=1024)

        def dump(impl):
            params = {"objective": "regression", "num_leaves": 15,
                      "min_data_in_leaf": 5, "max_bin": 32,
                      "tpu_hist_impl": impl, "tpu_block_rows": 256,
                      "verbosity": -1}
            ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
            bst = lgb.train(params, ds, num_boost_round=3,
                            verbose_eval=False)
            return bst.model_to_string().split("parameters", 1)[0]

        assert dump("pallas") == dump("xla")

    def test_pallas_matches_xla(self):
        from lightgbm_tpu.ops.histogram import (build_histogram_batched_t,
                                                pack_stats)
        rng = np.random.default_rng(3)
        nb, F, block, B, K = 3, 4, 256, 16, 5
        n = nb * block
        bins_t = jnp.asarray(
            rng.integers(0, B, size=(nb, F, block)), dtype=jnp.int32)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.abs(g) + 0.1
        stats = pack_stats(g, h, jnp.ones(n, jnp.float32), "hilo")
        stats_blocks = stats.reshape(stats.shape[0], nb, block)
        leaf_blocks = jnp.asarray(
            rng.integers(0, K + 2, size=(nb, block)), dtype=jnp.int32)
        slots = jnp.asarray([0, 2, 4, -1, 5], dtype=jnp.int32)
        a = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="xla")
        b = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="pallas")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # narrow dense storage: uint8 bins (the serial learner's default
        # when bins fit) must produce identical histograms on both backends
        bins_u8 = bins_t.astype(jnp.uint8)
        a8 = build_histogram_batched_t(bins_u8, stats_blocks, leaf_blocks,
                                       slots, B, "hilo", impl="xla")
        b8 = build_histogram_batched_t(bins_u8, stats_blocks, leaf_blocks,
                                       slots, B, "hilo", impl="pallas")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b8))


    def test_pallas_bp_padding_parity(self):
        # B=15 pads Bp->16 inside the kernel: the padded bin rows must not
        # leak into the returned [K, F, B, 3] histograms
        from lightgbm_tpu.ops.histogram import (build_histogram_batched_t,
                                                pack_stats)
        rng = np.random.default_rng(5)
        nb, F, block, B, K = 2, 3, 128, 15, 4
        n = nb * block
        bins_t = jnp.asarray(
            rng.integers(0, B, size=(nb, F, block)), dtype=jnp.uint8)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        stats = pack_stats(g, jnp.abs(g) + 0.5, jnp.ones(n, jnp.float32),
                           "hilo")
        stats_blocks = stats.reshape(stats.shape[0], nb, block)
        leaf_blocks = jnp.asarray(
            rng.integers(0, K, size=(nb, block)), dtype=jnp.int32)
        slots = jnp.asarray([1, 0, -1, 3], dtype=jnp.int32)
        a = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="pallas2")
        b = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="xla")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pallas2_matches_xla(self):
        # per-feature one-hot variant at its bigger native blocks
        from lightgbm_tpu.ops.histogram import (build_histogram_batched_t,
                                                pack_stats)
        rng = np.random.default_rng(6)
        nb, F, block, B, K = 2, 4, 512, 31, 6
        n = nb * block
        bins_t = jnp.asarray(
            rng.integers(0, B, size=(nb, F, block)), dtype=jnp.uint8)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        stats = pack_stats(g, jnp.abs(g) + 0.2, jnp.ones(n, jnp.float32),
                           "hilo")
        stats_blocks = stats.reshape(stats.shape[0], nb, block)
        leaf_blocks = jnp.asarray(
            rng.integers(0, K + 1, size=(nb, block)), dtype=jnp.int32)
        slots = jnp.asarray([2, 0, -1, 5, 1, 4], dtype=jnp.int32)
        a = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="xla")
        b = build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                      slots, B, "hilo", impl="pallas2")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pallas2_feature_chunked_grid(self, monkeypatch):
        # shrink the out-block VMEM budget so F=64 features are processed
        # in sublane-aligned divisor chunks (fblk=32 -> 2-chunk feature
        # grid axis), and the 2D (feature, row-block) grid must still
        # accumulate exactly
        from lightgbm_tpu.ops import histogram as H
        rng = np.random.default_rng(7)
        nb, F, block, B, K = 3, 64, 256, 16, 5
        Bp = 16
        ks_pad = 128
        monkeypatch.setattr(H, "_PERFEATURE_OUT_BUDGET",
                            32 * Bp * ks_pad * 4)  # fits fblk=32, not 64
        n = nb * block
        bins_t = jnp.asarray(
            rng.integers(0, B, size=(nb, F, block)), dtype=jnp.uint8)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        stats = H.pack_stats(g, jnp.abs(g) + 0.3, jnp.ones(n, jnp.float32),
                             "hilo")
        stats_blocks = stats.reshape(stats.shape[0], nb, block)
        leaf_blocks = jnp.asarray(
            rng.integers(0, K + 2, size=(nb, block)), dtype=jnp.int32)
        slots = jnp.asarray([0, 3, -1, 2, 6], dtype=jnp.int32)
        a = H.build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                        slots, B, "hilo", impl="xla")
        b = H.build_histogram_batched_t(bins_t, stats_blocks, leaf_blocks,
                                        slots, B, "hilo", impl="pallas2")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grower_pallas2_matches_xla_end_to_end(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(12)
        X = rng.normal(size=(1536, 4))
        y = np.sin(2 * X[:, 0]) + X[:, 1] + 0.1 * rng.normal(size=1536)

        def dump(impl):
            params = {"objective": "regression", "num_leaves": 15,
                      "min_data_in_leaf": 5, "max_bin": 32,
                      "tpu_hist_impl": impl, "tpu_block_rows": 512,
                      "verbosity": -1}
            ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
            bst = lgb.train(params, ds, num_boost_round=3,
                            verbose_eval=False)
            return bst.model_to_string().split("parameters", 1)[0]

        assert dump("pallas2") == dump("xla")


class TestFrontierRamp:
    """tpu_ramp pre-rounds must grow BIT-IDENTICAL trees (the frontier
    after r rounds never exceeds 2^r, so every ramp pre-round covers all
    splittable leaves the full-K loop would take — see GrowerParams.ramp)."""

    def _dump(self, X, y, **extra):
        import lightgbm_tpu as lgb
        params = {"objective": "regression", "num_leaves": 63,
                  "min_data_in_leaf": 5, "max_bin": 64,
                  "tpu_split_batch": 8, "verbosity": -1, **extra}
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train(params, ds, num_boost_round=4, verbose_eval=False)
        return bst.model_to_string().split("parameters", 1)[0]

    def test_bit_identical_trees(self):
        rng = np.random.default_rng(13)
        X = rng.normal(size=(4096, 6))
        y = X[:, 0] ** 2 - X[:, 1] + 0.3 * np.sin(4 * X[:, 2]) \
            + 0.1 * rng.normal(size=4096)
        assert (self._dump(X, y, tpu_ramp=True)
                == self._dump(X, y, tpu_ramp=False))

    def test_bit_identical_with_categoricals(self):
        rng = np.random.default_rng(14)
        n = 3000
        Xc = rng.integers(0, 9, size=n).astype(np.float64)
        Xn = rng.normal(size=(n, 3))
        X = np.column_stack([Xc, Xn])
        y = (Xc % 2) * 1.5 + Xn[:, 0] + 0.1 * rng.normal(size=n)
        extra = {"categorical_feature": [0]}
        assert (self._dump(X, y, tpu_ramp=True, **extra)
                == self._dump(X, y, tpu_ramp=False, **extra))


class TestPallas2Bundled:
    """EFB bundles + the perfeature kernel: the padded column axis and the
    bundle-histogram expansion must compose (learner pads g_pad to a
    32-multiple for pallas2; padding columns are all-zero and unused)."""

    def test_bundled_pallas2_matches_xla(self):
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(15)
        n = 4000
        X = np.zeros((n, 6))
        grp = rng.integers(0, 3, size=n)
        for g in range(3):
            X[grp == g, g] = rng.uniform(1, 2, size=(grp == g).sum())
        X[:, 3:] = rng.normal(size=(n, 3))
        y = X[:, 0] + 2 * X[:, 1] - X[:, 2] + X[:, 3] + \
            0.1 * rng.normal(size=n)

        def dump(impl):
            params = {"objective": "regression", "num_leaves": 15,
                      "min_data_in_leaf": 5, "max_bin": 32,
                      "enable_bundle": True, "tpu_hist_impl": impl,
                      "tpu_block_rows": 512, "verbosity": -1}
            ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
            bst = lgb.train(params, ds, num_boost_round=3,
                            verbose_eval=False)
            return bst.model_to_string().split("parameters", 1)[0]

        assert dump("pallas2") == dump("xla")


class TestPackedBins:
    """4-bit two-rows-per-byte bin packing (reference dense_nbits_bin.hpp
    analog): the packed pallas path must reproduce the unpacked models
    bit-for-bit, and the learner must only enable it when the layout
    supports it."""

    def _train(self, **extra):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(4)
        X = rng.normal(size=(3000, 10))
        y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "max_bin": 15, "tpu_hist_impl": "pallas2",
             "tpu_block_rows": 512, **extra}
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=5,
                        keep_training_booster=True)
        return bst

    def test_packed_model_identical_to_unpacked(self):
        out = {}
        for pack in (True, False):
            bst = self._train(tpu_pack_bins=pack)
            assert bst._driver.learner.packed_bins == pack
            out[pack] = bst.model_to_string().split("\nparameters:")[0]
        assert out[True] == out[False]

    def test_packed_flat_kernel_matches(self):
        bst = self._train(tpu_hist_impl="pallas", tpu_block_rows=256)
        ref = self._train(tpu_hist_impl="pallas", tpu_block_rows=256,
                          tpu_pack_bins=False)
        assert bst._driver.learner.packed_bins
        assert bst.model_to_string().split("\nparameters:")[0] == \
            ref.model_to_string().split("\nparameters:")[0]

    def test_packed_data_parallel_matches_unpacked(self):
        """The pack layout's blocks must coincide with the PER-SHARD
        grower blocks — a global-block layout split across data shards
        decodes the wrong rows silently (review finding, round 4)."""
        out = {}
        for pack in (True, False):
            bst = self._train(tree_learner="data", num_machines=8,
                              tpu_block_rows=256, tpu_pack_bins=pack)
            if pack:
                assert bst._driver.learner.packed_bins
            out[pack] = bst.model_to_string().split("\nparameters:")[0]
        assert out[True] == out[False]

    def test_packing_skipped_when_unsupported(self):
        # too many bins
        assert not self._train(max_bin=63)._driver.learner.packed_bins
        # xla impl
        assert not self._train(
            tpu_hist_impl="xla")._driver.learner.packed_bins
        # gather partition lowering
        assert not self._train(
            tpu_partition_impl="gather")._driver.learner.packed_bins
        # odd effective block (sub-256 alignment)
        assert not self._train(
            tpu_block_rows=128)._driver.learner.packed_bins


class TestVselectPartition:
    """tpu_partition_impl=vselect (one vectorized [K, n] pass) must
    reproduce the unrolled "select" lowering bit-for-bit across plain,
    categorical, EFB-bundled, and packed-bin configurations."""

    def _model(self, seed, cat=False, **extra):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(seed)
        X = rng.normal(size=(2500, 8))
        cat_idx = []
        if cat:
            X[:, 3] = rng.integers(0, 7, size=2500)
            cat_idx = [3]
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "max_bin": 31, "tpu_block_rows": 512, **extra}
        ds = lgb.Dataset(X, label=y, params=p,
                         categorical_feature=cat_idx or "auto")
        return lgb.train(p, ds, num_boost_round=5) \
            .model_to_string().split("\nparameters:")[0]

    @pytest.mark.parametrize("cfg", [
        {},
        {"cat": True},
        {"max_bin": 15, "tpu_hist_impl": "pallas2",
         "tpu_block_rows": 512},  # packed bins active
    ])
    def test_vselect_matches_select(self, cfg):
        cfg = dict(cfg)
        cat = cfg.pop("cat", False)
        a = self._model(9, cat=cat, tpu_partition_impl="select", **cfg)
        b = self._model(9, cat=cat, tpu_partition_impl="vselect", **cfg)
        assert a == b

    def test_vselect_matches_select_with_bundles(self):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(11)
        X = np.where(rng.random((3000, 10)) < 0.85, 0.0,
                     rng.normal(size=(3000, 10)))
        y = (X.sum(axis=1) > 0).astype(np.float64)
        out = []
        for impl in ("select", "vselect"):
            p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "max_bin": 31, "tpu_partition_impl": impl}
            ds = lgb.Dataset(X, label=y, params=p)
            out.append(lgb.train(p, ds, num_boost_round=5)
                       .model_to_string().split("\nparameters:")[0])
        assert out[0] == out[1]


class TestAutoHistResolution:
    """tpu_hist_impl=auto / tpu_block_rows=0 resolution (models/learner.py
    _resolve_hist_impl): platform- and VMEM-aware backend choice."""

    def _resolve(self, **params):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.models.learner import TPUTreeLearner
        cfg = Config({"objective": "binary",
                      **{k: v for k, v in params.items() if k != "_bins"}})
        prec = params.get("tpu_hist_precision", "hilo")
        return TPUTreeLearner._resolve_hist_impl(
            cfg, params.get("_bins", 255), prec)

    def test_cpu_auto_is_xla_streaming(self):
        # tests pin the cpu backend -> auto must never pick pallas here
        impl, block = self._resolve(num_leaves=255)
        assert impl == "xla"
        assert block == 16384

    def test_explicit_impl_and_block_pass_through(self):
        impl, block = self._resolve(tpu_hist_impl="pallas",
                                    tpu_block_rows=128)
        assert (impl, block) == ("pallas", 128)
        impl, block = self._resolve(tpu_hist_impl="xla")
        assert (impl, block) == ("xla", 16384)

    def test_pallas_auto_block_defaults_to_256(self):
        impl, block = self._resolve(tpu_hist_impl="pallas")
        assert (impl, block) == ("pallas", 256)

    def test_auto_vmem_branch_on_faked_tpu(self, monkeypatch):
        # exercise the auto branch's VMEM arithmetic by faking the platform
        class _Dev:
            platform = "tpu"
        monkeypatch.setattr(jax, "devices", lambda *a: [_Dev()])
        # Higgs shape -> the perfeature kernel at multi-k-row blocks
        # (docs/PERF_NOTES.md round-3 sweep winner)
        impl, block = self._resolve(num_leaves=255)
        assert (impl, block) == ("pallas2", 8192)
        # feature width never gates the choice (the kernel chunks the
        # feature axis itself); >256-bin data stores int32 bins whose
        # sublane tile is 8, so the kernel can retreat to 8-wide feature
        # chunks and 1024 bins still fits the VMEM accumulator budget
        impl, block = self._resolve(num_leaves=255, _bins=1024,
                                    max_bin=1024)
        assert (impl, block) == ("pallas2", 8192)
        # but a bin axis too tall for even the minimum 8-feature chunk
        # must fall back to the xla scan
        impl, block = self._resolve(num_leaves=255, _bins=2048,
                                    max_bin=2048)
        assert (impl, block) == ("xla", 16384)
        # explicit blocks beyond the hardware-validated range also fall
        # back (the [Bp, block]/[K*S, block] temporaries scale with block)
        impl, block = self._resolve(num_leaves=255, tpu_block_rows=32768)
        assert (impl, block) == ("xla", 32768)
        # f32 stays on the xla Precision.HIGHEST path in auto mode
        impl, block = self._resolve(num_leaves=255,
                                    tpu_hist_precision="f32")
        assert impl == "xla"
        # explicit non-lane-aligned block disables the pallas auto pick
        impl, block = self._resolve(num_leaves=255, tpu_block_rows=192)
        assert (impl, block) == ("xla", 192)


class TestSplitBatchAlpha:
    """tpu_split_batch_alpha near-tie guard (grower round body): at
    alpha ~ 1 only leaves within a hair of the round-max gain split, so
    batched growth must reduce to strict best-first (K=1) growth.  The
    comparison is the split multiset + predictions, not model text:
    near-tied leaves may split in one round instead of two consecutive
    ones, permuting leaf numbering without changing the tree function."""

    def _model(self, X, y, **extra):
        import lightgbm_tpu as lgb
        # num_leaves=16 with K=8 makes the leaf budget bind: WHICH splits
        # make the cut depends on growth order, so unguarded batching
        # demonstrably diverges from sequential and the alpha guard is
        # load-bearing in the equality assertion below
        params = {"objective": "regression", "num_leaves": 16,
                  "min_data_in_leaf": 5, "max_bin": 64,
                  "verbosity": -1, **extra}
        ds = lgb.Dataset(X, label=y, params={"max_bin": 64})
        bst = lgb.train(params, ds, num_boost_round=2, verbose_eval=False)
        splits = []

        def walk(nd):
            if "split_feature" in nd:
                splits.append((nd["split_feature"],
                               round(nd["threshold"], 6)))
                walk(nd["left_child"])
                walk(nd["right_child"])

        for t in bst.dump_model()["tree_info"]:
            walk(t["tree_structure"])
        return sorted(splits), bst.predict(X)

    def test_strict_alpha_reduces_to_sequential(self):
        rng = np.random.default_rng(31)
        X = rng.normal(size=(4096, 6))
        y = X[:, 0] ** 2 - X[:, 1] + 0.3 * np.sin(4 * X[:, 2]) \
            + 0.1 * rng.normal(size=4096)
        splits_seq, pred_seq = self._model(X, y, tpu_split_batch=1)
        # precondition: without the guard, batching picks a different
        # split set under this binding budget — otherwise the guarded
        # assertion below would pass vacuously
        splits_raw, _ = self._model(X, y, tpu_split_batch=8)
        assert splits_raw != splits_seq
        splits_a, pred_a = self._model(X, y, tpu_split_batch=8,
                                       tpu_split_batch_alpha=0.999)
        assert splits_a == splits_seq
        np.testing.assert_allclose(pred_a, pred_seq)
