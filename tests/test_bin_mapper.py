"""Unit + oracle-parity tests for the binning layer (SURVEY.md §2.1 BinMapper)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.bin_mapper import (BinMapper, BinType, MissingType,
                                        greedy_find_bin)
from lightgbm_tpu.io.dataset import TrainingData

from .conftest import has_oracle


class TestGreedyFindBin:
    def test_few_distinct_values(self):
        dv = [1.0, 2.0, 3.0]
        cnt = [10, 10, 10]
        bounds = greedy_find_bin(dv, cnt, max_bin=10, total_cnt=30, min_data_in_bin=3)
        assert bounds[-1] == float("inf")
        # boundaries must separate the distinct values
        assert len(bounds) == 3
        assert 1.0 < bounds[0] < 2.0
        assert 2.0 < bounds[1] < 3.0

    def test_min_data_in_bin_merges(self):
        dv = [1.0, 2.0, 3.0, 4.0]
        cnt = [1, 1, 1, 27]
        bounds = greedy_find_bin(dv, cnt, max_bin=10, total_cnt=30, min_data_in_bin=3)
        # 1,2,3 must be merged until >= 3 samples per bin
        assert len(bounds) == 2

    def test_many_distinct_equal_counts(self):
        dv = [float(i) for i in range(100)]
        cnt = [10] * 100
        bounds = greedy_find_bin(dv, cnt, max_bin=10, total_cnt=1000, min_data_in_bin=3)
        assert len(bounds) == 10
        # roughly equal-count bins: each bin spans ~10 values
        edges = [-np.inf] + bounds
        per_bin = [sum(c for v, c in zip(dv, cnt) if lo < v <= hi)
                   for lo, hi in zip(edges[:-1], edges[1:])]
        assert max(per_bin) <= 2 * min(per_bin)


class TestBinMapper:
    def test_numerical_basic(self):
        rng = np.random.default_rng(0)
        vals = rng.normal(size=1000)
        m = BinMapper()
        m.find_bin(vals, 1000, max_bin=16)
        assert m.missing_type == MissingType.NONE
        assert 2 <= m.num_bin <= 16
        bins = m.values_to_bins(vals)
        assert bins.min() >= 0 and bins.max() < m.num_bin
        # order preserving: larger value -> same or larger bin
        order = np.argsort(vals)
        assert np.all(np.diff(bins[order]) >= 0)

    def test_zero_bin_dedicated(self):
        rng = np.random.default_rng(1)
        vals = np.concatenate([rng.normal(size=500), np.zeros(500)])
        m = BinMapper()
        # sample excludes zeros; total count implies them
        m.find_bin(vals[np.abs(vals) > 1e-35], 1000, max_bin=32)
        zb = m.value_to_bin(0.0)
        assert m.default_bin == zb
        neg = m.value_to_bin(-0.5)
        pos = m.value_to_bin(0.5)
        assert neg < zb <= pos or neg <= zb < pos

    def test_nan_goes_to_last_bin(self):
        rng = np.random.default_rng(2)
        vals = np.concatenate([rng.normal(size=900), [np.nan] * 100])
        m = BinMapper()
        m.find_bin(vals, 1000, max_bin=16, use_missing=True)
        assert m.missing_type == MissingType.NAN
        assert m.value_to_bin(np.nan) == m.num_bin - 1
        assert m.values_to_bins(np.array([np.nan]))[0] == m.num_bin - 1

    def test_zero_as_missing(self):
        rng = np.random.default_rng(3)
        vals = rng.normal(size=1000)
        m = BinMapper()
        m.find_bin(vals, 2000, max_bin=16, zero_as_missing=True)
        assert m.missing_type == MissingType.ZERO

    def test_trivial_constant(self):
        m = BinMapper()
        m.find_bin(np.array([]), 1000, max_bin=16)  # all zeros
        assert m.is_trivial

    def test_categorical(self):
        rng = np.random.default_rng(4)
        vals = rng.choice([0, 1, 2, 5, 9], size=1000, p=[0.4, 0.3, 0.2, 0.07, 0.03])
        m = BinMapper()
        m.find_bin(vals.astype(float), 1000, max_bin=16,
                   bin_type=BinType.CATEGORICAL)
        assert m.bin_type == BinType.CATEGORICAL
        # most frequent category -> lowest bins, bin 0 is not category 0
        assert m.bin_2_categorical[0] != 0
        for cat in [0, 1, 2, 5, 9]:
            b = m.value_to_bin(float(cat))
            assert 0 <= b < m.num_bin
        # unseen category -> last bin
        assert m.value_to_bin(777.0) == m.num_bin - 1

    def test_roundtrip_serialization(self):
        rng = np.random.default_rng(5)
        vals = rng.normal(size=1000)
        m = BinMapper()
        m.find_bin(vals, 1000, max_bin=32)
        m2 = BinMapper.from_dict(m.to_dict())
        x = rng.normal(size=100)
        assert np.array_equal(m.values_to_bins(x), m2.values_to_bins(x))


class TestTrainingData:
    def test_from_matrix(self, binary_example):
        cfg = Config({"max_bin": 255, "min_data_in_bin": 3})
        d = TrainingData.from_matrix(binary_example["X_train"],
                                     binary_example["y_train"], cfg)
        assert d.num_data == 7000
        assert d.num_features <= 28
        assert d.bins.shape == (7000, d.num_features)
        assert d.metadata.label.shape == (7000,)

    def test_valid_alignment(self, binary_example):
        cfg = Config({"max_bin": 64})
        d = TrainingData.from_matrix(binary_example["X_train"],
                                     binary_example["y_train"], cfg)
        v = d.create_valid(binary_example["X_test"], binary_example["y_test"])
        assert v.mappers is d.mappers
        assert v.bins.shape[1] == d.bins.shape[1]

    def test_from_file(self, binary_example):
        cfg = Config({"max_bin": 255})
        d = TrainingData.from_file(binary_example["train_file"], cfg)
        assert d.num_data == 7000
        assert d.num_total_features == 28


@pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
class TestOracleParity:
    """Bit-exact bin parity vs the compiled reference (SURVEY.md §4 test model)."""

    @pytest.mark.parametrize("max_bin", [15, 63, 255])
    def test_binary_example_bins_match(self, binary_example, max_bin):
        from .oracle import dump_dataset_bins
        ref = dump_dataset_bins(binary_example["train_file"],
                                f"max_bin={max_bin} min_data_in_bin=3")
        cfg = Config({"max_bin": max_bin, "min_data_in_bin": 3})
        mine = TrainingData.from_file(binary_example["train_file"], cfg)
        assert ref["num_data"] == mine.num_data
        # compare per-original-column bin values
        ref_bins = ref["bins"]
        assert ref_bins.shape[0] == mine.num_data
        mismatched_cols = []
        for j, col in enumerate(mine.used_feature_idx):
            if not np.array_equal(ref_bins[:, col], mine.bins[:, j].astype(np.int64)):
                diff = int((ref_bins[:, col] != mine.bins[:, j]).sum())
                mismatched_cols.append((col, diff))
        assert not mismatched_cols, f"bin mismatch in columns {mismatched_cols}"
