// OpenMP forest predictor: the whole-model traversal hot loop in native
// code.
//
// Role mirror of the reference's prediction path (reference
// src/boosting/gbdt_prediction.cpp:13-58 PredictRaw over per-row OMP,
// tree walk in include/LightGBM/tree.h:238-318).  The Python/JAX side
// packs every tree's node tables into ONE set of concatenated arrays
// (offsets per tree), so a single C call scores all rows x all trees with
// no per-tree Python dispatch — the fix for the host-side per-tree loop
// that dominated multi-hundred-tree predicts.
//
// Decision semantics match lightgbm_tpu/models/tree.py Tree.predict /
// Tree._categorical_go_left exactly (f64 thresholds, zero/nan missing
// handling, category bitsets), which in turn match the reference model
// format — verified by the oracle interchange tests.

#include <cmath>
#include <cstdint>
#include <omp.h>

#define LGBM_EXPORT extern "C" __attribute__((visibility("default")))

// num_threads config plumbing (reference honors it via OpenMP everywhere,
// e.g. src/c_api.cpp omp_set_num_threads on num_threads>0); n<=0 restores
// the pre-override default (which respects the user's OMP_NUM_THREADS),
// captured on the first call — every override goes through here, so the
// first-call value is the genuine startup default
LGBM_EXPORT void LGBMTPU_SetNumThreads(int32_t n) {
  static const int startup_default = omp_get_max_threads();
  omp_set_num_threads(n > 0 ? n : startup_default);
}

namespace {

constexpr double kZeroThreshold = 1e-35;
constexpr int8_t kCategoricalMask = 1;
constexpr int8_t kDefaultLeftMask = 2;

struct Forest {
  const int32_t* node_offset;   // [T+1] into node tables
  const int32_t* leaf_offset;   // [T+1] into leaf_value
  const int32_t* split_feature;
  const double* threshold;
  const int8_t* decision_type;
  const int32_t* left_child;
  const int32_t* right_child;
  const double* leaf_value;
  const int32_t* cat_bound_offset;  // [T+1] into cat_boundaries
  const int32_t* cat_boundaries;    // per-tree word-range boundaries
  const int32_t* cat_word_offset;   // [T+1] into cat_words
  const uint32_t* cat_words;        // bitset words (categories going left)
};

// leaf index (within the tree's leaf block) for one row of one tree
inline int32_t walk(const Forest& f, int32_t tree, const double* row) {
  const int32_t base = f.node_offset[tree];
  const int32_t num_nodes = f.node_offset[tree + 1] - base;
  if (num_nodes == 0) return 0;
  int32_t node = 0;
  while (node >= 0) {
    const int32_t k = base + node;
    const double v = row[f.split_feature[k]];
    const int8_t dt = f.decision_type[k];
    const int mt = (dt >> 2) & 3;
    bool left;
    if (dt & kCategoricalMask) {
      // category bitset membership; negatives route right.  NaN routes
      // right only for missing_type NaN — otherwise it folds to category
      // 0, matching Tree._categorical_go_left (models/tree.py:216-233)
      left = false;
      int64_t cat = -1;
      if (std::isnan(v)) {
        if (mt != 2) cat = 0;
      } else {
        // truncate BEFORE the negative test: values in (-1, 0) fold to
        // category 0, like the oracle's int64(fval) then <0 check
        cat = static_cast<int64_t>(v);
      }
      if (cat >= 0) {
        const int32_t cidx = static_cast<int32_t>(f.threshold[k]);
        const int32_t* bounds = f.cat_boundaries + f.cat_bound_offset[tree];
        const uint32_t* words = f.cat_words + f.cat_word_offset[tree];
        const int64_t w = cat / 32;
        if (w < bounds[cidx + 1] - bounds[cidx]) {
          left = (words[bounds[cidx] + w] >> (cat % 32)) & 1u;
        }
      }
    } else {
      double fv = v;
      bool is_default;
      if (mt == 2) {  // NaN missing
        is_default = std::isnan(fv);
      } else {
        if (std::isnan(fv)) fv = 0.0;
        is_default = (mt == 1) && std::fabs(fv) <= kZeroThreshold;
      }
      left = is_default ? (dt & kDefaultLeftMask) != 0
                        : fv <= f.threshold[k];
    }
    node = left ? f.left_child[k] : f.right_child[k];
  }
  return ~node;
}

}  // namespace

// Sum leaf values of trees [0, num_trees) into out[class][row]; tree i
// belongs to class i % num_class (the reference's per-iteration class
// interleaving, gbdt_prediction.cpp:17-29).  early_stop_freq > 0 enables
// prediction early stopping (reference src/boosting/
// prediction_early_stop.cpp:75-81): every freq iterations the row's
// margin — |score| for binary, best-minus-second for multiclass — is
// checked against early_stop_margin and the remaining trees are skipped
// once it is exceeded.
LGBM_EXPORT int LGBMTPU_ForestPredict(
    const double* X, int64_t nrow, int32_t ncol, int32_t num_trees,
    int32_t num_class, const int32_t* node_offset,
    const int32_t* leaf_offset, const int32_t* split_feature,
    const double* threshold, const int8_t* decision_type,
    const int32_t* left_child, const int32_t* right_child,
    const double* leaf_value, const int32_t* cat_bound_offset,
    const int32_t* cat_boundaries, const int32_t* cat_word_offset,
    const uint32_t* cat_words, int32_t early_stop_freq,
    double early_stop_margin, double* out) {
  Forest f{node_offset, leaf_offset, split_feature, threshold,
           decision_type, left_child, right_child, leaf_value,
           cat_bound_offset, cat_boundaries, cat_word_offset, cat_words};
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < nrow; ++r) {
    const double* row = X + r * ncol;
    for (int32_t t = 0; t < num_trees; ++t) {
      const int32_t leaf = walk(f, t, row);
      out[(t % num_class) * nrow + r] += leaf_value[f.leaf_offset[t] + leaf];
      if (early_stop_freq > 0 && t % num_class == num_class - 1) {
        const int32_t iter = t / num_class + 1;
        if (iter % early_stop_freq == 0) {
          double margin;
          if (num_class == 1) {
            margin = std::fabs(out[r]);
          } else {
            double best = out[r], second = -1e300;
            for (int32_t c = 1; c < num_class; ++c) {
              const double v = out[c * nrow + r];
              if (v > best) { second = best; best = v; }
              else if (v > second) { second = v; }
            }
            margin = best - second;
          }
          if (margin >= early_stop_margin) break;
        }
      }
    }
  }
  return 0;
}

// Leaf indices instead of summed values: out[row][tree].
LGBM_EXPORT int LGBMTPU_ForestPredictLeaf(
    const double* X, int64_t nrow, int32_t ncol, int32_t num_trees,
    const int32_t* node_offset, const int32_t* leaf_offset,
    const int32_t* split_feature, const double* threshold,
    const int8_t* decision_type, const int32_t* left_child,
    const int32_t* right_child, const double* leaf_value,
    const int32_t* cat_bound_offset, const int32_t* cat_boundaries,
    const int32_t* cat_word_offset, const uint32_t* cat_words,
    int32_t* out) {
  Forest f{node_offset, leaf_offset, split_feature, threshold,
           decision_type, left_child, right_child, leaf_value,
           cat_bound_offset, cat_boundaries, cat_word_offset, cat_words};
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < nrow; ++r) {
    const double* row = X + r * ncol;
    for (int32_t t = 0; t < num_trees; ++t) {
      out[r * num_trees + t] = walk(f, t, row);
    }
  }
  return 0;
}

// ---- binned-space walker ---------------------------------------------
//
// Same node tables as the raw walker but thresholds are BIN ids
// (threshold_in_bin / split_feature_inner / *_inner bitsets) and rows are
// the uint8/uint16 bin matrix; missing routing consults per-feature
// num_bin/default_bin/missing_type (the NumericalDecisionInner semantics,
// reference tree.h:252-318).  Scores a SUBSET of trees with per-tree
// scales in one OMP pass — the host-side per-tree loop this replaces
// dominated DART drop/restore and rollback at many trees x datasets.

namespace {

struct BinnedForest {
  const int32_t* node_offset;
  const int32_t* leaf_offset;
  const int32_t* split_feature_inner;
  const int32_t* threshold_in_bin;
  const int8_t* decision_type;
  const int32_t* left_child;
  const int32_t* right_child;
  const double* leaf_value;
  const int32_t* cat_bound_offset;
  const int32_t* cat_boundaries;
  const int32_t* cat_word_offset;
  const uint32_t* cat_words;
  const int32_t* num_bin;       // per inner feature
  const int32_t* default_bin;
  const int32_t* missing_type;
};

template <typename BinT>
inline int32_t walk_binned(const BinnedForest& f, int32_t tree,
                           const BinT* row, int64_t row_stride) {
  const int32_t base = f.node_offset[tree];
  if (f.node_offset[tree + 1] - base == 0) return 0;
  int32_t node = 0;
  while (node >= 0) {
    const int32_t k = base + node;
    const int32_t feat = f.split_feature_inner[k];
    const int64_t fbin = static_cast<int64_t>(row[feat * row_stride]);
    const int8_t dt = f.decision_type[k];
    const int mt = f.missing_type[feat];
    bool left;
    if (dt & kCategoricalMask) {
      left = false;
      const int32_t cidx = f.threshold_in_bin[k];
      const int32_t* bounds = f.cat_boundaries + f.cat_bound_offset[tree];
      const uint32_t* words = f.cat_words + f.cat_word_offset[tree];
      const int64_t w = fbin / 32;
      if (w < bounds[cidx + 1] - bounds[cidx]) {
        left = (words[bounds[cidx] + w] >> (fbin % 32)) & 1u;
      }
    } else {
      bool is_missing;
      if (mt == 2) {
        is_missing = fbin == f.num_bin[feat] - 1;
      } else if (mt == 1) {
        is_missing = fbin == f.default_bin[feat];
      } else {
        is_missing = false;
      }
      left = is_missing ? (dt & kDefaultLeftMask) != 0
                        : fbin <= f.threshold_in_bin[k];
    }
    node = left ? f.left_child[k] : f.right_child[k];
  }
  return ~node;
}

}  // namespace

// bins laid out [nrow, ncol] row-major; bin_dtype: 0 = uint8, 1 = uint16.
// For each listed tree t (tree_ids[i]) adds scale[i] * leaf_value to
// out[row] — one call covers a DART drop set or a rollback.
LGBM_EXPORT int LGBMTPU_ForestPredictBinnedSubset(
    const void* bins, int32_t bin_dtype, int64_t nrow, int32_t ncol,
    const int32_t* tree_ids, const double* scales, int32_t num_listed,
    const int32_t* node_offset, const int32_t* leaf_offset,
    const int32_t* split_feature_inner, const int32_t* threshold_in_bin,
    const int8_t* decision_type, const int32_t* left_child,
    const int32_t* right_child, const double* leaf_value,
    const int32_t* cat_bound_offset, const int32_t* cat_boundaries,
    const int32_t* cat_word_offset, const uint32_t* cat_words,
    const int32_t* num_bin, const int32_t* default_bin,
    const int32_t* missing_type, double* out) {
  BinnedForest f{node_offset, leaf_offset, split_feature_inner,
                 threshold_in_bin, decision_type, left_child, right_child,
                 leaf_value, cat_bound_offset, cat_boundaries,
                 cat_word_offset, cat_words, num_bin, default_bin,
                 missing_type};
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < nrow; ++r) {
    double acc = 0.0;
    for (int32_t i = 0; i < num_listed; ++i) {
      const int32_t t = tree_ids[i];
      int32_t leaf;
      if (bin_dtype == 0) {
        leaf = walk_binned<uint8_t>(
            f, t, static_cast<const uint8_t*>(bins) + r * ncol, 1);
      } else {
        leaf = walk_binned<uint16_t>(
            f, t, static_cast<const uint16_t*>(bins) + r * ncol, 1);
      }
      acc += scales[i] * leaf_value[f.leaf_offset[t] + leaf];
    }
    out[r] += acc;
  }
  return 0;
}
