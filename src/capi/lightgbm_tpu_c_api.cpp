// lib_lightgbm_tpu.so — the LGBM_* C ABI for the TPU-native framework.
//
// Role mirror of reference src/c_api.cpp (the ABI consumed by the Python /
// R / SWIG bindings and external integrations, reference
// include/LightGBM/c_api.h:52-1018) with the stack inverted: the compute
// engine here is Python+JAX (the XLA executable is the native core), so
// this C++ layer embeds CPython and marshals each call into
// lightgbm_tpu.capi_support.  Handles are integer ids owned by the Python
// registry; buffers cross as raw pointers and are wrapped with numpy on
// the Python side.
//
// Error contract matches the reference: every entry point returns 0/-1 and
// LGBM_GetLastError() returns the last failure message (thread-local, like
// the reference's error ring, c_api.h:40).
//
// Build: see src/capi/build.sh (g++ -shared against libpython).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

#define LGBM_EXPORT extern "C" __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

namespace {

thread_local std::string g_last_error = "everything is fine";
std::once_flag g_init_flag;
PyObject* g_support = nullptr;  // lightgbm_tpu.capi_support module

void set_error(const std::string& msg) { g_last_error = msg; }

// Initialize the embedded interpreter exactly once.  When the host process
// already runs Python (e.g. a ctypes test), reuse its interpreter and only
// import the support module under the GIL.
void ensure_python() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // embedded host: release the GIL acquired by Py_Initialize so that
      // PyGILState_Ensure works from any caller thread
      PyEval_SaveThread();
    }
    PyGILState_STATE st = PyGILState_Ensure();
    // make the package importable: LIGHTGBM_TPU_PYROOT or this .so's repo
    const char* root = std::getenv("LIGHTGBM_TPU_PYROOT");
    PyObject* sys_path = PySys_GetObject("path");
    if (root && sys_path) {
      PyObject* p = PyUnicode_FromString(root);
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
    g_support = PyImport_ImportModule("lightgbm_tpu.capi_support");
    if (!g_support) {
      PyErr_Print();
    }
    PyGILState_Release(st);
  });
}

std::string py_error_string() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  if (trace) {  // append the traceback for diagnosability
    PyObject* tb_mod = PyImport_ImportModule("traceback");
    if (tb_mod) {
      PyObject* lines = PyObject_CallMethod(tb_mod, "format_exception",
                                            "OOO", type, value, trace);
      if (lines) {
        PyObject* sep = PyUnicode_FromString("");
        PyObject* joined = PyUnicode_Join(sep, lines);
        if (joined) {
          const char* c = PyUnicode_AsUTF8(joined);
          if (c) msg = c;
          Py_DECREF(joined);
        }
        Py_DECREF(sep);
        Py_DECREF(lines);
      }
      Py_DECREF(tb_mod);
    }
    PyErr_Clear();
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Call capi_support.<fn>(args...) under the GIL; returns a NEW reference or
// nullptr (error already recorded).
PyObject* call_support(const char* fn, const char* fmt, ...) {
  ensure_python();
  if (!g_support) {
    set_error("lightgbm_tpu.capi_support import failed "
              "(set LIGHTGBM_TPU_PYROOT to the repo root)");
    return nullptr;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* callee = PyObject_GetAttrString(g_support, fn);
  PyObject* result = nullptr;
  if (callee) {
    va_list va;
    va_start(va, fmt);
    PyObject* args = Py_VaBuildValue(fmt, va);
    va_end(va);
    if (args) {
      result = PyObject_CallObject(callee, args);
      Py_DECREF(args);
    }
    Py_DECREF(callee);
  }
  if (!result) {
    set_error(py_error_string());
    PyErr_Clear();
  }
  PyGILState_Release(st);
  return result;  // caller must take GIL again to DECREF… see drop()
}

// DECREF helper that re-takes the GIL (call_support released it).
void drop(PyObject* o) {
  if (!o) return;
  PyGILState_STATE st = PyGILState_Ensure();
  Py_DECREF(o);
  PyGILState_Release(st);
}

long long as_int(PyObject* o, bool* ok) {
  PyGILState_STATE st = PyGILState_Ensure();
  long long v = PyLong_AsLongLong(o);
  *ok = !PyErr_Occurred();
  if (!*ok) {
    set_error(py_error_string());
    PyErr_Clear();
  }
  PyGILState_Release(st);
  return v;
}

// unpack an (a, b) int tuple
double as_double(PyObject* o, bool* ok) {
  PyGILState_STATE st = PyGILState_Ensure();
  double v = PyFloat_AsDouble(o);
  *ok = !PyErr_Occurred();
  if (!*ok) {
    set_error(py_error_string());
    PyErr_Clear();
  }
  PyGILState_Release(st);
  return v;
}
bool as_int2(PyObject* o, long long* a, long long* b) {
  PyGILState_STATE st = PyGILState_Ensure();
  bool ok = false;
  if (PyTuple_Check(o) && PyTuple_Size(o) == 2) {
    *a = PyLong_AsLongLong(PyTuple_GetItem(o, 0));
    *b = PyLong_AsLongLong(PyTuple_GetItem(o, 1));
    ok = !PyErr_Occurred();
  }
  if (!ok) {
    set_error("expected (int, int) result");
    PyErr_Clear();
  }
  PyGILState_Release(st);
  return ok;
}

std::string as_str(PyObject* o, bool* ok) {
  PyGILState_STATE st = PyGILState_Ensure();
  std::string out;
  const char* c = PyUnicode_AsUTF8(o);
  *ok = (c != nullptr);
  if (c) out = c;
  else {
    set_error(py_error_string());
    PyErr_Clear();
  }
  PyGILState_Release(st);
  return out;
}

inline void* to_handle(long long id) {
  return reinterpret_cast<void*>(static_cast<intptr_t>(id));
}
inline long long from_handle(const void* h) {
  return static_cast<long long>(reinterpret_cast<intptr_t>(h));
}

}  // namespace

LGBM_EXPORT const char* LGBM_GetLastError() { return g_last_error.c_str(); }

// ------------------------------------------------------------------ dataset

LGBM_EXPORT int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                          int32_t nrow, int32_t ncol,
                                          int is_row_major,
                                          const char* parameters,
                                          const DatasetHandle reference,
                                          DatasetHandle* out) {
  PyObject* r = call_support("dataset_create_from_mat", "(LiiiisL)",
                             (long long)(intptr_t)data, data_type,
                             (int)nrow, (int)ncol, is_row_major,
                             parameters ? parameters : "",
                             from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col, const char* parameters, const DatasetHandle reference,
    DatasetHandle* out) {
  PyObject* r = call_support(
      "dataset_create_from_csr", "(LiLLiLLLsL)",
      (long long)(intptr_t)indptr, indptr_type,
      (long long)(intptr_t)indices, (long long)(intptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      parameters ? parameters : "", from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromFile(const char* filename,
                                           const char* parameters,
                                           const DatasetHandle reference,
                                           DatasetHandle* out) {
  PyObject* r = call_support("dataset_create_from_file", "(ssL)", filename,
                             parameters ? parameters : "",
                             from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetSaveBinary(DatasetHandle handle,
                                       const char* filename) {
  PyObject* r = call_support("dataset_save_binary", "(Ls)",
                             from_handle(handle), filename);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetFree(DatasetHandle handle) {
  PyObject* r = call_support("free_handle", "(L)", from_handle(handle));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  PyObject* r = call_support("dataset_num_data", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = (int32_t)v;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetNumFeature(DatasetHandle handle,
                                          int32_t* out) {
  PyObject* r =
      call_support("dataset_num_feature", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = (int32_t)v;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetSetField(DatasetHandle handle,
                                     const char* field_name,
                                     const void* field_data, int num_element,
                                     int type) {
  PyObject* r = call_support("dataset_set_field", "(LsLii)",
                             from_handle(handle), field_name,
                             (long long)(intptr_t)field_data, num_element,
                             type);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetField(DatasetHandle handle,
                                     const char* field_name, int* out_len,
                                     const void** out_ptr, int* out_type) {
  PyObject* r = call_support("dataset_get_field", "(Ls)",
                             from_handle(handle), field_name);
  if (!r) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  bool ok = PyTuple_Check(r) && PyTuple_Size(r) == 3;
  long long ptr = 0, len = 0, dt = -1;
  if (ok) {
    ptr = PyLong_AsLongLong(PyTuple_GetItem(r, 0));
    len = PyLong_AsLongLong(PyTuple_GetItem(r, 1));
    dt = PyLong_AsLongLong(PyTuple_GetItem(r, 2));
    ok = !PyErr_Occurred();
  }
  if (!ok) {
    set_error("dataset_get_field returned malformed tuple");
    PyErr_Clear();
  }
  Py_DECREF(r);
  PyGILState_Release(st);
  if (!ok) return -1;
  *out_ptr = reinterpret_cast<const void*>(static_cast<intptr_t>(ptr));
  *out_len = (int)len;
  *out_type = (int)dt;
  return 0;
}

// ------------------------------------------------------------------ booster

LGBM_EXPORT int LGBM_BoosterCreate(const DatasetHandle train_data,
                                   const char* parameters,
                                   BoosterHandle* out) {
  PyObject* r = call_support("booster_create", "(Ls)",
                             from_handle(train_data),
                             parameters ? parameters : "");
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCreateFromModelfile(const char* filename,
                                                int* out_num_iterations,
                                                BoosterHandle* out) {
  PyObject* r = call_support("booster_create_from_modelfile", "(s)", filename);
  if (!r) return -1;
  long long h, iters;
  bool ok = as_int2(r, &h, &iters);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  *out_num_iterations = (int)iters;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterLoadModelFromString(const char* model_str,
                                                int* out_num_iterations,
                                                BoosterHandle* out) {
  PyObject* r = call_support("booster_load_from_string", "(s)", model_str);
  if (!r) return -1;
  long long h, iters;
  bool ok = as_int2(r, &h, &iters);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  *out_num_iterations = (int)iters;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterFree(BoosterHandle handle) {
  PyObject* r = call_support("free_handle", "(L)", from_handle(handle));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterAddValidData(BoosterHandle handle,
                                         const DatasetHandle valid_data) {
  PyObject* r = call_support("booster_add_valid", "(LL)",
                             from_handle(handle), from_handle(valid_data));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumClasses(BoosterHandle handle,
                                          int* out_len) {
  PyObject* r =
      call_support("booster_num_classes", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                          int* is_finished) {
  PyObject* r = call_support("booster_update", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *is_finished = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                                const float* grad,
                                                const float* hess,
                                                int* is_finished) {
  PyObject* r = call_support("booster_update_custom", "(LLL)",
                             from_handle(handle), (long long)(intptr_t)grad,
                             (long long)(intptr_t)hess);
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *is_finished = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  PyObject* r = call_support("booster_rollback", "(L)", from_handle(handle));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                                int* out_iteration) {
  PyObject* r =
      call_support("booster_current_iteration", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_iteration = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle,
                                               int* out_models) {
  PyObject* r =
      call_support("booster_num_total_model", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_models = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  PyObject* r =
      call_support("booster_num_feature", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetEvalCounts(BoosterHandle handle,
                                          int* out_len) {
  PyObject* r =
      call_support("booster_eval_counts", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetEvalNames(BoosterHandle handle, int* out_len,
                                         char** out_strs) {
  PyObject* r =
      call_support("booster_get_eval_names", "(L)", from_handle(handle));
  if (!r) return -1;
  bool ok;
  std::string joined = as_str(r, &ok);
  drop(r);
  if (!ok) return -1;
  int n = 0;
  size_t start = 0;
  while (start <= joined.size() && !joined.empty()) {
    size_t end = joined.find('\n', start);
    std::string item = joined.substr(
        start, end == std::string::npos ? std::string::npos : end - start);
    std::strcpy(out_strs[n++], item.c_str());
    if (end == std::string::npos) break;
    start = end + 1;
  }
  *out_len = n;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx,
                                    int* out_len, double* out_results) {
  PyObject* r = call_support("booster_get_eval", "(LiL)",
                             from_handle(handle), data_idx,
                             (long long)(intptr_t)out_results);
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                                           int predict_type,
                                           int num_iteration,
                                           int64_t* out_len) {
  PyObject* r = call_support("booster_calc_num_predict", "(Liii)",
                             from_handle(handle), num_row, predict_type,
                             num_iteration);
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForMat(BoosterHandle handle,
                                          const void* data, int data_type,
                                          int32_t nrow, int32_t ncol,
                                          int is_row_major, int predict_type,
                                          int num_iteration,
                                          const char* parameter,
                                          int64_t* out_len,
                                          double* out_result) {
  PyObject* r = call_support(
      "booster_predict_for_mat", "(LLiiiiiisL)", from_handle(handle),
      (long long)(intptr_t)data, data_type, (int)nrow, (int)ncol,
      is_row_major, predict_type, num_iteration, parameter ? parameter : "",
      (long long)(intptr_t)out_result);
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                                      const char* filename) {
  PyObject* r = call_support("booster_save_model", "(Lis)",
                             from_handle(handle), num_iteration, filename);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                              int num_iteration,
                                              int64_t buffer_len,
                                              int64_t* out_len,
                                              char* out_str) {
  PyObject* r = call_support("booster_save_to_string", "(Li)",
                             from_handle(handle), num_iteration);
  if (!r) return -1;
  bool ok;
  std::string s = as_str(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = (int64_t)s.size() + 1;
  if ((int64_t)s.size() + 1 <= buffer_len && out_str) {
    std::memcpy(out_str, s.c_str(), s.size() + 1);
  }
  return 0;
}

LGBM_EXPORT int LGBM_BoosterDumpModel(BoosterHandle handle, int num_iteration,
                                      int64_t buffer_len, int64_t* out_len,
                                      char* out_str) {
  PyObject* r = call_support("booster_dump_model", "(Li)",
                             from_handle(handle), num_iteration);
  if (!r) return -1;
  bool ok;
  std::string s = as_str(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = (int64_t)s.size() + 1;
  if ((int64_t)s.size() + 1 <= buffer_len && out_str) {
    std::memcpy(out_str, s.c_str(), s.size() + 1);
  }
  return 0;
}

LGBM_EXPORT int LGBM_BoosterFeatureImportance(BoosterHandle handle,
                                              int num_iteration,
                                              int importance_type,
                                              double* out_results) {
  PyObject* r = call_support("booster_feature_importance", "(LiiL)",
                             from_handle(handle), num_iteration,
                             importance_type,
                             (long long)(intptr_t)out_results);
  if (!r) return -1;
  drop(r);
  return 0;
}

// ------------------------------------------------------------------ network

LGBM_EXPORT int LGBM_NetworkInit(const char* machines, int local_listen_port,
                                 int listen_time_out, int num_machines) {
  PyObject* r = call_support("network_init", "(siii)",
                             machines ? machines : "", local_listen_port,
                             listen_time_out, num_machines);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_NetworkFree() {
  PyObject* r = call_support("network_free", "()");
  if (!r) return -1;
  drop(r);
  return 0;
}

// External collective injection (reference c_api.h:1018
// LGBM_NetworkInitWithFunctions).  The reference swaps its socket
// reduce-scatter/allgather for caller-supplied function pointers; here the
// collectives are XLA programs compiled against a mesh, so injected host
// function pointers cannot participate in the compiled path.  Accept a
// single-machine no-op (rank 0 / num_machines 1) for wrapper compatibility
// and reject real multi-machine injection loudly.
LGBM_EXPORT int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                              void* reduce_scatter_ext_fun,
                                              void* allgather_ext_fun) {
  (void)reduce_scatter_ext_fun;
  (void)allgather_ext_fun;
  if (num_machines <= 1) return 0;
  set_error(
      "LGBM_NetworkInitWithFunctions: host-side collective injection is "
      "incompatible with compiled XLA collectives; configure a device mesh "
      "(num_machines/machines) instead");
  return -1;
}

// ---- round-3 API breadth: booster mutation / file predict / dataset
// subset & names (reference c_api.h:286-470,644-720,905-960) ----

LGBM_EXPORT int LGBM_BoosterResetParameter(BoosterHandle handle,
                                           const char* parameters) {
  PyObject* r = call_support("booster_reset_parameter", "(Ls)",
                             from_handle(handle), parameters);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterMerge(BoosterHandle handle,
                                  BoosterHandle other_handle) {
  PyObject* r = call_support("booster_merge", "(LL)", from_handle(handle),
                             from_handle(other_handle));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterShuffleModels(BoosterHandle handle,
                                          int start_iter, int end_iter) {
  PyObject* r = call_support("booster_shuffle_models", "(Lii)",
                             from_handle(handle), start_iter, end_iter);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                                         int leaf_idx, double* out_val) {
  PyObject* r = call_support("booster_get_leaf_value", "(Lii)",
                             from_handle(handle), tree_idx, leaf_idx);
  if (!r) return -1;
  bool ok;
  double v = as_double(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_val = v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                                         int leaf_idx, double val) {
  PyObject* r = call_support("booster_set_leaf_value", "(Liid)",
                             from_handle(handle), tree_idx, leaf_idx, val);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForFile(BoosterHandle handle,
                                           const char* data_filename,
                                           int data_has_header,
                                           int predict_type,
                                           int num_iteration,
                                           const char* parameter,
                                           const char* result_filename) {
  PyObject* r = call_support("booster_predict_for_file", "(Lsiiiss)",
                             from_handle(handle), data_filename,
                             data_has_header, predict_type, num_iteration,
                             parameter, result_filename);
  if (!r) return -1;
  drop(r);
  return 0;
}


namespace {
// Tab-joined python string -> caller's preallocated name buffers.
// Contract is reference-v2.3.2-identical (c_api.h:303): the CALLER
// provides at least num-names pointers, each wide enough for its name —
// the ABI carries no capacity information to check against.
int split_names_result(PyObject* r, char** names, int* num_names) {
  PyGILState_STATE st = PyGILState_Ensure();
  const char* joined = PyUnicode_AsUTF8(r);
  std::string copy = joined ? joined : "";
  bool ok = joined != nullptr;
  if (!ok) {
    set_error(py_error_string());
    PyErr_Clear();
  }
  PyGILState_Release(st);
  drop(r);
  if (!ok) return -1;
  if (copy.empty()) {  // no names known: report zero, write nothing
    *num_names = 0;
    return 0;
  }
  int count = 0;
  const char* start = copy.c_str();
  while (true) {
    const char* tab = std::strchr(start, '\t');
    size_t len = tab ? static_cast<size_t>(tab - start) : std::strlen(start);
    if (names && names[count]) {
      std::memcpy(names[count], start, len);
      names[count][len] = '\0';
    }
    ++count;
    if (!tab) break;
    start = tab + 1;
  }
  *num_names = count;
  return 0;
}
}  // namespace

LGBM_EXPORT int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                            const char** feature_names,
                                            int num_feature_names) {
  std::string joined;
  for (int i = 0; i < num_feature_names; ++i) {
    if (i) joined += "\t";
    joined += feature_names[i];
  }
  PyObject* r = call_support("dataset_set_feature_names", "(Ls)",
                             from_handle(handle), joined.c_str());
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetGetFeatureNames(DatasetHandle handle,
                                            char** feature_names,
                                            int* num_feature_names) {
  PyObject* r = call_support("dataset_get_feature_names", "(L)",
                             from_handle(handle));
  if (!r) return -1;
  return split_names_result(r, feature_names, num_feature_names);
}

LGBM_EXPORT int LGBM_DatasetGetSubset(DatasetHandle handle,
                                      const int32_t* used_row_indices,
                                      int32_t num_used_row_indices,
                                      const char* parameters,
                                      DatasetHandle* out) {
  PyObject* r = call_support("dataset_get_subset", "(LLis)",
                             from_handle(handle),
                             reinterpret_cast<long long>(used_row_indices),
                             num_used_row_indices, parameters);
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_SetLastError(const char* msg) {
  set_error(msg ? msg : "");
  return 0;
}

LGBM_EXPORT int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                                 int* out_tree_per_iter) {
  PyObject* r = call_support("booster_num_model_per_iteration", "(L)",
                             from_handle(handle));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_tree_per_iter = (int)v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetFeatureNames(BoosterHandle handle,
                                            int* out_len,
                                            char** out_strs) {
  // NOTE the reference v2.3.2 argument order differs from the Dataset
  // variant: (handle, int* out_len, char** out_strs) — c_api.h:573
  PyObject* r = call_support("booster_get_feature_names", "(L)",
                             from_handle(handle));
  if (!r) return -1;
  return split_names_result(r, out_strs, out_len);
}

LGBM_EXPORT int LGBM_BoosterPredictForMatSingleRow(
    BoosterHandle handle, const void* data, int data_type, int ncol,
    int is_row_major, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                   is_row_major, predict_type,
                                   num_iteration, parameter, out_len,
                                   out_result);
}

LGBM_EXPORT int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  PyObject* r = call_support(
      "booster_predict_for_csr", "(LLiLLiLLLiisL)", from_handle(handle),
      reinterpret_cast<long long>(indptr), indptr_type,
      reinterpret_cast<long long>(indices),
      reinterpret_cast<long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type, num_iteration,
      parameter, reinterpret_cast<long long>(out_result));
  if (!r) return -1;
  bool ok;
  long long n = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = n;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromMats(
    int32_t nmat, const void** data, int data_type, int32_t* nrow,
    int32_t ncol, int is_row_major, const char* parameters,
    DatasetHandle reference, DatasetHandle* out) {
  PyObject* r = call_support(
      "dataset_create_from_mats", "(LiLiiisL)",
      reinterpret_cast<long long>(data), data_type,
      reinterpret_cast<long long>(nrow), (int)nmat, (int)ncol,
      is_row_major, parameters, from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                                          int64_t* out_len) {
  PyObject* r = call_support("booster_get_num_predict", "(Li)",
                             from_handle(handle), data_idx);
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = v;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                                       int64_t* out_len, double* out_result) {
  PyObject* r = call_support("booster_get_predict", "(LiL)",
                             from_handle(handle), data_idx,
                             reinterpret_cast<long long>(out_result));
  if (!r) return -1;
  bool ok;
  long long v = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = v;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetUpdateParam(DatasetHandle handle,
                                        const char* parameters) {
  PyObject* r = call_support("dataset_update_param", "(Ls)",
                             from_handle(handle), parameters);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateByReference(DatasetHandle reference,
                                              int64_t num_total_row,
                                              DatasetHandle* out) {
  PyObject* r = call_support("dataset_create_by_reference", "(LL)",
                             from_handle(reference),
                             static_cast<long long>(num_total_row));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetPushRows(DatasetHandle handle, const void* data,
                                     int data_type, int32_t nrow,
                                     int32_t ncol, int32_t start_row) {
  PyObject* r = call_support("dataset_push_rows", "(LLiiii)",
                             from_handle(handle),
                             reinterpret_cast<long long>(data), data_type,
                             (int)nrow, (int)ncol, (int)start_row);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetDumpText(DatasetHandle handle,
                                     const char* filename) {
  PyObject* r = call_support("dataset_dump_text", "(Ls)",
                             from_handle(handle), filename);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSRSingleRow(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                   data, data_type, nindptr, nelem,
                                   num_col, predict_type, num_iteration,
                                   parameter, out_len, out_result);
}

LGBM_EXPORT int LGBM_DatasetCreateFromCSC(
    const void* col_ptr, int col_ptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t ncol_ptr, int64_t nelem,
    int64_t num_row, const char* parameters, DatasetHandle reference,
    DatasetHandle* out) {
  PyObject* r = call_support(
      "dataset_create_from_csc", "(LiLLiLLLsL)",
      reinterpret_cast<long long>(col_ptr), col_ptr_type,
      reinterpret_cast<long long>(indices),
      reinterpret_cast<long long>(data), data_type,
      static_cast<long long>(ncol_ptr), static_cast<long long>(nelem),
      static_cast<long long>(num_row), parameters, from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSC(
    BoosterHandle handle, const void* col_ptr, int col_ptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t ncol_ptr, int64_t nelem, int64_t num_row, int predict_type,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  PyObject* r = call_support(
      "booster_predict_for_csc", "(LLiLLiLLLiisL)", from_handle(handle),
      reinterpret_cast<long long>(col_ptr), col_ptr_type,
      reinterpret_cast<long long>(indices),
      reinterpret_cast<long long>(data), data_type,
      static_cast<long long>(ncol_ptr), static_cast<long long>(nelem),
      static_cast<long long>(num_row), predict_type, num_iteration,
      parameter, reinterpret_cast<long long>(out_result));
  if (!r) return -1;
  bool ok;
  long long n = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = n;
  return 0;
}

LGBM_EXPORT int LGBM_DatasetAddFeaturesFrom(DatasetHandle target,
                                            DatasetHandle source) {
  PyObject* r = call_support("dataset_add_features_from", "(LL)",
                             from_handle(target), from_handle(source));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                              DatasetHandle train_data) {
  PyObject* r = call_support("booster_reset_training_data", "(LL)",
                             from_handle(handle), from_handle(train_data));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForMats(
    BoosterHandle handle, const void** data, int data_type, int32_t nrow,
    int32_t* nrows_per_mat, int32_t nmat, int32_t ncol, int predict_type,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  int64_t total = 0;
  for (int32_t i = 0; i < nmat; ++i) total += nrows_per_mat[i];
  if (total != nrow) {
    set_error("sum of nrows_per_mat does not match nrow");
    return -1;
  }
  PyObject* r = call_support(
      "booster_predict_for_mats", "(LLiLiiiisL)", from_handle(handle),
      reinterpret_cast<long long>(data), data_type,
      reinterpret_cast<long long>(nrows_per_mat), (int)nmat, (int)ncol,
      predict_type, num_iteration, parameter,
      reinterpret_cast<long long>(out_result));
  if (!r) return -1;
  bool ok;
  long long n = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out_len = n;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterRefit(BoosterHandle handle,
                                  const int32_t* leaf_preds, int32_t nrow,
                                  int32_t ncol) {
  PyObject* r = call_support("booster_refit", "(LLii)", from_handle(handle),
                             reinterpret_cast<long long>(leaf_preds),
                             (int)nrow, (int)ncol);
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetPushRowsByCSR(
    DatasetHandle dataset, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int64_t start_row) {
  PyObject* r = call_support(
      "dataset_push_rows_by_csr", "(LLiLLiLLLL)", from_handle(dataset),
      reinterpret_cast<long long>(indptr), indptr_type,
      reinterpret_cast<long long>(indices),
      reinterpret_cast<long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), static_cast<long long>(start_row));
  if (!r) return -1;
  drop(r);
  return 0;
}

LGBM_EXPORT int LGBM_DatasetCreateFromSampledColumn(
    double** sample_data, int** sample_indices, int32_t ncol,
    const int* num_per_col, int32_t num_sample_row, int32_t num_total_row,
    const char* parameters, DatasetHandle* out) {
  PyObject* r = call_support(
      "dataset_create_from_sampled_column", "(LLiLiis)",
      reinterpret_cast<long long>(sample_data),
      reinterpret_cast<long long>(sample_indices), (int)ncol,
      reinterpret_cast<long long>(num_per_col), (int)num_sample_row,
      (int)num_total_row, parameters);
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}

// The reference's CSRFunc contract passes a pointer to a C++
// std::function<void(int idx, std::vector<std::pair<int, double>>&)>
// (reference src/c_api.cpp:768) — a C++-ABI-only entry used by the SWIG
// wrapper.  Drive the callback row by row into a CSR buffer, then share
// the CSR creation path.
#include <functional>
#include <utility>
#include <vector>

LGBM_EXPORT int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr,
                                              int num_rows, int64_t num_col,
                                              const char* parameters,
                                              const DatasetHandle reference,
                                              DatasetHandle* out) {
  if (num_col <= 0) {
    set_error("the number of columns should be greater than zero");
    return -1;
  }
  auto& get_row = *static_cast<
      std::function<void(int, std::vector<std::pair<int, double>>&)>*>(
      get_row_funptr);
  std::vector<int32_t> indptr{0};
  std::vector<int32_t> indices;
  std::vector<double> values;
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    get_row(i, row);
    for (const auto& kv : row) {
      indices.push_back(static_cast<int32_t>(kv.first));
      values.push_back(kv.second);
    }
    indptr.push_back(static_cast<int32_t>(indices.size()));
  }
  // numpy rejects NULL even for zero-length views: keep the pointers
  // non-null when the callback produced no pairs at all
  static int32_t dummy_idx = 0;
  static double dummy_val = 0.0;
  const int32_t* idx_p = indices.empty() ? &dummy_idx : indices.data();
  const double* val_p = values.empty() ? &dummy_val : values.data();
  PyObject* r = call_support(
      "dataset_create_from_csr", "(LiLLiLLLsL)",
      reinterpret_cast<long long>(indptr.data()), 2 /*int32*/,
      reinterpret_cast<long long>(idx_p),
      reinterpret_cast<long long>(val_p), 1 /*float64*/,
      static_cast<long long>(indptr.size()),
      static_cast<long long>(indices.size()),
      static_cast<long long>(num_col), parameters, from_handle(reference));
  if (!r) return -1;
  bool ok;
  long long h = as_int(r, &ok);
  drop(r);
  if (!ok) return -1;
  *out = to_handle(h);
  return 0;
}
