#!/bin/sh
# Build lib_lightgbm_tpu.so — the native LGBM_* C ABI shim.
# Usage: src/capi/build.sh [outdir]   (default: repo root)
set -e
HERE="$(cd "$(dirname "$0")" && pwd)"
ROOT="$(cd "$HERE/../.." && pwd)"
OUT="${1:-$ROOT}"
PYINC="$(python3 -c 'import sysconfig; print(sysconfig.get_paths()["include"])')"
PYLIBDIR="$(python3 -c 'import sysconfig; print(sysconfig.get_config_var("LIBDIR"))')"
PYLIB="$(python3 -c 'import sysconfig; v=sysconfig.get_config_var("LDVERSION"); print("python"+v)')"
g++ -O3 -fPIC -shared -std=c++17 -fopenmp \
    -I"$PYINC" \
    "$HERE/lightgbm_tpu_c_api.cpp" "$HERE/forest_predictor.cpp" \
    -L"$PYLIBDIR" -l"$PYLIB" \
    -o "$OUT/lib_lightgbm_tpu.so"
echo "built $OUT/lib_lightgbm_tpu.so"
