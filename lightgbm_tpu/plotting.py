"""Plotting helpers (role of reference python-package/lightgbm/
plotting.py:29-473): feature importance, metric curves, split-value
histograms, and tree diagrams.

matplotlib is imported lazily; tree diagrams additionally need graphviz
and raise a clear ImportError without it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster


def _plt():
    try:
        import matplotlib.pyplot as plt
    except ImportError as exc:  # pragma: no cover - env without matplotlib
        raise ImportError("plotting requires matplotlib") from exc
    return plt


def _to_booster(model) -> Booster:
    if isinstance(model, Booster):
        return model
    sk_booster = getattr(model, "booster_", None)
    if sk_booster is not None:
        return sk_booster
    raise TypeError("expected a Booster or fitted sklearn wrapper")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal bar chart of per-feature importance."""
    plt = _plt()
    bst = _to_booster(booster)
    importance = np.asarray(bst.feature_importance(importance_type))
    names = bst.feature_name()
    pairs = sorted(zip(names, importance), key=lambda kv: kv[1])
    if ignore_zero:
        pairs = [p for p in pairs if p[1] != 0]
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    if not pairs:
        raise ValueError("no importance to plot")
    labels, values = zip(*pairs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ypos = np.arange(len(values))
    ax.barh(ypos, values, height=height, align="center", **kwargs)
    for y, v in zip(ypos, values):
        ax.text(v + 1e-9, y,
                f"{v:.{precision}f}" if importance_type == "gain"
                else str(int(v)),
                va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_record, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                figsize=None, dpi=None, grid: bool = True):
    """Metric curves from an evals_result dict (or a Booster trained with
    record_evaluation)."""
    plt = _plt()
    if isinstance(booster_or_record, dict):
        record = booster_or_record
    else:
        record = getattr(booster_or_record, "evals_result", None)
        if not record:
            raise ValueError(
                "pass the evals_result dict from train(..., evals_result=)")
    if not record:
        raise ValueError("empty evaluation record")
    names = dataset_names or list(record.keys())
    first = record[names[0]]
    if metric is None:
        metric = next(iter(first.keys()))
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    for name in names:
        series = record.get(name, {}).get(metric)
        if series is None:
            continue
        ax.plot(np.arange(1, len(series) + 1), series, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature: Union[int, str], bins=None,
                               ax=None, width_coef: float = 0.8, xlim=None,
                               ylim=None,
                               title: str = "Split value histogram for "
                                            "feature with @index/name@ "
                                            "@feature@",
                               xlabel: str = "Feature split value",
                               ylabel: str = "Count", figsize=None, dpi=None,
                               grid: bool = True):
    """Histogram of the model's split thresholds on one feature."""
    plt = _plt()
    bst = _to_booster(booster)
    if isinstance(feature, str):
        feature = bst.feature_name().index(feature)
    values = []
    for tree in bst._driver.models:
        ni = tree.num_leaves - 1
        for j in range(ni):
            if (int(tree.split_feature[j]) == feature
                    and not (int(tree.decision_type[j]) & 1)):
                values.append(float(tree.threshold[j]))
    if not values:
        raise ValueError(
            f"feature {feature} is not used in any numerical split")
    counts, edges = np.histogram(values, bins=bins or "auto")
    centers = (edges[:-1] + edges[1:]) / 2
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centers, counts, width=width_coef * (edges[1] - edges[0]))
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title.replace("@index/name@", "index")
                 .replace("@feature@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _node_label(node: Dict[str, Any], feature_names: List[str],
                precision: int) -> str:
    if "split_feature" in node:
        f = node["split_feature"]
        name = (feature_names[f] if f < len(feature_names)
                else f"Column_{f}")
        op = "==" if node.get("decision_type") == "==" else "<="
        thr = node["threshold"]
        if not isinstance(thr, str):  # categorical dumps "c1||c2||..."
            thr = round(thr, precision)
        return (f"{name} {op} {thr}\n"
                f"gain: {round(node.get('split_gain', 0.0), precision)}\n"
                f"count: {node.get('internal_count', 0)}")
    return (f"leaf {node.get('leaf_index', 0)}: "
            f"{round(node.get('leaf_value', 0.0), precision)}\n"
            f"count: {node.get('leaf_count', 0)}")


def create_tree_digraph(booster, tree_index: int = 0, precision: int = 3,
                        **kwargs):
    """graphviz Digraph of one tree (reference create_tree_digraph)."""
    try:
        import graphviz
    except ImportError as exc:
        raise ImportError("create_tree_digraph requires the graphviz "
                          "package") from exc
    bst = _to_booster(booster)
    dump = bst.dump_model()
    if tree_index >= len(dump["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range")
    tree = dump["tree_info"][tree_index]["tree_structure"]
    names = dump.get("feature_names", bst.feature_name())
    g = graphviz.Digraph(**kwargs)
    counter = [0]

    def walk(node) -> str:
        nid = f"n{counter[0]}"
        counter[0] += 1
        g.node(nid, _node_label(node, names, precision),
               shape="rectangle" if "split_feature" in node else "ellipse")
        if "split_feature" in node:
            left = walk(node["left_child"])
            right = walk(node["right_child"])
            g.edge(nid, left, label="yes")
            g.edge(nid, right, label="no")
        return nid

    walk(tree)
    return g


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              precision: int = 3, **kwargs):
    """Render one tree into a matplotlib axes (via graphviz)."""
    plt = _plt()
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                precision=precision, **kwargs)
    import io as _io

    try:
        image = graph.pipe(format="png")
    except Exception as exc:  # graphviz binary missing
        raise RuntimeError("graphviz executables are required to render "
                           "trees") from exc
    import matplotlib.image as mpimg

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(mpimg.imread(_io.BytesIO(image)))
    ax.axis("off")
    return ax
