"""Training entry points: train() and cv() (reference engine.py:18,373)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Dataset
from .booster import Booster
from .callback import CallbackEnv, EarlyStopException, early_stopping, log_evaluation

# iteration-count aliases already warned about this process: repeated
# train() calls with the same alias (sweeps, CV loops, MULTICHIP runs)
# warn once, not once per call
_warned_num_iter_aliases: set = set()


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: Union[str, List[str]] = "auto",
          categorical_feature: Union[str, List] = "auto",
          learning_rates=None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          early_stopping_rounds: Optional[int] = None,
          verbose_eval: Union[bool, int] = True,
          evals_result: Optional[Dict] = None,
          resume: bool = False) -> Booster:
    params = copy.deepcopy(params)
    if feature_name != "auto":
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto":
        train_set.set_categorical_feature(categorical_feature)
    if fobj is not None:
        params["objective"] = "none"
    for alias in ("num_boost_round", "num_iterations", "num_iteration", "n_iter",
                  "num_tree", "num_trees", "num_round", "num_rounds",
                  "n_estimators"):
        if alias in params:
            # params win over the argument, but never silently
            # (reference engine.py:148 warns identically) — deduped per
            # alias per process so retrain loops don't spam the log
            if alias not in _warned_num_iter_aliases:
                import warnings

                warnings.warn(f"Found `{alias}` in params. Will use it "
                              "instead of argument")
                _warned_num_iter_aliases.add(alias)
            num_boost_round = int(params.pop(alias))
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping", "n_iter_no_change"):
        if alias in params and params[alias] is not None:
            early_stopping_rounds = int(params.pop(alias))

    first_metric_only = bool(params.get("first_metric_only", False))

    if init_model is not None:
        if isinstance(init_model, str):
            init_booster = Booster(model_file=init_model)
        else:
            init_booster = init_model
        init_model_str = init_booster.model_to_string()
    else:
        init_model_str = None

    booster = Booster(params=params, train_set=train_set)
    if init_model_str is not None:
        booster._driver.merge_from_model_string(init_model_str)
    booster.set_train_data_name(params.get("train_data_name", "training"))

    valid_sets = valid_sets or []
    if valid_names is None:
        valid_names = [f"valid_{i}" for i in range(len(valid_sets))]
    is_valid_contain_train = False
    train_data_name = "training"
    for vs, name in zip(valid_sets, valid_names):
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = name
            continue
        booster.add_valid(vs, name)

    callbacks = list(callbacks) if callbacks else []
    if learning_rates is not None:
        # per-iteration learning-rate schedule (reference engine.py:
        # learning_rates -> callback.reset_parameter)
        if not isinstance(learning_rates, list) \
                and not callable(learning_rates):
            raise ValueError(
                "learning_rates must be a list or a callable")
        from .callback import reset_parameter

        callbacks.append(reset_parameter(learning_rate=learning_rates))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(early_stopping(early_stopping_rounds, first_metric_only,
                                        verbose=bool(verbose_eval)))
    if verbose_eval is True:
        callbacks.append(log_evaluation(1))
    elif isinstance(verbose_eval, int) and verbose_eval >= 1:
        callbacks.append(log_evaluation(verbose_eval))
    if evals_result is not None:
        from .callback import record_evaluation
        callbacks.append(record_evaluation(evals_result))

    # fault tolerance: atomic interval checkpoints (tpu_checkpoint_dir)
    # plus resume=True restart from the newest VALID bundle (torn/
    # corrupt checkpoints are skipped with a warning).  The checkpoint
    # callback is appended unless the caller supplied their own.
    ckpt_dir = str(params.get("tpu_checkpoint_dir", "") or "")
    ckpt_manager = None
    from .callback import _Checkpoint

    ckpt_cb = next((cb for cb in callbacks if isinstance(cb, _Checkpoint)),
                   None)
    if ckpt_cb is None and ckpt_dir:
        ckpt_cb = _Checkpoint(
            ckpt_dir,
            interval=int(params.get("tpu_checkpoint_interval", 1) or 1),
            keep=int(params.get("tpu_checkpoint_keep", 3) or 3))
        callbacks.append(ckpt_cb)
    if ckpt_cb is not None:
        ckpt_manager = ckpt_cb.manager
        ckpt_cb.peers = [cb for cb in callbacks if cb is not ckpt_cb]
    if resume and ckpt_manager is None:
        raise ValueError("resume=True needs tpu_checkpoint_dir (or an "
                         "explicit checkpoint callback)")

    cb_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    cb_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]
    cb_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cb_after.sort(key=lambda cb: getattr(cb, "order", 0))

    start_iteration = 0
    if resume:
        from .utils.checkpoint import restore_checkpoint

        restored = restore_checkpoint(booster, ckpt_manager,
                                      callbacks=callbacks)
        if restored is not None:
            # the stored iteration counts init_model trees too; the loop
            # below counts only NEW rounds (restore_checkpoint already
            # set best_iteration)
            start_iteration = (int(restored["iteration"])
                               - int(restored.get("num_init_iteration", 0)))

    # training snapshots (reference GBDT::Train, gbdt.cpp:290-294: every
    # snapshot_freq iterations the model is saved as <out>.snapshot_iter_N)
    snapshot_freq = int(params.get("snapshot_freq", -1) or -1)
    snapshot_out = str(params.get("output_model", "LightGBM_model.txt"))

    # graceful preemption: with checkpointing configured, SIGTERM (the
    # TPU-preemption signal) becomes a KeyboardInterrupt so the atomic-
    # iteration rollback + final checkpoint flush below run before exit
    import threading as _threading

    prev_sigterm = None
    if ckpt_manager is not None and \
            _threading.current_thread() is _threading.main_thread():
        import signal as _signal

        def _on_sigterm(signum, frame):
            raise KeyboardInterrupt("SIGTERM")

        try:
            prev_sigterm = _signal.signal(_signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):  # pragma: no cover - exotic host
            prev_sigterm = None

    from . import obs

    def _dump_trace() -> None:
        # Chrome-trace dump + JSONL flush at end of train (success,
        # early stop, or interrupt alike — the trace of a FAILED run is
        # the one worth reading).  Runs AFTER the final checkpoint
        # flush on every path so the checkpoint's own spans/events make
        # the dump; no-op without tpu_trace_dir
        if obs.tracing_on():
            obs.write_chrome_trace()
            obs.flush()

    evaluation_result_list: List = []
    try:
        for i in range(start_iteration, num_boost_round):
            # the per-round telemetry span covers callbacks + update +
            # eval — under tpu_telemetry=trace the summed round spans
            # account for >= 95% of the train-loop wall (asserted by
            # tests/test_telemetry.py); obs.span is a shared null
            # context manager when tracing is off
            # one always-on flight-recorder entry per round, recorded
            # at round START in every mode: the blackbox of a dying
            # run names the round it died IN (the trace span mirror
            # only lands at span exit, which a mid-round death never
            # reaches)
            obs.flightrecorder.note("round", "train/round", iteration=i)
            with obs.span("train/round", iteration=i):
                for cb in cb_before:
                    cb(CallbackEnv(model=booster, params=params, iteration=i,
                                   begin_iteration=0,
                                   end_iteration=num_boost_round,
                                   evaluation_result_list=None))
                booster.update(fobj=fobj)
                if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
                    booster.save_model(
                        f"{snapshot_out}.snapshot_iter_{i + 1}")

                evaluation_result_list: List = []
                if valid_sets:
                    if is_valid_contain_train:
                        evaluation_result_list.extend(
                            booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
                    if obs.metrics_on():
                        # train/valid metric TIME SERIES (ISSUE 14): the
                        # registry's bounded sample ring keeps the
                        # per-iteration values in order — model_report
                        # reads its learning curves back from here
                        for item in evaluation_result_list:
                            obs.REGISTRY.observe(
                                "lgbm_train_metric", float(item[2]),
                                help="per-iteration train/valid metric "
                                     "values (ring = learning curve)",
                                dataset=str(item[0]),
                                metric=str(item[1]))
                try:
                    for cb in cb_after:
                        cb(CallbackEnv(
                            model=booster, params=params, iteration=i,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=evaluation_result_list))
                except EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    evaluation_result_list = e.best_score
                    break
    except BaseException as exc:
        # interrupt/device failure: the partial iteration was already
        # rolled back inside update(); flush a final checkpoint so the
        # run restarts from the last COMPLETE iteration, then re-raise
        if ckpt_manager is not None:
            from .parallel.collective import CollectiveTimeout
            from .utils.checkpoint import flush_checkpoint
            from .utils.log import Log

            if isinstance(exc, CollectiveTimeout):
                # a hung peer, not a local fault: tell the operator the
                # run degraded by design — the flushed checkpoint is the
                # rejoin point once the group is rebuilt
                Log.warning(
                    f"collective {exc.name!r} timed out "
                    f"({exc.timeout_s:g}s) at iteration "
                    f"{booster.current_iteration()}: rolled back to the "
                    "last complete iteration, flushing a final "
                    "checkpoint; restart the group and resume=True to "
                    "rejoin (elastic: any shard/host count)")
            flush_checkpoint(booster, ckpt_manager, callbacks=callbacks)
        # blackbox AFTER the checkpoint flush: the dump's metric
        # snapshot then carries the flush's own counters, proving to
        # the postmortem reader that the checkpoint landed before the
        # process died (SIGTERM rides this path as KeyboardInterrupt)
        obs.flightrecorder.note("crash", "train_interrupted",
                                type=type(exc).__name__,
                                iteration=booster.current_iteration())
        obs.flightrecorder.dump(f"train_interrupt:{type(exc).__name__}",
                                exc=exc)
        _dump_trace()
        raise
    finally:
        if prev_sigterm is not None:
            import signal as _signal

            _signal.signal(_signal.SIGTERM, prev_sigterm)
    if ckpt_manager is not None:
        # early stop (or a zero-round run) can end between interval
        # marks: one final bundle covers the completed state
        from .utils.checkpoint import flush_checkpoint

        flush_checkpoint(booster, ckpt_manager, callbacks=callbacks)
    _dump_trace()

    booster.best_score = {}
    for item in evaluation_result_list:
        booster.best_score.setdefault(item[0], {})[item[1]] = item[2]
    if booster.best_iteration < 0:
        booster.best_iteration = -1
    if not keep_training_booster:
        # reference engine.py: the returned booster becomes predict-only
        # (training data freed); pass keep_training_booster=True to keep
        # updating it.  free_dataset snapshots the bin mappers first, so
        # the returned booster keeps the device='tpu' predict path
        # (jitted bin-space forest traversal) without its training data.
        booster.free_dataset()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters returned by cv() (reference engine.py:296)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        # fan any Booster method out across the fold ensemble, collecting
        # one result per fold
        if name.startswith("_"):
            raise AttributeError(name)
        return _FoldFanout(self.boosters, name)


class _FoldFanout:
    """Callable that maps a Booster method over every cv fold."""

    def __init__(self, boosters: List[Booster], method: str):
        self._boosters = boosters
        self._method = method

    def __call__(self, *args: Any, **kwargs: Any) -> List[Any]:
        return [getattr(b, self._method)(*args, **kwargs)
                for b in self._boosters]


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    group = full_data.get_field("group")
    rng = np.random.default_rng(seed)
    if group is not None:
        # group-aware folds: split whole queries
        boundaries = group
        num_queries = len(boundaries) - 1
        q_idx = np.arange(num_queries)
        if shuffle:
            rng.shuffle(q_idx)
        folds = []
        flat_group = np.zeros(num_data, dtype=np.int64)
        for q in range(num_queries):
            flat_group[boundaries[q]:boundaries[q + 1]] = q
        for k in range(nfold):
            test_queries = set(q_idx[k::nfold].tolist())
            test_mask = np.isin(flat_group, list(test_queries))
            folds.append((np.where(~test_mask)[0], np.where(test_mask)[0]))
    elif stratified:
        label = full_data.get_field("label")
        folds = []
        idx_by_class: List[np.ndarray] = []
        for c in np.unique(label):
            ci = np.where(label == c)[0]
            if shuffle:
                rng.shuffle(ci)
            idx_by_class.append(ci)
        for k in range(nfold):
            test_idx = np.concatenate([ci[k::nfold] for ci in idx_by_class])
            mask = np.zeros(num_data, dtype=bool)
            mask[test_idx] = True
            folds.append((np.where(~mask)[0], np.where(mask)[0]))
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        folds = [(np.setdiff1d(idx, idx[k::nfold], assume_unique=False),
                  idx[k::nfold]) for k in range(nfold)]
    return folds


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       early_stopping_rounds: Optional[int] = None,
       verbose_eval: Union[bool, int, None] = None, show_stdv: bool = True,
       seed: int = 0, callbacks=None, return_cvbooster: bool = False) -> Dict:
    params = copy.deepcopy(params)
    if metrics is not None:
        params["metric"] = metrics
    for alias in ("num_boost_round", "num_iterations", "num_iteration", "n_iter",
                  "num_tree", "num_trees", "num_round", "num_rounds",
                  "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))

    if folds is None:
        stratified = stratified and str(params.get("objective", "")).startswith(
            ("binary", "multiclass"))
        folds = _make_n_folds(train_set, nfold, params, seed, stratified, shuffle)
    elif hasattr(folds, "split"):
        label = train_set.get_field("label")
        folds = list(folds.split(np.zeros(train_set.num_data()), label))

    cvbooster = CVBooster()
    raw_results: List[List] = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        te = train_set.subset(np.sort(test_idx))
        bst = Booster(params=params, train_set=tr)
        bst.add_valid(te, "valid")
        cvbooster._append(bst)

    results: Dict[str, List[float]] = {}
    for i in range(num_boost_round):
        all_evals: List[List] = []
        for bst in cvbooster.boosters:
            bst.update(fobj=fobj)
            all_evals.append(bst.eval_valid(feval))
        # aggregate across folds
        agg: Dict[str, List[float]] = {}
        higher: Dict[str, bool] = {}
        for evals in all_evals:
            for item in evals:
                key = f"{item[1]}"
                agg.setdefault(key, []).append(item[2])
                higher[key] = item[3]
        stop = False
        for key, vals in agg.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results.setdefault(f"{key}-mean", []).append(mean)
            results.setdefault(f"{key}-stdv", []).append(std)
        if verbose_eval:
            msgs = [f"{k}: {np.mean(v):g} + {np.std(v):g}" for k, v in agg.items()]
            print(f"[{i + 1}]\t" + "\t".join(msgs))
        if early_stopping_rounds and i >= early_stopping_rounds:
            for key, vals in agg.items():
                series = results[f"{key}-mean"]
                best = (np.argmax(series) if higher[key] else np.argmin(series))
                if i - best >= early_stopping_rounds:
                    cvbooster.best_iteration = int(best) + 1
                    stop = True
                break  # first metric decides
        if stop:
            for key in list(results):
                results[key] = results[key][:cvbooster.best_iteration]
            break

    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
