"""Atomic iteration-granular training checkpoints.

A checkpoint is one directory (``ckpt-<iteration>``) holding the full
restart bundle:

* ``model.txt``   — the model string with its ``tpu_bin_mappers:`` and
  ``pandas_categorical:`` trailers (the same bytes ``save_model`` would
  write), so trees rebind into bin space EXACTLY on restore;
* ``state.json``  — the driver's non-array training state: iteration
  counter, bagging/quantization PRNG key words, numpy bit-generator
  states, boost-from-average init scores + flags, early-stop callback
  snapshots, a params fingerprint;
* ``arrays.npz``  — the f32 score buffers (train + per-valid-set) and
  the cached bagging mask.  Restoring the scores byte-for-byte is what
  makes a resumed run produce the *bit-identical* model an
  uninterrupted run would have: replaying trees through the forest
  kernel would re-round the f32 accumulation in a different order.

Write protocol (torn-write safe on POSIX): every file lands in a
hidden temp directory first, each file is flushed + fsync'd, the
manifest (CRC32 + byte count per file) is written last, the temp
directory is atomically renamed into place, and the parent directory
is fsync'd.  A crash at ANY point leaves either a complete previous
checkpoint or an ignorable temp/corrupt directory — `load_latest`
walks newest-first and skips (with a warning) anything whose manifest
is missing, unparseable, or whose CRCs don't match.

Retention keeps the newest `keep` valid checkpoints.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import faultline
from .log import Log

MANIFEST = "manifest.json"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-ckpt-"
FORMAT_VERSION = 1


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # non-POSIX / exotic fs: rename is still atomic
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_file(path: str, data: bytes) -> None:
    """Write + fsync one payload file, honoring the `checkpoint_write`
    fault point: ``truncate`` writes half the bytes (a torn write the
    manifest CRC will catch), ``raise`` aborts mid-bundle."""
    action = faultline.fire("checkpoint_write", path=os.path.basename(path))
    if action == "truncate":
        data = data[:len(data) // 2]
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


class CheckpointManager:
    """Atomic write + validated read + keep-last-N retention over one
    checkpoint directory."""

    def __init__(self, directory: str, keep: int = 3):
        if not directory:
            raise ValueError("checkpoint directory must be non-empty")
        self.directory = str(directory)
        self.keep = max(int(keep), 1)
        os.makedirs(self.directory, exist_ok=True)

    # -- naming --------------------------------------------------------
    @staticmethod
    def _name(iteration: int) -> str:
        return f"{_PREFIX}{int(iteration):08d}"

    @staticmethod
    def _iteration_of(name: str) -> Optional[int]:
        if not name.startswith(_PREFIX):
            return None
        try:
            return int(name[len(_PREFIX):])
        except ValueError:
            return None

    def checkpoints(self) -> List[Tuple[int, str]]:
        """(iteration, path) of every checkpoint-named dir, newest first
        (validity NOT checked here)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            it = self._iteration_of(name)
            if it is not None:
                out.append((it, os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    def latest_iteration(self) -> Optional[int]:
        cks = self.checkpoints()
        return cks[0][0] if cks else None

    # -- write ---------------------------------------------------------
    def save(self, iteration: int, model_text: str, state: Dict,
             arrays: Dict[str, np.ndarray]) -> str:
        """Write one atomic checkpoint bundle; returns its path.
        Re-saving an iteration that already has a VALID checkpoint is a
        no-op (the flush-on-exit path may race a just-written interval
        checkpoint)."""
        final = os.path.join(self.directory, self._name(iteration))
        if os.path.isdir(final) and self.validate(final):
            return final
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{int(iteration):08d}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            import io as _io

            payloads: Dict[str, bytes] = {
                "model.txt": model_text.encode("utf-8"),
                "state.json": json.dumps(state, sort_keys=True).encode(),
            }
            buf = _io.BytesIO()
            np.savez(buf, **arrays)
            payloads["arrays.npz"] = buf.getvalue()
            manifest = {"format": FORMAT_VERSION, "iteration": int(iteration),
                        "files": {}}
            for name, data in payloads.items():
                # the manifest records the INTENDED bytes: an injected
                # (or real) torn write then fails CRC validation exactly
                # like a crash mid-write would
                manifest["files"][name] = {"crc32": zlib.crc32(data),
                                           "bytes": len(data)}
                _write_file(os.path.join(tmp, name), data)
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            if os.path.isdir(final):  # stale invalid leftover
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retain()
        return final

    def _retain(self) -> None:
        """Keep the newest `keep` checkpoints; drop older ones and any
        stale temp directories."""
        for it, path in self.checkpoints()[self.keep:]:
            shutil.rmtree(path, ignore_errors=True)
        try:
            for name in os.listdir(self.directory):
                if name.startswith(_TMP_PREFIX):
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
        except OSError:
            pass

    # -- read ----------------------------------------------------------
    def validate(self, path: str) -> bool:
        """Manifest present, parseable, and every listed file's CRC32 +
        size match."""
        try:
            with open(os.path.join(path, MANIFEST)) as f:
                manifest = json.load(f)
            files = manifest["files"]
            for name, meta in files.items():
                with open(os.path.join(path, name), "rb") as f:
                    data = f.read()
                if len(data) != int(meta["bytes"]) \
                        or zlib.crc32(data) != int(meta["crc32"]):
                    return False
            return {"model.txt", "state.json", "arrays.npz"} <= set(files)
        except (OSError, ValueError, KeyError, TypeError):
            return False

    def load_latest(self) -> Optional[Tuple[int, str, Dict, Dict, str]]:
        """Newest VALID checkpoint as (iteration, model_text, state,
        arrays, path); torn/corrupt checkpoints are skipped with a
        warning.  None when no valid checkpoint exists."""
        for it, path in self.checkpoints():
            if not self.validate(path):
                Log.warning(f"skipping corrupt/torn checkpoint {path} "
                            "(manifest missing or CRC mismatch)")
                continue
            try:
                with open(os.path.join(path, "model.txt"),
                          encoding="utf-8") as f:
                    model_text = f.read()
                with open(os.path.join(path, "state.json")) as f:
                    state = json.load(f)
                with np.load(os.path.join(path, "arrays.npz"),
                             allow_pickle=False) as z:
                    arrays = {k: z[k] for k in z.files}
            except (OSError, ValueError, KeyError) as exc:
                Log.warning(f"skipping unreadable checkpoint {path}: {exc}")
                continue
            return it, model_text, state, arrays, path
        return None


# ---------------------------------------------------------------------------
# Booster-level bundle assembly
# ---------------------------------------------------------------------------
def _params_fingerprint(params: Dict) -> int:
    """Stable fingerprint of the training params a bitwise resume
    depends on (everything: cheap, and any difference is suspect)."""
    try:
        text = json.dumps({str(k): str(v) for k, v in params.items()},
                          sort_keys=True)
    except (TypeError, ValueError):
        text = str(sorted(str(k) for k in params))
    return zlib.crc32(text.encode())


def _callback_states(callbacks) -> Dict:
    out = {}
    for cb in callbacks or []:
        key = getattr(cb, "state_key", None)
        snap = getattr(cb, "snapshot_state", None)
        if key and callable(snap):
            out[str(key)] = snap()
    return out


def save_checkpoint(booster, manager: CheckpointManager,
                    callbacks=None) -> str:
    """Write one atomic checkpoint of a live training booster."""
    state, arrays = booster._driver.capture_train_state()
    state["best_iteration"] = int(booster.best_iteration)
    state["params_fingerprint"] = _params_fingerprint(booster.params)
    cb_states = _callback_states(callbacks)
    if cb_states:
        state["callbacks"] = cb_states
    model_text = booster.model_to_string(num_iteration=-1)
    return manager.save(state["iteration"], model_text, state, arrays)


def restore_checkpoint(booster, manager: CheckpointManager,
                       callbacks=None) -> Optional[Dict]:
    """Restore a booster from the newest valid checkpoint; returns the
    restored state dict (with "iteration") or None when no valid
    checkpoint exists.  The booster must have been constructed with the
    SAME training dataset and params as the checkpointed run for the
    bitwise-resume guarantee to hold; a params fingerprint mismatch
    warns but proceeds."""
    found = manager.load_latest()
    if found is None:
        return None
    it, model_text, state, arrays, path = found
    fp = _params_fingerprint(booster.params)
    if state.get("params_fingerprint") not in (None, fp):
        Log.warning(
            f"resuming from {path} with different training params; the "
            "resumed model will NOT be bit-identical to an uninterrupted "
            "run")
    booster._driver.restore_train_state(model_text, state, arrays)
    booster.best_iteration = int(state.get("best_iteration", -1))
    for cb in callbacks or []:
        key = getattr(cb, "state_key", None)
        restore = getattr(cb, "restore_state", None)
        saved = (state.get("callbacks") or {}).get(str(key)) if key else None
        if saved is not None and callable(restore):
            restore(saved)
    Log.info(f"resumed training from checkpoint {path} "
             f"(iteration {state['iteration']})")
    return state


def flush_checkpoint(booster, manager: CheckpointManager,
                     callbacks=None) -> Optional[str]:
    """Best-effort final checkpoint (interrupt/exit path): skips when a
    VALID newest checkpoint already covers the current iteration (a torn
    same-iteration bundle must not suppress the flush); never lets a
    checkpoint failure mask the original exception."""
    try:
        cks = manager.checkpoints()
        if cks and cks[0][0] == booster.current_iteration() \
                and manager.validate(cks[0][1]):
            return None
        return save_checkpoint(booster, manager, callbacks=callbacks)
    except BaseException as exc:  # noqa: BLE001 - must not mask the cause
        Log.warning(f"final checkpoint flush failed: "
                    f"{type(exc).__name__}: {exc}")
        return None
