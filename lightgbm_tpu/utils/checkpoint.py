"""Atomic iteration-granular training checkpoints.

A checkpoint is one directory (``ckpt-<iteration>``) holding the full
restart bundle:

* ``model.txt``   — the model string with its ``tpu_bin_mappers:`` and
  ``pandas_categorical:`` trailers (the same bytes ``save_model`` would
  write), so trees rebind into bin space EXACTLY on restore;
* ``state.json``  — the driver's non-array training state: iteration
  counter, bagging/quantization PRNG key words, numpy bit-generator
  states, boost-from-average init scores + flags, early-stop callback
  snapshots, a params fingerprint;
* ``arrays.npz``  — the f32 score buffers (train + per-valid-set) and
  the cached bagging mask.  Restoring the scores byte-for-byte is what
  makes a resumed run produce the *bit-identical* model an
  uninterrupted run would have: replaying trees through the forest
  kernel would re-round the f32 accumulation in a different order.

Write protocol (torn-write safe on POSIX): every file lands in a
hidden temp directory first, each file is flushed + fsync'd, the
manifest (CRC32 + byte count per file) is written last, the temp
directory is atomically renamed into place, and the parent directory
is fsync'd.  A crash at ANY point leaves either a complete previous
checkpoint or an ignorable temp/corrupt directory — `load_latest`
walks newest-first and skips (with a warning) anything whose manifest
is missing, unparseable, or whose CRCs don't match.

Retention keeps the newest `keep` valid checkpoints; pruning deletes
oldest-first, so an interrupt mid-prune can only ever leave EXTRA old
bundles behind, never fewer recent ones.

Multihost groups (ISSUE 8): in a ``jax.distributed`` run every host
writes its LOCAL bundle into ``host-<k>/ckpt-<iteration>`` under the
shared checkpoint root, then all hosts barrier on an allgather of
their (iteration, manifest CRC, local rows) triples — proof every
bundle is durable — and rank 0 alone commits ``global-<iteration>.json``
at the root (host count, per-host CRCs + row counts, shard topology,
params fingerprint), again via temp + fsync + atomic rename.  Resume
walks global manifests newest-first and refuses torn or
mixed-iteration sets: a group is only eligible when every listed host
bundle is present, CRC-valid, and at the manifest's iteration.

Elastic resume: the score buffers are (or reassemble to) GLOBAL f32
row buffers and every PRNG stream keys on global state, so a
checkpoint taken at P shards/hosts resumes at P' (including 1).
Host-partitioned groups are reassembled in process order via the
per-host row counts and re-sliced for the live topology
(`parallel.mesh.local_row_offset`); single-host checkpoints resume at
any device-shard count as-is.  Quantized (int8/int16) training keys
its stochastic rounding on the GLOBAL row index, so elastic resumes
stay BIT-IDENTICAL to uninterrupted runs.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import faultline
from .log import Log

MANIFEST = "manifest.json"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-ckpt-"
_HOST_PREFIX = "host-"
_GLOBAL_PREFIX = "global-"
FORMAT_VERSION = 1

# Topology / operational params whose change does NOT break the bitwise
# resume contract (scores are global f32 buffers; quantized rounding
# keys on GLOBAL row index; aggregation sums are associative ints) —
# excluded from the resume fingerprint so elastic resume is silent.
# Everything else that differs is named in the mismatch message.
ELASTIC_PARAMS = frozenset({
    "tree_learner", "num_machines", "machines", "machine_list_filename",
    "local_listen_port", "time_out", "pre_partition", "num_threads",
    "tpu_feature_shards", "tpu_topology_hosts", "tpu_hist_agg",
    "tpu_donate_buffers",
    "tpu_compile_cache_dir", "tpu_collective_timeout_s",
    "tpu_collective_retries", "tpu_resume_elastic", "tpu_resume_strict",
    "tpu_checkpoint_dir", "tpu_checkpoint_interval",
    "tpu_checkpoint_keep", "verbosity",
})


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # non-POSIX / exotic fs: rename is still atomic
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_file(path: str, data: bytes) -> None:
    """Write + fsync one payload file, honoring the `checkpoint_write`
    fault point: ``truncate`` writes half the bytes (a torn write the
    manifest CRC will catch), ``raise`` aborts mid-bundle."""
    action = faultline.fire("checkpoint_write", path=os.path.basename(path))
    if action == "truncate":
        data = data[:len(data) // 2]
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


class CheckpointManager:
    """Atomic write + validated read + keep-last-N retention over one
    checkpoint directory.

    ``host_count > 1`` switches to the multihost layout: this host's
    bundles live under ``<root>/host-<host_index>/`` and group commits
    (`commit_global`) land ``global-<iteration>.json`` manifests at the
    root.  Single-host managers keep the flat PR-7 layout byte-for-byte.
    """

    def __init__(self, directory: str, keep: int = 3,
                 host_index: int = 0, host_count: int = 1):
        if not directory:
            raise ValueError("checkpoint directory must be non-empty")
        self.root = str(directory)
        self.host_index = int(host_index)
        self.host_count = max(int(host_count), 1)
        self.directory = (self.root if self.host_count == 1
                          else self.host_dir(self.host_index))
        self.keep = max(int(keep), 1)
        os.makedirs(self.directory, exist_ok=True)

    def host_dir(self, host: int) -> str:
        return os.path.join(self.root, f"{_HOST_PREFIX}{int(host):05d}")

    # -- naming --------------------------------------------------------
    @staticmethod
    def _name(iteration: int) -> str:
        return f"{_PREFIX}{int(iteration):08d}"

    @staticmethod
    def _iteration_of(name: str) -> Optional[int]:
        if not name.startswith(_PREFIX):
            return None
        try:
            return int(name[len(_PREFIX):])
        except ValueError:
            return None

    def checkpoints(self) -> List[Tuple[int, str]]:
        """(iteration, path) of every checkpoint-named dir, newest first
        (validity NOT checked here)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            it = self._iteration_of(name)
            if it is not None:
                out.append((it, os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    def latest_iteration(self) -> Optional[int]:
        cks = self.checkpoints()
        return cks[0][0] if cks else None

    # -- write ---------------------------------------------------------
    def save(self, iteration: int, model_text: str, state: Dict,
             arrays: Dict[str, np.ndarray]) -> str:
        """Write one atomic checkpoint bundle; returns its path.
        Re-saving an iteration that already has a VALID checkpoint is a
        no-op (the flush-on-exit path may race a just-written interval
        checkpoint)."""
        from .. import obs

        final = os.path.join(self.directory, self._name(iteration))
        if os.path.isdir(final) and self.validate(final):
            return final
        t0 = time.perf_counter()
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{int(iteration):08d}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            import io as _io

            with obs.span("checkpoint/save", iteration=int(iteration)):
                payloads: Dict[str, bytes] = {
                    "model.txt": model_text.encode("utf-8"),
                    "state.json": json.dumps(state, sort_keys=True).encode(),
                }
                buf = _io.BytesIO()
                np.savez(buf, **arrays)
                payloads["arrays.npz"] = buf.getvalue()
                manifest = {"format": FORMAT_VERSION,
                            "iteration": int(iteration), "files": {}}
                for name, data in payloads.items():
                    # the manifest records the INTENDED bytes: an
                    # injected (or real) torn write then fails CRC
                    # validation exactly like a crash mid-write would
                    manifest["files"][name] = {"crc32": zlib.crc32(data),
                                               "bytes": len(data)}
                    _write_file(os.path.join(tmp, name), data)
                with open(os.path.join(tmp, MANIFEST), "w") as f:
                    json.dump(manifest, f)
                    f.flush()
                    os.fsync(f.fileno())
                _fsync_dir(tmp)
                if os.path.isdir(final):  # stale invalid leftover
                    shutil.rmtree(final, ignore_errors=True)
                os.replace(tmp, final)
                _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retain()
        # rare, durable, worth counting unconditionally: write wall +
        # bundle count beside the train metrics
        obs.REGISTRY.inc("lgbm_checkpoint_writes_total",
                         help="atomic checkpoint bundles committed")
        obs.REGISTRY.observe("lgbm_checkpoint_seconds",
                             time.perf_counter() - t0, op="save")
        obs.event("checkpoint_saved", iteration=int(iteration))
        return final

    def _retain(self) -> None:
        """Keep the newest `keep` checkpoints; drop older ones and any
        stale temp directories.  Deletions run OLDEST-first: a SIGTERM
        (or any interrupt) landing mid-prune then leaves extra OLD
        bundles behind — recoverable clutter — and can never have
        touched the newest valid bundle, which is excluded from the
        deletion list by construction."""
        for it, path in reversed(self.checkpoints()[self.keep:]):
            shutil.rmtree(path, ignore_errors=True)
        try:
            for name in os.listdir(self.directory):
                if name.startswith(_TMP_PREFIX):
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
        except OSError:
            pass

    # -- read ----------------------------------------------------------
    def validate(self, path: str) -> bool:
        """Manifest present, parseable, and every listed file's CRC32 +
        size match."""
        try:
            with open(os.path.join(path, MANIFEST)) as f:
                manifest = json.load(f)
            files = manifest["files"]
            for name, meta in files.items():
                with open(os.path.join(path, name), "rb") as f:
                    data = f.read()
                if len(data) != int(meta["bytes"]) \
                        or zlib.crc32(data) != int(meta["crc32"]):
                    return False
            return {"model.txt", "state.json", "arrays.npz"} <= set(files)
        except (OSError, ValueError, KeyError, TypeError):
            return False

    @staticmethod
    def _read_bundle(path: str) -> Tuple[str, Dict, Dict]:
        """(model_text, state, arrays) of one validated bundle dir."""
        with open(os.path.join(path, "model.txt"), encoding="utf-8") as f:
            model_text = f.read()
        with open(os.path.join(path, "state.json")) as f:
            state = json.load(f)
        with np.load(os.path.join(path, "arrays.npz"),
                     allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        return model_text, state, arrays

    def load_latest(self) -> Optional[Tuple[int, str, Dict, Dict, str]]:
        """Newest VALID checkpoint as (iteration, model_text, state,
        arrays, path); torn/corrupt checkpoints are skipped with a
        warning.  None when no valid checkpoint exists."""
        for it, path in self.checkpoints():
            if not self.validate(path):
                Log.warning(f"skipping corrupt/torn checkpoint {path} "
                            "(manifest missing or CRC mismatch)")
                continue
            try:
                model_text, state, arrays = self._read_bundle(path)
            except (OSError, ValueError, KeyError) as exc:
                Log.warning(f"skipping unreadable checkpoint {path}: {exc}")
                continue
            return it, model_text, state, arrays, path
        return None

    # -- multihost group commit + read ---------------------------------
    def manifest_crc(self, path: str) -> Optional[int]:
        """CRC32 of a bundle's manifest bytes — the durable identity a
        host proves at the commit barrier (the manifest itself CRCs
        every payload file, so this one word covers the bundle)."""
        try:
            with open(os.path.join(path, MANIFEST), "rb") as f:
                return zlib.crc32(f.read())
        except OSError:
            return None

    def _default_barrier(self, vec: np.ndarray) -> List[np.ndarray]:
        """All-hosts-durable barrier: allgather each host's commit
        triple, under the collective watchdog."""
        if self.host_count == 1:
            return [vec]
        from ..parallel.topology import host_allgather

        out = host_allgather(np.asarray(vec), name="checkpoint_barrier")
        return [np.asarray(row) for row in np.asarray(out)]

    def commit_global(self, iteration: int, topology: Optional[Dict] = None,
                      rows: int = 0, params_fingerprint: int = 0,
                      barrier=None) -> Optional[str]:
        """Barrier on every host's durable local bundle, then commit the
        group manifest (rank 0 only; returns its path there, None on
        other ranks).  Refuses — without writing — when any host reports
        a torn bundle or a different iteration (a mixed/torn set must
        never look committed).  A host with a torn LOCAL bundle still
        ENTERS the barrier, contributing a sentinel — raising before the
        allgather would strand every healthy peer inside it, the exact
        hang this layer exists to eliminate; the sentinel makes the
        whole group refuse symmetrically instead.  `barrier` is
        injectable for single-process tests simulating a host group."""
        local = os.path.join(self.directory, self._name(iteration))
        crc = self.manifest_crc(local)
        torn = crc is None or not self.validate(local)
        vec = np.asarray([-1 if torn else int(iteration),
                          int(crc or 0), int(rows)], np.int64)
        entries = [np.asarray(e).reshape(-1)
                   for e in (barrier or self._default_barrier)(vec)]
        if len(entries) != self.host_count:
            raise ValueError(
                f"checkpoint barrier returned {len(entries)} entries for "
                f"{self.host_count} hosts")
        iters = sorted({int(e[0]) for e in entries})
        if -1 in iters:
            bad = [k for k, e in enumerate(entries) if int(e[0]) == -1]
            raise ValueError(
                f"host(s) {bad} reported a torn/missing local bundle at "
                f"iteration {iteration}; refusing the global commit")
        if iters != [int(iteration)]:
            raise ValueError(
                "mixed-iteration checkpoint set across hosts "
                f"(iterations {iters}); refusing the global commit")
        if self.host_index != 0:
            return None
        manifest = {
            "format": FORMAT_VERSION,
            "iteration": int(iteration),
            "host_count": int(self.host_count),
            "hosts": [{"index": k, "crc": int(e[1]), "rows": int(e[2])}
                      for k, e in enumerate(entries)],
            "params_fingerprint": int(params_fingerprint),
            "topology": dict(topology or {}),
        }
        name = f"{_GLOBAL_PREFIX}{int(iteration):08d}.json"
        tmp = os.path.join(self.root, f".tmp-{name}-{os.getpid()}")
        _write_file(tmp, json.dumps(manifest, sort_keys=True).encode())
        os.replace(tmp, os.path.join(self.root, name))
        _fsync_dir(self.root)
        self._retain_global()
        from .. import obs

        obs.REGISTRY.inc("lgbm_checkpoint_commits_total",
                         help="group manifests committed (rank 0)")
        obs.event("checkpoint_group_committed", iteration=int(iteration),
                  host_count=int(self.host_count))
        return os.path.join(self.root, name)

    def group_manifests(self) -> List[Tuple[int, str]]:
        """(iteration, path) of every global manifest, newest first."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if not (name.startswith(_GLOBAL_PREFIX)
                    and name.endswith(".json")):
                continue
            try:
                it = int(name[len(_GLOBAL_PREFIX):-len(".json")])
            except ValueError:
                continue
            out.append((it, os.path.join(self.root, name)))
        out.sort(reverse=True)
        return out

    def _retain_global(self) -> None:
        for it, path in reversed(self.group_manifests()[self.keep:]):
            try:
                os.unlink(path)
            except OSError:
                pass
        # stale manifest temps from a commit interrupted between write
        # and rename — harmless debris, but unbounded across preemptions
        try:
            for name in os.listdir(self.root):
                if name.startswith(f".tmp-{_GLOBAL_PREFIX}"):
                    os.unlink(os.path.join(self.root, name))
        except OSError:
            pass

    def host_bundle_path(self, host: int, iteration: int,
                         host_count: Optional[int] = None) -> str:
        """Bundle dir of `host` at `iteration` under the STORED layout
        (flat when the checkpoint was single-host)."""
        hc = self.host_count if host_count is None else int(host_count)
        base = self.root if hc == 1 else self.host_dir(host)
        return os.path.join(base, self._name(iteration))

    def validate_group(self, manifest: Dict) -> bool:
        """Every host bundle the manifest lists is present, CRC-matched,
        and at the manifest's iteration — the torn/mixed-set gate.  The
        WHOLE walk is exception-guarded: a malformed manifest (hosts not
        a list, entries missing keys) must read as invalid and be
        skipped with a warning upstream, never crash the resume."""
        try:
            it = int(manifest["iteration"])
            hc = int(manifest["host_count"])
            hosts = manifest["hosts"]
            if len(hosts) != hc:
                return False
            for entry in hosts:
                path = self.host_bundle_path(int(entry["index"]), it,
                                             host_count=hc)
                if self.manifest_crc(path) != int(entry["crc"]):
                    return False
                if not self.validate(path):
                    return False
        except (KeyError, TypeError, ValueError, AttributeError):
            return False
        return True

    def load_latest_group(self) -> Optional[Tuple[int, Dict]]:
        """Newest fully-valid committed group as (iteration, manifest);
        torn/partial/mixed groups are skipped with a warning."""
        for it, path in self.group_manifests():
            try:
                with open(path) as f:
                    manifest = json.load(f)
            except (OSError, ValueError) as exc:
                Log.warning(f"skipping unreadable group manifest {path}: "
                            f"{exc}")
                continue
            if not self.validate_group(manifest):
                Log.warning(
                    f"skipping torn/partial checkpoint group {path}: a "
                    "host bundle is missing, corrupt, or at a different "
                    "iteration")
                continue
            return it, manifest
        return None


# ---------------------------------------------------------------------------
# Booster-level bundle assembly
# ---------------------------------------------------------------------------
def make_manager(directory: str, keep: int = 3) -> CheckpointManager:
    """CheckpointManager bound to this process's position in the live
    host group (flat single-host layout when the group is 1)."""
    import jax

    return CheckpointManager(directory, keep=keep,
                             host_index=int(jax.process_index()),
                             host_count=int(jax.process_count()))


def _params_snapshot(params: Dict) -> Dict[str, str]:
    """Canonical-keyed stringified params — stored in the bundle so a
    mismatch at resume can NAME the differing keys, not just a
    fingerprint inequality."""
    from ..config import canonical_name

    out: Dict[str, str] = {}
    for k, v in (params or {}).items():
        canon = canonical_name(str(k)) or str(k)
        out[canon] = str(v)
    return out


def _params_fingerprint(params: Dict) -> int:
    """Stable fingerprint of the training params a bitwise resume
    depends on.  Topology/operational keys (`ELASTIC_PARAMS`) are
    excluded: resharding P -> P' must not read as a params change."""
    snap = {k: v for k, v in _params_snapshot(params).items()
            if k not in ELASTIC_PARAMS}
    return zlib.crc32(json.dumps(snap, sort_keys=True).encode())


def _params_fingerprint_legacy(params: Dict) -> int:
    """The PR-7 fingerprint (all params, raw keys) — kept so bundles
    written before the snapshot existed still compare meaningfully."""
    try:
        text = json.dumps({str(k): str(v) for k, v in params.items()},
                          sort_keys=True)
    except (TypeError, ValueError):
        text = str(sorted(str(k) for k in params))
    return zlib.crc32(text.encode())


def params_diff(stored: Dict[str, str], live: Dict[str, str]
                ) -> Tuple[List[Tuple[str, str, str]],
                           List[Tuple[str, str, str]]]:
    """Key-level diff of two params snapshots as (elastic_changes,
    material_changes), each a list of (key, stored_value, live_value)
    with "<unset>" marking absence.  Elastic changes are topology moves
    the bitwise-resume contract absorbs; material changes break it."""
    elastic: List[Tuple[str, str, str]] = []
    material: List[Tuple[str, str, str]] = []
    for k in sorted(set(stored) | set(live)):
        a = stored.get(k, "<unset>")
        b = live.get(k, "<unset>")
        if a == b:
            continue
        (elastic if k in ELASTIC_PARAMS else material).append((k, a, b))
    return elastic, material


def _fmt_diff(changes: Sequence[Tuple[str, str, str]]) -> str:
    return ", ".join(f"{k}: {a} -> {b}" for k, a, b in changes)


def _resume_flags(booster) -> Tuple[bool, bool]:
    """(tpu_resume_elastic, tpu_resume_strict) from the driver's
    already-validated Config — no re-parsing of the raw params dict.
    Registry defaults apply for drivers without a training config
    (they cannot restore anyway; restore_train_state raises)."""
    cfg = getattr(booster._driver, "config", None)
    if cfg is None:
        return True, False
    return bool(cfg.tpu_resume_elastic), bool(cfg.tpu_resume_strict)


def _callback_states(callbacks) -> Dict:
    out = {}
    for cb in callbacks or []:
        key = getattr(cb, "state_key", None)
        snap = getattr(cb, "snapshot_state", None)
        if key and callable(snap):
            out[str(key)] = snap()
    return out


def save_checkpoint(booster, manager: CheckpointManager,
                    callbacks=None, barrier=None) -> str:
    """Write one atomic checkpoint of a live training booster.  In a
    multihost group the local bundle is followed by the all-hosts-
    durable barrier and rank 0's global-manifest commit."""
    state, arrays = booster._driver.capture_train_state()
    state["best_iteration"] = int(booster.best_iteration)
    state["params_fingerprint"] = _params_fingerprint(booster.params)
    state["params_snapshot"] = _params_snapshot(booster.params)
    cb_states = _callback_states(callbacks)
    if cb_states:
        state["callbacks"] = cb_states
    model_text = booster.model_to_string(num_iteration=-1)
    path = manager.save(state["iteration"], model_text, state, arrays)
    if manager.host_count > 1:
        topo = dict(state.get("topology") or {})
        manager.commit_global(
            state["iteration"], topology=topo,
            rows=int(topo.get("rows", 0)),
            params_fingerprint=state["params_fingerprint"],
            barrier=barrier)
    return path


# params whose change is a TOPOLOGY move — the set `tpu_resume_elastic=
# false` refuses (the broader ELASTIC_PARAMS also holds operational
# knobs like verbosity that no mode should refuse)
_TOPOLOGY_KEYS = frozenset({
    "tree_learner", "num_machines", "machines", "machine_list_filename",
    "pre_partition", "tpu_feature_shards", "tpu_hist_agg",
})


def _live_partition(booster) -> Tuple[bool, int, int, int]:
    """(partitioned, local_rows, global_offset, global_rows) of the
    live training context.  Replicated/single-process ingest holds the
    full global rows locally, so offset 0 and total == local."""
    drv = booster._driver
    local_n = int(drv.train_data.num_data)
    partitioned = bool(getattr(drv.learner, "_partitioned", False))
    if partitioned:
        from ..parallel.mesh import local_row_offset

        offset, total = local_row_offset(local_n)
    else:
        offset, total = 0, local_n
    return partitioned, local_n, offset, total


def _slice_rows(arrays: Dict, offset: int, local_n: int) -> Dict:
    """Re-shard GLOBAL row buffers to this process's live slice.  Valid-
    set score buffers are left as-is: `restore_train_state` replays any
    whose length no longer matches its live valid set."""
    out = dict(arrays)
    a = out.get("train_scores")
    if a is not None and a.shape[1] != local_n:
        out["train_scores"] = np.ascontiguousarray(
            a[:, offset:offset + local_n])
    m = out.get("bag_mask")
    if m is not None and m.shape[0] != local_n:
        out["bag_mask"] = np.ascontiguousarray(m[offset:offset + local_n])
    return out


def _uncommitted_group_agreement(manager: CheckpointManager
                                 ) -> Tuple[int, bool]:
    """(min-common locally-valid iteration, mixed) across the host
    group, agreed over barriers of each host's local bundle state.
    `manager.directory` already IS this host's bundle dir, so the local
    walk uses the manager directly.

    Two symmetric rounds: (1) gather each host's NEWEST valid
    iteration and take the min; (2) gather whether every host holds a
    VALID bundle at exactly that min — host k's newest being N does not
    imply its older bundle at min(N') is intact, and discovering that
    asymmetrically (one rank raising while peers load and train) would
    desync the group.  Both rounds' inputs/outputs are identical on all
    ranks, so every host raises or proceeds together.  `mixed` marks
    the impossible-to-agree case: some host holds bundles while another
    holds none (its state is locally unrecoverable)."""
    newest = -1
    for cand_it, cand_path in manager.checkpoints():
        if manager.validate(cand_path):
            newest = cand_it
            break
    entries = [int(np.asarray(e).reshape(-1)[0])
               for e in manager._default_barrier(
                   np.asarray([newest, 0, 0], np.int64))]
    lo, hi = min(entries), max(entries)
    if lo < 0:
        return -1, hi >= 0
    mine_ok = int(manager.validate(
        os.path.join(manager.directory, manager._name(lo))))
    oks = [int(np.asarray(e).reshape(-1)[0])
           for e in manager._default_barrier(
               np.asarray([mine_ok, 0, 0], np.int64))]
    if not all(oks):
        bad = [k for k, ok in enumerate(oks) if not ok]
        raise ValueError(
            f"uncommitted multihost resume agreed on iteration {lo} but "
            f"host(s) {bad} hold no valid bundle there; the group "
            "cannot resume consistently — clear the checkpoint dir to "
            "start fresh everywhere")
    return lo, False


def _peek_bundle_state(manager: CheckpointManager, iteration: int
                       ) -> Dict:
    """This host's bundle state.json at `iteration`, {} when unreadable
    — a cheap metadata peek (no model/array IO)."""
    return _read_json(os.path.join(manager.directory,
                                   CheckpointManager._name(iteration),
                                   "state.json"))


def _uncommitted_group_resume(manager: CheckpointManager, target: int
                              ) -> Tuple[int, str, Dict, Dict, str]:
    """Load this host's bundle at the group-agreed min-common
    iteration (a set whose global manifest never committed — e.g. the
    final flush's barrier died with a peer).  Validity at `target` was
    already barriered by the agreement; a failure here is a race since
    that check and still raises (every peer hit the same agreement)."""
    path = os.path.join(manager.directory, manager._name(target))
    if not manager.validate(path):
        raise ValueError(
            f"uncommitted multihost resume agreed on iteration {target} "
            f"but this host's bundle {path} is missing or torn; the "
            "group cannot resume consistently")
    Log.warning(
        "no committed checkpoint group at or above this iteration; "
        f"resuming from the group's min-common local iteration {target}")
    model_text, state, arrays = manager._read_bundle(path)
    return target, model_text, state, arrays, path


def _load_for_topology(booster, manager: CheckpointManager,
                       allow_elastic: bool
                       ) -> Optional[Tuple[int, str, Dict, Dict, str]]:
    """Newest restorable checkpoint resolved against the LIVE topology.

    * A committed group at the live host count: each host reads its own
      bundle (local slices already match the live partition).
    * A committed group at a DIFFERENT host count (elastic): reassemble
      the global row buffers from every host bundle in process order,
      then re-slice for the live partition.
    * No group manifests: the flat single-host layout loads directly —
      also the device-shard elastic path, since flat arrays are already
      global — re-sliced when the live ingest is partitioned.
    * Multihost manager but no committed group (e.g. the final flush's
      barrier timed out on a dead peer): the hosts AGREE on the
      min-common locally-valid iteration over a barrier — per-host
      "newest local bundle" choices would restore different iterations
      and desync every subsequent collective.
    """
    # ---- pick the NEWEST durable source, not the first that exists:
    # a committed group, an uncommitted-but-agreed per-host set, and a
    # flat root checkpoint can all coexist (e.g. a pod run committed at
    # iteration 6, was elastically resumed single-host to iteration 9,
    # and died again) — resuming the committed group unconditionally
    # would silently discard the newer durable progress.  A committed
    # group takes equal-iteration ties (it is the coordinated record).
    group = manager.load_latest_group()
    group_it = group[0] if group is not None else -1
    flat_mgr = (manager if manager.host_count == 1
                else CheckpointManager(manager.root, keep=manager.keep))
    flat_it = next((cit for cit, cpath in flat_mgr.checkpoints()
                    if flat_mgr.validate(cpath)), -1)
    agreed_it, mixed = -1, False
    if manager.host_count > 1:
        # the agreement barrier runs UNCONDITIONALLY on every multihost
        # resume: whether its result is used depends only on shared
        # root state, so every rank still enters the same collectives
        # in the same order
        agreed_it, mixed = _uncommitted_group_agreement(manager)
    if agreed_it >= 0:
        # an uncommitted set is only usable at its ORIGINAL host count:
        # without a committed manifest there is no coordinated record
        # of the old partition to re-shard from, so a topology change
        # falls back to the newest committed/flat source instead of
        # handing each live host a stale slice (every bundle records
        # the same host_count, so this local peek is group-consistent)
        stored_hc = int((_peek_bundle_state(manager, agreed_it)
                         .get("topology") or {})
                        .get("host_count", manager.host_count))
        if stored_hc != manager.host_count:
            msg = (
                f"newest uncommitted checkpoint set (iteration "
                f"{agreed_it}) was written by {stored_hc} host(s) but "
                f"the live group has {manager.host_count}; it cannot be "
                "re-sharded without a committed manifest — restart with "
                f"{stored_hc} hosts to recover iteration {agreed_it}")
            if group_it < 0 and flat_it < 0:
                # nothing to fall back to: refuse rather than silently
                # train from scratch over recoverable state
                raise ValueError(msg)
            Log.warning(msg + "; falling back to an older "
                        "committed/flat checkpoint")
            agreed_it = -1

    if group_it < 0 and agreed_it < 0 and flat_it < 0:
        if mixed:
            raise ValueError(
                "uncommitted multihost checkpoint set: some host has no "
                "valid local bundle and no committed group or flat "
                "checkpoint exists; the group cannot resume "
                "consistently — clear the checkpoint dir to start "
                "fresh everywhere")
        return None

    if agreed_it > group_it and agreed_it >= flat_it:
        return _uncommitted_group_resume(manager, agreed_it)

    if flat_it > group_it and flat_it > agreed_it:
        if manager.host_count > 1 and not allow_elastic:
            raise ValueError(
                "checkpoint was written single-host but the live group "
                f"has {manager.host_count} hosts; set tpu_resume_elastic"
                "=true to re-shard on load")
        flat = flat_mgr.load_latest()
        if flat is None:  # raced away since the peek; nothing newer
            return None
        it, model_text, state, arrays, path = flat
        partitioned, local_n, offset, total = _live_partition(booster)
        stored_rows = int((state.get("topology") or {}).get("rows",
                                                            total))
        if stored_rows != total:
            raise ValueError(
                f"checkpoint {path} was taken over {stored_rows} rows "
                f"but the live dataset holds {total}; resume needs the "
                "same training data")
        return it, model_text, state, _slice_rows(arrays, offset,
                                                  local_n), path

    it, manifest = group
    stored_hc = int(manifest["host_count"])
    if stored_hc == manager.host_count:
        path = manager.host_bundle_path(manager.host_index, it)
        try:
            model_text, state, arrays = manager._read_bundle(path)
        except (OSError, ValueError, KeyError) as exc:
            # returning None would train THIS rank from scratch while
            # its peers resume at iteration `it` — a guaranteed
            # collective desync; fail loud instead
            raise ValueError(
                f"committed checkpoint bundle {path} is unreadable "
                f"({exc}); refusing to restart this rank from zero "
                f"while its peers resume iteration {it}") from exc
        return it, model_text, state, arrays, path
    if not allow_elastic:
        raise ValueError(
            f"checkpoint group was written by {stored_hc} hosts but the "
            f"live group has {manager.host_count}; set "
            "tpu_resume_elastic=true to re-shard on load")
    # ---- elastic host-count change: reassemble global row buffers ----
    hosts = sorted(manifest["hosts"], key=lambda e: int(e["index"]))
    bundles = []
    for entry in hosts:
        path = manager.host_bundle_path(int(entry["index"]), it,
                                        host_count=stored_hc)
        bundles.append(manager._read_bundle(path))
    model_text, state, _ = bundles[0]
    stored_partitioned = bool(
        (state.get("topology") or {}).get("partitioned", stored_hc > 1))
    if stored_partitioned:
        arrays: Dict = {}
        arrays["train_scores"] = np.concatenate(
            [b[2]["train_scores"] for b in bundles], axis=1)
        masks = [b[2].get("bag_mask") for b in bundles]
        if all(m is not None for m in masks):
            arrays["bag_mask"] = np.concatenate(masks, axis=0)
        # per-host valid slices of the OLD partition cannot be
        # reassembled against the new valid sets: replay handles them
    else:
        # replicated ingest: every host already holds the global arrays
        arrays = dict(bundles[0][2])
    partitioned, local_n, offset, total = _live_partition(booster)
    stored_total = int(sum(int(e.get("rows", 0)) for e in hosts)) \
        or arrays["train_scores"].shape[1]
    if arrays["train_scores"].shape[1] != total:
        raise ValueError(
            f"checkpoint group covers {stored_total} global rows but the "
            f"live dataset holds {total}; elastic resume needs the same "
            "training data in the same global row order")
    Log.info(f"elastic resume: re-sharding checkpoint group at iteration "
             f"{it} from {stored_hc} host(s) to {manager.host_count}")
    return it, model_text, state, _slice_rows(arrays, offset,
                                              local_n), \
        manager.host_bundle_path(0, it, host_count=stored_hc)


def restore_checkpoint(booster, manager: CheckpointManager,
                       callbacks=None) -> Optional[Dict]:
    """Restore a booster from the newest valid checkpoint; returns the
    restored state dict (with "iteration") or None when no valid
    checkpoint exists.  The booster must have been constructed with the
    same training DATA as the checkpointed run; the shard/host topology
    may differ (elastic resume — global buffers are re-sliced for the
    live mesh and the bitwise contract holds for quantized precisions).
    A MATERIAL params mismatch names the differing keys: a warning by
    default, an error under `tpu_resume_strict`."""
    allow_elastic, strict = _resume_flags(booster)
    t_restore = time.perf_counter()
    found = _load_for_topology(booster, manager, allow_elastic)
    if found is None:
        return None
    it, model_text, state, arrays, path = found
    stored_snap = state.get("params_snapshot")
    if stored_snap is not None:
        elastic, material = params_diff(stored_snap,
                                        _params_snapshot(booster.params))
        topo_moves = [c for c in elastic if c[0] in _TOPOLOGY_KEYS]
        # the topology refusal must run regardless of what ELSE changed:
        # a co-occurring material diff must not smuggle a refused
        # re-shard past tpu_resume_elastic=false
        if topo_moves and not allow_elastic:
            raise ValueError(
                f"resume topology changed ({_fmt_diff(topo_moves)}) but "
                "tpu_resume_elastic=false refuses re-sharding")
        if material:
            msg = (f"resuming from {path} with different training params "
                   f"({_fmt_diff(material)}); the resumed model will NOT "
                   "be bit-identical to an uninterrupted run")
            if strict:
                raise ValueError(msg + " (tpu_resume_strict=true)")
            Log.warning(msg)
        elif topo_moves:
            Log.info("elastic resume: topology params changed "
                     f"({_fmt_diff(topo_moves)}); scores are global "
                     "buffers, so the bitwise contract holds for "
                     "quantized precisions")
    elif state.get("params_fingerprint") not in (
            None, _params_fingerprint_legacy(booster.params)):
        Log.warning(
            f"resuming from {path} with different training params; the "
            "resumed model will NOT be bit-identical to an uninterrupted "
            "run")
    booster._driver.restore_train_state(model_text, state, arrays)
    booster.best_iteration = int(state.get("best_iteration", -1))
    for cb in callbacks or []:
        key = getattr(cb, "state_key", None)
        restore = getattr(cb, "restore_state", None)
        saved = (state.get("callbacks") or {}).get(str(key)) if key else None
        if saved is not None and callable(restore):
            restore(saved)
    Log.info(f"resumed training from checkpoint {path} "
             f"(iteration {state['iteration']})")
    from .. import obs

    obs.REGISTRY.inc("lgbm_checkpoint_restores_total",
                     help="successful checkpoint restores")
    obs.REGISTRY.observe("lgbm_checkpoint_seconds",
                         time.perf_counter() - t_restore, op="restore")
    obs.event("checkpoint_restored", iteration=int(state["iteration"]))
    return state


def flush_checkpoint(booster, manager: CheckpointManager,
                     callbacks=None, barrier=None) -> Optional[str]:
    """Best-effort final checkpoint (interrupt/exit path): skips when a
    VALID newest checkpoint already covers the current iteration (a torn
    same-iteration bundle must not suppress the flush); never lets a
    checkpoint failure mask the original exception.  In a multihost
    group, a locally-covered iteration whose GLOBAL manifest never
    committed (e.g. the barrier died with a peer) retries the commit —
    and when even that fails, the durable LOCAL bundle still supports
    the per-host fallback resume."""
    try:
        cks = manager.checkpoints()
        if cks and cks[0][0] == booster.current_iteration() \
                and manager.validate(cks[0][1]):
            if manager.host_count > 1:
                committed = any(it == cks[0][0] and
                                manager.validate_group(_read_json(p))
                                for it, p in manager.group_manifests())
                if not committed:
                    topo = booster._driver.topology_snapshot()
                    manager.commit_global(
                        cks[0][0], topology=topo,
                        rows=int(topo.get("rows", 0)),
                        params_fingerprint=_params_fingerprint(
                            booster.params),
                        barrier=barrier)
            return None
        return save_checkpoint(booster, manager, callbacks=callbacks,
                               barrier=barrier)
    except BaseException as exc:  # noqa: BLE001 - must not mask the cause
        Log.warning(f"final checkpoint flush failed: "
                    f"{type(exc).__name__}: {exc}")
        return None


def _read_json(path: str) -> Dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}
