"""Leveled logging (reference include/LightGBM/utils/log.h:37-104).

Levels mirror the reference LogLevel enum: Fatal=-1, Warning=0, Info=1,
Debug=2.  `Log.fatal` raises (reference log.h:76-90 throws
std::runtime_error); the active level is settable per-thread
(reference THREAD_LOCAL level, log.h:104) and maps from the `verbosity`
config param the same way the reference does (c_api.cpp maps
verbosity<0 -> Fatal, 0 -> Warning, 1 -> Info, >1 -> Debug).

A redirect callback supports the binding use-case (reference
Log::ResetCallBack used by the R/Python packages).

Multihost attribution: once `jax.process_count() > 1` every line gets a
``[host k]`` prefix so interleaved pod logs stay attributable, and
every `Log.warning` counts into the telemetry registry
(``lgbm_log_warnings_total``) so a fleet's warning rate is scrapeable
even when nobody is tailing stdout.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

LOG_FATAL = -1
LOG_WARNING = 0
LOG_INFO = 1
LOG_DEBUG = 2

_state = threading.local()
_callback: Optional[Callable[[str], None]] = None
_host_tag_cache: Optional[str] = None


def _host_tag() -> str:
    """``"[host k] "`` on a >1-process group, else "".  Resolved lazily
    and only from an ALREADY-initialized jax backend (logging must never
    force backend init); a positive resolution is cached — process
    count cannot change after distributed init."""
    global _host_tag_cache
    if _host_tag_cache is not None:
        return _host_tag_cache
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return ""
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return ""
        if int(jax_mod.process_count()) > 1:
            _host_tag_cache = f"[host {int(jax_mod.process_index())}] "
        else:
            _host_tag_cache = ""
    except Exception:  # pragma: no cover - backend mid-teardown
        return ""
    return _host_tag_cache


class LightGBMError(RuntimeError):
    """Raised by Log.fatal (the analog of the reference's
    std::runtime_error thrown from Log::Fatal)."""


class Log:
    @staticmethod
    def reset_level(level: int) -> None:
        _state.level = int(level)

    @staticmethod
    def level_from_verbosity(verbosity: int) -> int:
        if verbosity < 0:
            return LOG_FATAL
        if verbosity == 0:
            return LOG_WARNING
        if verbosity == 1:
            return LOG_INFO
        return LOG_DEBUG

    @staticmethod
    def get_level() -> int:
        return getattr(_state, "level", LOG_INFO)

    @staticmethod
    def reset_callback(cb: Optional[Callable[[str], None]]) -> None:
        global _callback
        _callback = cb

    @staticmethod
    def _write(level: int, tag: str, msg: str) -> None:
        if level > Log.get_level():
            return
        line = f"{_host_tag()}[LightGBM] [{tag}] {msg}\n"
        if _callback is not None:
            _callback(line)
        else:
            sys.stdout.write(line)
            sys.stdout.flush()

    @staticmethod
    def debug(msg: str) -> None:
        Log._write(LOG_DEBUG, "Debug", msg)

    @staticmethod
    def info(msg: str) -> None:
        Log._write(LOG_INFO, "Info", msg)

    @staticmethod
    def warning(msg: str) -> None:
        # count BEFORE the verbosity filter: a silenced fleet's warning
        # rate stays observable through the registry
        from ..obs.metrics import REGISTRY

        REGISTRY.inc("lgbm_log_warnings_total",
                     help="Log.warning calls (pre-verbosity-filter)")
        Log._write(LOG_WARNING, "Warning", msg)

    @staticmethod
    def fatal(msg: str) -> None:
        Log._write(LOG_FATAL, "Fatal", msg)
        raise LightGBMError(msg)
