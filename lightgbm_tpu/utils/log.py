"""Leveled logging (reference include/LightGBM/utils/log.h:37-104).

Levels mirror the reference LogLevel enum: Fatal=-1, Warning=0, Info=1,
Debug=2.  `Log.fatal` raises (reference log.h:76-90 throws
std::runtime_error); the active level is settable per-thread
(reference THREAD_LOCAL level, log.h:104) and maps from the `verbosity`
config param the same way the reference does (c_api.cpp maps
verbosity<0 -> Fatal, 0 -> Warning, 1 -> Info, >1 -> Debug).

A redirect callback supports the binding use-case (reference
Log::ResetCallBack used by the R/Python packages).
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

LOG_FATAL = -1
LOG_WARNING = 0
LOG_INFO = 1
LOG_DEBUG = 2

_state = threading.local()
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(RuntimeError):
    """Raised by Log.fatal (the analog of the reference's
    std::runtime_error thrown from Log::Fatal)."""


class Log:
    @staticmethod
    def reset_level(level: int) -> None:
        _state.level = int(level)

    @staticmethod
    def level_from_verbosity(verbosity: int) -> int:
        if verbosity < 0:
            return LOG_FATAL
        if verbosity == 0:
            return LOG_WARNING
        if verbosity == 1:
            return LOG_INFO
        return LOG_DEBUG

    @staticmethod
    def get_level() -> int:
        return getattr(_state, "level", LOG_INFO)

    @staticmethod
    def reset_callback(cb: Optional[Callable[[str], None]]) -> None:
        global _callback
        _callback = cb

    @staticmethod
    def _write(level: int, tag: str, msg: str) -> None:
        if level > Log.get_level():
            return
        line = f"[LightGBM] [{tag}] {msg}\n"
        if _callback is not None:
            _callback(line)
        else:
            sys.stdout.write(line)
            sys.stdout.flush()

    @staticmethod
    def debug(msg: str) -> None:
        Log._write(LOG_DEBUG, "Debug", msg)

    @staticmethod
    def info(msg: str) -> None:
        Log._write(LOG_INFO, "Info", msg)

    @staticmethod
    def warning(msg: str) -> None:
        Log._write(LOG_WARNING, "Warning", msg)

    @staticmethod
    def fatal(msg: str) -> None:
        Log._write(LOG_FATAL, "Fatal", msg)
        raise LightGBMError(msg)
