"""Persisted perf autotuning: measured winners replace hard-coded "auto".

PRs 3-5 tuned the histogram kernel by hand and froze the winners into
`_resolve_hist_impl`'s heuristics; every new backend generation re-opens
the question and the answer so far lived in a human re-running
tools/perf_probe.py.  This module makes the sweep's verdict durable:

* a PROFILE FILE (JSON, beside the PR-4 persistent XLA compile cache by
  default) maps (backend platform, device count, shape bucket) to the
  measured winning configuration — hist impl x block today, with the
  aggregation and bucket-policy winners recorded alongside for the
  learner's other "auto" sites;
* `tpu_autotune=load` resolves every "auto" from the profile when a
  matching entry exists; a profile recorded on a DIFFERENT platform or
  device count raises AutotuneStaleProfile — measured numbers from the
  wrong topology are worse than heuristics because they look authoritative;
* `tpu_autotune=tune` measures the missing bucket first (the same
  bench_hist_operands microbench perf_probe's hist sweep runs, on
  synthetic operands keyed by the bucket — dataset-independent, so one
  profile serves every same-shaped dataset), persists it, then loads.

Shape buckets quantize (rows, features) to powers of two and carry the
bin count exactly — the same coarsening the PR-4 compile-cache shape
buckets apply, so profile entries and cached XLA programs invalidate on
the same boundaries.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from typing import Dict, Optional

import numpy as np

LOG = logging.getLogger("lightgbm_tpu.autotune")

PROFILE_VERSION = 1
# rows of synthetic operands per tune measurement: enough blocks for a
# stable rows/s at every candidate block size, small enough that a tune
# pass costs seconds, not a training run
_TUNE_ROWS_CAP = 131072
_TUNE_REPS = 3


class AutotuneStaleProfile(RuntimeError):
    """The profile was recorded on a different backend/topology.

    Raised (never silently ignored) in load/tune modes: applying a v5e
    profile to a v4 pod — or a 1-chip profile to an 8-chip mesh — would
    pin measured-looking but wrong winners.  Delete or re-tune the file."""


def profile_path(config) -> str:
    """Resolved profile location: the explicit override, else beside the
    persistent XLA compile cache, else a dotfile in the working dir."""
    explicit = str(getattr(config, "tpu_autotune_profile", "") or "")
    if explicit:
        return explicit
    cache_dir = str(getattr(config, "tpu_compile_cache_dir", "") or "")
    if cache_dir:
        return os.path.join(cache_dir, "autotune_profile.json")
    return os.path.join(os.getcwd(), ".lgbtpu_autotune.json")


def backend_fingerprint() -> Dict[str, object]:
    import jax

    return {"platform": str(jax.devices()[0].platform),
            "device_count": int(jax.device_count())}


def shape_bucket(n_rows: int, num_features: int, num_bins: int) -> str:
    """Power-of-two (rows, features) + exact bin count bucket key."""
    def up2(x):
        return 1 << max(int(x) - 1, 1).bit_length()

    return f"r{up2(n_rows)}_f{up2(num_features)}_b{int(num_bins)}"


def load_profile(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            prof = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        LOG.warning("autotune profile %r unreadable (%s) — ignoring", path,
                    exc)
        return None
    if not isinstance(prof, dict) or "entries" not in prof:
        LOG.warning("autotune profile %r malformed — ignoring", path)
        return None
    return prof


def save_profile(path: str, profile: dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # atomic replace: a concurrent reader never sees a half-written file
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(profile, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def check_fingerprint(profile: dict, path: str) -> None:
    """Raise AutotuneStaleProfile unless the profile matches this process'
    backend platform, device count, and schema version."""
    fp = backend_fingerprint()
    if int(profile.get("version", -1)) != PROFILE_VERSION:
        raise AutotuneStaleProfile(
            f"autotune profile {path!r} has schema version "
            f"{profile.get('version')!r}, this build expects "
            f"{PROFILE_VERSION}; re-run `perf_probe tune` (or delete it)")
    for key in ("platform", "device_count"):
        got, now = profile.get(key), fp[key]
        if got != now:
            raise AutotuneStaleProfile(
                f"autotune profile {path!r} was recorded on {key}={got!r} "
                f"but this process runs {key}={now!r} — measured winners "
                "from another topology are refused; re-run `perf_probe "
                "tune` here (or point tpu_autotune_profile elsewhere)")


def tune_entry(n_rows: int, num_features: int, num_bins: int,
               precision: str, split_batch: int = 25) -> dict:
    """Measure the hist-kernel winners for one shape bucket.

    Synthetic operands (bucket-keyed rng) through the grower's own
    batched contraction — the same microbench tools/perf_probe.py's hist
    sweep times — across impl x block, including the fused megakernel
    path where the precision supports its in-kernel scan.  Returns the
    profile entry (winning impl/block + the full measured table)."""
    import jax
    import jax.numpy as jnp

    from ..ops.fused import fused_scan_ok, mosaic_int16_ok
    from ..ops.histogram import (_INT_STAT_DTYPES, bench_hist_operands,
                                 build_histogram_batched_t)

    on_tpu = jax.devices()[0].platform == "tpu"
    n = min(int(n_rows), _TUNE_ROWS_CAP)
    rng = np.random.default_rng(num_features * 1_000_003 + num_bins)
    bins_np = rng.integers(
        0, num_bins, size=(n, num_features)).astype(
            np.uint8 if num_bins <= 256 else np.int32)
    K = split_batch

    candidates = [("xla", 8192), ("xla", 16384)]
    if on_tpu or jax.devices()[0].platform == "cpu":
        # pallas candidates run the interpreter off-TPU: slow but small n
        # keeps a CPU tune pass tractable, and the RELATIVE ranking is
        # what load mode consumes
        candidates += [("pallas2", 4096), ("pallas2", 8192)]
        if precision in _INT_STAT_DTYPES:
            candidates += [("fused", 4096), ("fused", 8192)]

    def _fit_block(block: int) -> int:
        # datasets smaller than a candidate block still deserve a
        # measured winner: clamp to the largest pow2 block the rows can
        # fill (floor 1024) instead of skipping — every candidate
        # skipping out used to raise 'no viable candidate' on any
        # dataset under the smallest block
        while block > 1024 and block > n:
            block //= 2
        return block

    seen = set()
    table = {}
    for impl, block in candidates:
        block = _fit_block(block)
        if n < block or (impl, block) in seen:
            continue
        seen.add((impl, block))
        if impl == "pallas2" and precision == "int16" and on_tpu \
                and not mosaic_int16_ok():
            continue  # probe already warned loudly
        if impl == "fused" and not fused_scan_ok(precision):
            continue
        try:
            bins_tb, stats, n_use = bench_hist_operands(
                bins_np, precision, block)
            nb = n_use // block
            leaf_b = jnp.asarray(
                rng.integers(0, K, size=n_use).astype(np.int32)
                .reshape(nb, block))
            slots = jnp.arange(K, dtype=jnp.int32)
            # graftlint: disable-next-line=J201 throwaway measurement probes on synthetic operands — deliberately off-ledger so tuning never perturbs n_programs gates
            fn = jax.jit(lambda b, s, l, i=impl: build_histogram_batched_t(
                b, s, l, slots, num_bins, precision, impl=i))
            # graftlint: disable-next-line=J201 probe warm-up (see above)
            jax.block_until_ready(fn(bins_tb, stats, leaf_b))  # compile
            t0 = time.perf_counter()
            for _ in range(_TUNE_REPS):
                # graftlint: disable-next-line=J201 probe timing loop (see above)
                jax.block_until_ready(fn(bins_tb, stats, leaf_b))
            rps = n_use * _TUNE_REPS / max(time.perf_counter() - t0, 1e-9)
            table[f"{impl}:{block}"] = rps
        except Exception as exc:
            LOG.warning("autotune candidate %s:%d failed: %s: %s", impl,
                        block, type(exc).__name__, exc)
    if not table:
        raise RuntimeError(
            f"autotune measured no viable candidate for "
            f"{n_rows}x{num_features} rows/features at {num_bins} bins")
    best = max(table, key=table.get)
    impl, block = best.split(":")
    return {
        "hist_impl": impl,
        "block_rows": int(block),
        "rows_per_sec": table[best],
        # the non-hist "auto" winners: recorded from the same measured
        # principles the heuristics encode (scatter beats psum whenever a
        # real data axis exists — PR-11's comm sweep; bucket policy
        # trades compile count for pad waste and stays fine by default)
        "hist_agg": ("scatter" if backend_fingerprint()["device_count"] > 1
                     else "psum"),
        "bucket_policy": "fine",
        "precision": precision,
        "table": table,
    }


def resolve_autotune(config, n_rows: int, num_features: int, num_bins: int,
                     precision: str) -> Optional[dict]:
    """The learner's one entry point: the profile entry for this shape
    bucket, or None (mode off / nothing measured).  load mode refuses
    stale profiles (AutotuneStaleProfile); tune mode measures and
    persists missing entries first."""
    mode = str(getattr(config, "tpu_autotune", "off"))
    if mode == "off":
        return None
    if mode not in ("load", "tune"):
        raise ValueError(f"tpu_autotune={mode!r}; expected off, load, "
                         "or tune")
    path = profile_path(config)
    prof = load_profile(path)
    if prof is not None:
        check_fingerprint(prof, path)
    bucket = shape_bucket(n_rows, num_features, num_bins)
    entry = (prof or {}).get("entries", {}).get(bucket)
    if entry is not None and str(entry.get("precision")) != precision:
        entry = None  # measured at another stats precision: re-tune
    if entry is None:
        if mode == "load":
            LOG.info("autotune: no profile entry for bucket %s at %r — "
                     "auto falls back to the built-in heuristics", bucket,
                     path)
            return None
        try:
            entry = tune_entry(n_rows, num_features, num_bins, precision)
        except RuntimeError as exc:
            # nothing measurable (e.g. a dataset below the smallest
            # candidate block): tuning must never kill a training run —
            # fall back to the heuristics, loudly, and persist nothing
            LOG.warning("autotune: %s — auto falls back to the built-in "
                        "heuristics", exc)
            return None
        prof = prof or {"version": PROFILE_VERSION,
                        **backend_fingerprint(), "entries": {}}
        prof["entries"][bucket] = entry
        save_profile(path, prof)
        LOG.info("autotune: measured bucket %s -> %s:%d (%.0f rows/s), "
                 "persisted to %r", bucket, entry["hist_impl"],
                 entry["block_rows"], entry["rows_per_sec"], path)
    return entry


__all__ = ["AutotuneStaleProfile", "PROFILE_VERSION", "backend_fingerprint",
           "check_fingerprint", "load_profile", "profile_path",
           "resolve_autotune", "save_profile", "shape_bucket",
           "tune_entry"]
