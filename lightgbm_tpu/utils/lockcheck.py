"""Instrumented-lock runtime checker: lock-order inversions,
hold-while-dispatching, and mutation-without-lock, caught under tests.

The graftlint concurrency rules (tools/graftlint) prove statically that
declared shared state is only mutated under its owning lock — but a
static lock-ownership map cannot see dynamic acquisition ORDER (the
deadlock ingredient) or a lock accidentally held across a device
dispatch (the serving latency ingredient: one wedged jit call would
stall every thread queued on that lock).  This module is the runtime
half of the same contract:

* **order graph** — every enabled acquire records the edge
  ``held-lock -> acquiring-lock`` into a process-global directed graph;
  an acquire whose reverse edge is already present is a lock-order
  inversion (two threads interleaving those call sites can deadlock)
  and records a violation naming both sites.
* **hold-while-dispatching** — dispatch sites (the serving batcher's
  runner call, ``ModelEntry.predict``'s device launch) call
  `check_dispatch(site)`; if the calling thread holds ANY instrumented
  lock at that moment, a violation records which one.  Device walls are
  unbounded from the host's point of view — nothing may be held across
  them.
* **mutation ownership** — `check_owned(lock)` asserts the calling
  thread currently holds `lock`; sprinkled next to guarded-state
  mutations (or used by tests hammering a structure) it catches the
  mutation-without-lock bug class the static map enforces by
  declaration.

Overhead discipline: the checker ships DISABLED.  A disabled
`InstrumentedLock.acquire` is one module-global flag load and a
delegated ``threading.Lock.acquire`` — the serving/obs hot paths that
create their locks through `make_lock` stay inside the telemetry
off-mode <1% gate (tests/test_telemetry.py extends its microbench with
a disabled lockcheck acquire/release to pin this).  `enable()` is for
tests and debugging sessions, never production serving.

Violations are RECORDED, not raised (default): a checker that throws
from inside ``acquire`` would turn a diagnosed bug into an undiagnosed
crash in whatever thread happened to trip it.  Tests read
`violations()`; `enable(strict=True)` opts into raising
`LockCheckError` at the detection site for pinpoint stack traces.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "InstrumentedLock", "LockCheckError", "check_dispatch", "check_owned",
    "enable", "enabled", "held_names", "make_lock", "make_rlock",
    "reset", "violations",
]


class LockCheckError(RuntimeError):
    """Raised at the detection site under enable(strict=True)."""


_enabled = False
_strict = False
_tls = threading.local()          # .held: List[InstrumentedLock]
_graph_lock = threading.Lock()    # guards _edges and _violations
# (id(before), id(after)) -> first site.  INSTANCE-keyed, not
# name-keyed: two ServingSessions share lock NAMES ("serving.stats"),
# and a name-keyed graph would both miss real A/B-vs-B/A inversions
# between the sessions' distinct locks and conflate orders across
# instances that can never deadlock.  (ids are only meaningful while
# the locks are alive — fine for a test-scoped checker; reset()
# between tests clears the graph.)
_edges: Dict[Tuple[int, int], str] = {}
_edge_refs: List = []             # keeps edge locks alive: no id reuse
_violations: List[Dict] = []


def enable(on: bool = True, strict: bool = False) -> None:
    """Arm/disarm the checker process-wide (tests only — see module
    docstring for the overhead contract)."""
    global _enabled, _strict
    _enabled = bool(on)
    _strict = bool(strict)


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Clear the order graph and recorded violations (enabled state and
    existing locks persist)."""
    with _graph_lock:
        _edges.clear()
        del _edge_refs[:]
        del _violations[:]


def violations() -> List[Dict]:
    with _graph_lock:
        return list(_violations)


def _held() -> List["InstrumentedLock"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def held_names() -> List[str]:
    """Names of instrumented locks the CALLING thread holds, in
    acquisition order."""
    return [lk.name for lk in _held()]


def _site() -> str:
    """Compact caller site (file:line of the frame outside this
    module) for violation records.  Basename EQUALITY, not endswith:
    'test_lockcheck.py'.endswith('lockcheck.py') is True, and skipping
    the checker's own test file would name a pytest frame instead of
    the violating line."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        base = frame.filename.rsplit("/", 1)[-1]
        if base != "lockcheck.py":
            return f"{base}:{frame.lineno}"
    return "?"


def _record(kind: str, detail: str) -> None:
    rec = {"kind": kind, "detail": detail, "site": _site(),
           "thread": threading.current_thread().name}
    with _graph_lock:
        _violations.append(rec)
    if _strict:
        raise LockCheckError(f"{kind}: {detail} at {rec['site']}")


class InstrumentedLock:
    """threading.Lock/RLock plus order-graph and ownership tracking.

    Transparent where it matters: ``with``-statement protocol,
    acquire/release signatures, and `locked()` all delegate.  NOT a
    drop-in for ``threading.Condition(lock)`` — Condition pokes at
    private lock internals; keep Condition-paired locks plain (the
    static graftlint map still covers their guarded state)."""

    __slots__ = ("_lock", "name", "_reentrant", "_owner", "_depth")

    def __init__(self, name: str, reentrant: bool = False):
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self.name = str(name)
        self._reentrant = bool(reentrant)
        self._owner: Optional[int] = None
        self._depth = 0

    # -- core protocol --------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._lock.acquire(blocking, timeout)
        me = threading.get_ident()
        reacquire = self._reentrant and self._owner == me
        pending = []
        if not reacquire:
            # inversion DETECTION runs before blocking (strict mode must
            # fire before a real deadlock hangs us); edge RECORDING waits
            # for acquire success — a failed trylock (the deliberate
            # trylock-with-backoff deadlock-avoidance pattern) must not
            # poison the graph with an order that never held a lock
            for h in _held():
                if h is self:
                    continue
                rev = (id(self), id(h))
                with _graph_lock:
                    first = _edges.get(rev)
                if first is not None:
                    _record("lock-order-inversion",
                            f"acquiring {self.name!r} while holding "
                            f"{h.name!r}, but the opposite order was "
                            f"taken at {first}")
                else:
                    pending.append(h)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if reacquire:
                self._depth += 1
            else:
                if pending:
                    site = _site()
                    with _graph_lock:
                        for h in pending:
                            edge = (id(h), id(self))
                            if edge not in _edges:
                                _edges[edge] = site
                                _edge_refs.append((h, self))
                self._owner = me
                self._depth = 1
                _held().append(self)
        return ok

    def release(self) -> None:
        # ownership cleanup runs whenever WE hold tracking state — even
        # if the checker was disabled mid-critical-section — or a stale
        # held entry would poison later check_dispatch/check_owned
        # calls on this thread.  The disabled steady state costs one
        # None check (owner is never set while disabled).
        if self._owner is not None and \
                self._owner == threading.get_ident():
            self._depth -= 1
            if self._depth <= 0:
                self._owner = None
                held = _held()
                if self in held:
                    held.remove(self)
        self._lock.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        try:
            return self._lock.locked()
        except AttributeError:  # RLock before 3.14 has no locked()
            return self._owner is not None

    # -- checker surface ------------------------------------------------
    def owned(self) -> bool:
        """Does the CALLING thread hold this lock?  Only meaningful
        while the checker is enabled (ownership is not tracked on the
        disabled fast path)."""
        return self._owner == threading.get_ident()


def make_lock(name: str) -> InstrumentedLock:
    """The lock constructor serving/obs use instead of a bare
    ``threading.Lock()``: instrumented, but one flag check from free
    while the checker is disabled (the default)."""
    return InstrumentedLock(name)


def make_rlock(name: str) -> InstrumentedLock:
    return InstrumentedLock(name, reentrant=True)


def check_owned(lock: InstrumentedLock, what: str = "") -> None:
    """Record a violation when the calling thread mutates guarded state
    without holding its owning lock.  No-op while disabled."""
    if not _enabled:
        return
    if not isinstance(lock, InstrumentedLock) or not lock.owned():
        name = getattr(lock, "name", "?")
        _record("mutation-without-lock",
                f"{what or 'guarded state'} mutated without holding "
                f"{name!r}")


def check_dispatch(site: str) -> None:
    """Record a violation when a device-dispatch site runs with ANY
    instrumented lock held (a wedged device wall would stall every
    thread queued on it).  No-op while disabled."""
    if not _enabled:
        return
    held = held_names()
    if held:
        _record("hold-while-dispatching",
                f"dispatch site {site!r} entered holding {held}")
