from .backend import pin_cpu_backend, probe_default_backend  # noqa: F401
from .log import Log  # noqa: F401
