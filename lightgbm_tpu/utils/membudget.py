"""Device-memory budgeting: preflight planning, OOM classification, and
the deterministic degradation ladder (ISSUE 15).

Until now HBM exhaustion was an unclassified ``XlaRuntimeError`` that
killed a run outright: no preflight warning, no recovery, no named
postmortem.  PR 12 made memory *observable* (per-program
``memory_analysis()`` on the CompileLedger, per-phase peak watermarks,
per-model HBM gauges); this module makes it an *enforced, recoverable
contract*:

* **classification** — `is_oom_error` recognizes the
  ``RESOURCE_EXHAUSTED`` / out-of-memory shapes jax surfaces
  (``XlaRuntimeError`` text is the only stable signal across jaxlib
  versions), and `oom_guard(site)` wraps every guarded device site so
  an allocation failure re-raises as a structured `DeviceOutOfMemory`
  naming the site — counted (``lgbm_oom_events_total{site=}``), noted
  in the flight recorder WITH a device-memory snapshot, and ready for
  the recovery machinery above it.  The guard also hosts the
  ``device_alloc`` fault-injection point (`utils/faultline.py`), whose
  ``oom`` action raises a realistic RESOURCE_EXHAUSTED-shaped error —
  chaos tests exercise exactly the classification path real OOMs take.
* **budget** — `budget_bytes(config)` resolves the enforced HBM budget:
  explicit ``tpu_hbm_budget_bytes``, else device capacity
  (``memory_stats()['bytes_limit']``) scaled by ``tpu_hbm_budget_frac``;
  None on backends that report nothing (CPU) — a missing number is
  never invented.  `serving_budget_bytes` is the serving twin
  (``serving_hbm_budget_bytes``, falling back to the training budget).
* **preflight planning** — `plan_training` itemizes the predictable HBM
  consumers from closed-form buffer models anchored to the LIVE learner
  buffers (binned matrix, the [L, G/P, B, 3] histogram pool, stats
  planes, score + donation buffers, packed forest, ingest/predict chunk
  scratch) into a `MemoryPlan` that either fits the budget or carries a
  named, itemized refusal table.  `ledger_cross_check` compares the
  plan against the CompileLedger's independent ``memory_analysis()``
  oracle where one exists.  `plan_model_load` is the serving-side twin:
  predicted packed-table + launch-scratch bytes BEFORE any upload, so
  the registry can refuse (HTTP 507) instead of warming into a crash.
* **degradation ladder** — `DegradationLadder` owns the deterministic,
  logged retry sequence a mid-train OOM descends after the PR-7
  iteration rollback: (1) halve ``tpu_ingest_chunk_rows`` /
  ``tpu_predict_chunk_rows`` (floor 4096), (2) switch
  ``tpu_hist_agg=psum`` -> ``scatter`` (the ~P x per-shard pool
  reduction, PR 5), (3) drop ``tpu_bucket_policy=wide`` -> ``fine``
  (smaller launch/ramp shapes, PR 6).  Every step is BITWISE-INVISIBLE
  — each knob is already proven to leave model bytes unchanged — so a
  run that settles after k steps produces a model file byte-identical
  to an undisturbed run at the settled configuration.  Exhaustion is a
  structured `MemoryLadderExhausted` that rides the existing
  final-checkpoint-flush + blackbox-dump path.

Nothing here ever forces a backend init, and classification never
swallows a non-OOM error: a ValueError stays a ValueError.
"""

from __future__ import annotations

import contextlib
import math
import re
from typing import Any, Dict, List, Optional, Tuple

from . import faultline

#: the guarded device sites `oom_guard` may name — one vocabulary shared
#: by the classifier, the metrics labels, the flight recorder, and the
#: chaos tests (the `device_alloc` faultline point fires at each)
OOM_SITES = ("train_step", "ingest_chunk", "predict_chunk",
             "score_replay", "registry_load", "registry_warmup",
             "serve_dispatch")

#: deterministic ladder floors: chunk shrinking never goes below these
#: (4096 rows is the smallest launch bucket the wide policy emits; the
#: binning kernel's own minimum is far lower and never the binding one)
CHUNK_FLOOR = 4096

#: ladder step vocabulary, in descent order; the final rung trades the
#: device-resident binned matrix for the streamed layout (ops/stream.py)
#: instead of raising MemoryLadderExhausted — slower, but the run
#: completes (and stays bitwise for int8/int16 precisions)
LADDER_STEPS = ("shrink_chunk_rows", "hist_agg_scatter", "fused_unfuse",
                "bucket_policy_fine", "stream_layout")

_OOM_RE = re.compile(
    r"RESOURCE[ _]EXHAUSTED|out of memory|"
    r"failed to allocate|allocation (failure|failed)|"
    r"exceeds the memory capacity|insufficient memory",
    re.IGNORECASE)
# the bare acronym only as an upper-case whole word: a case-insensitive
# unanchored "OOM" would classify "no room left" / "zoom level" errors
_OOM_WORD_RE = re.compile(r"\bOOM\b")

#: exception TYPE names that may carry an OOM (jaxlib's runtime error
#: class moved modules across versions; the NAME is the stable part)
_RUNTIME_ERROR_NAMES = ("XlaRuntimeError", "JaxRuntimeError",
                        "RuntimeError", "InternalError",
                        "ResourceExhaustedError")


class DeviceOutOfMemory(RuntimeError):
    """A device allocation failure, classified and named.

    Carries the guarded `site` it surfaced at plus any diagnostics the
    site attached; `__cause__` is the raw backend error."""

    def __init__(self, message: str, site: str = "unknown",
                 info: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.site = str(site)
        self.info = dict(info or {})


class MemoryLadderExhausted(DeviceOutOfMemory):
    """The degradation ladder ran out of bitwise-invisible steps.

    Raised after the failed iteration was rolled back, so the booster
    stays usable; `engine.train` flushes a final checkpoint and the
    flight recorder dumps the blackbox (with the memory snapshot) on
    the way out."""


class ServingMemoryExhausted(DeviceOutOfMemory):
    """A model load the serving HBM budget cannot admit (HTTP 507):
    the registry refused BEFORE uploading (or after eviction could not
    free enough), with the itemized plan in the message."""

    http_status = 507


def is_oom_error(exc: BaseException) -> bool:
    """Is `exc` a device out-of-memory?  Already-classified errors pass
    through; raw backend errors classify on the RESOURCE_EXHAUSTED /
    out-of-memory message shapes — jaxlib's error TYPES move between
    modules across versions, so the text is the stable signal.  A
    generic `faultline.FaultInjected` (the plain ``raise`` action)
    never classifies: only the ``oom`` action's realistic error does."""
    if isinstance(exc, DeviceOutOfMemory):
        return True
    if isinstance(exc, faultline.FaultInjected):
        return False
    if type(exc).__name__ not in _RUNTIME_ERROR_NAMES \
            and not isinstance(exc, (RuntimeError, MemoryError)):
        return False
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return bool(_OOM_RE.search(msg) or _OOM_WORD_RE.search(msg))


def memory_snapshot() -> Dict[str, Optional[int]]:
    """Best-effort device-memory numbers for diagnostics (all None on
    CPU): what the blackbox and the structured errors carry."""
    from ..obs import resources

    return {"hbm_bytes_in_use": resources.hbm_bytes_in_use(),
            "hbm_peak_bytes": resources.peak_hbm_bytes(),
            "hbm_capacity_bytes": device_capacity_bytes()}


def note_oom(site: str, exc: Optional[BaseException] = None,
             **info) -> None:
    """Record one classified OOM: counter + flight-recorder entry with
    the device-memory snapshot (the postmortem's first question is
    'how full was HBM' — answer it in the ring, not in a log grep)."""
    from ..obs import REGISTRY, flightrecorder

    REGISTRY.inc("lgbm_oom_events_total", site=str(site),
                 help="classified device out-of-memory errors per "
                      "guarded site")
    snap = {k: v for k, v in memory_snapshot().items() if v is not None}
    flightrecorder.note("oom", "device_oom", site=str(site),
                        error=(str(exc)[:160] if exc is not None else None),
                        **snap, **{k: str(v) for k, v in info.items()})


@contextlib.contextmanager
def oom_guard(site: str, **info):
    """Guard one device site: hosts the ``device_alloc`` fault point
    and re-raises any classified allocation failure as a structured
    `DeviceOutOfMemory` naming the site.  Non-OOM errors pass through
    untouched — classification must never mask a data error."""
    try:
        faultline.fire("device_alloc", site=site, **info)
        yield
    except DeviceOutOfMemory:
        raise  # already classified at an inner site: keep its name
    except Exception as exc:
        if not is_oom_error(exc):
            raise
        note_oom(site, exc, **info)
        raise DeviceOutOfMemory(
            f"device out of memory at {site!r}: {str(exc)[:200]}",
            site=site, info=info) from exc


# ---------------------------------------------------------------------------
# budget resolution
# ---------------------------------------------------------------------------
#: one-shot capacity memo ([] = not yet known): capacity is static per
#: process, and re-querying every device's memory_stats() on every
#: /healthz probe or locked eviction path would pay device round-trips
#: to re-derive a constant.  Only a DEFINITIVE answer is cached — an
#: empty device list (jax not initialized yet) stays uncached so the
#: first post-init call resolves correctly.
_capacity_memo: List[Optional[int]] = []


def device_capacity_bytes() -> Optional[int]:
    """Smallest per-device HBM capacity across reporting devices
    (``bytes_limit`` / ``bytes_reservable_limit``), or None (CPU).
    The MINIMUM is the binding constraint for replicated buffers."""
    if _capacity_memo:
        return _capacity_memo[0]
    from ..obs import resources

    if not resources._devices():
        return None  # backend not up: answer unknown, do NOT pin it
    vals: List[int] = []
    for s in resources.all_device_memory_stats():
        if s is None:
            continue
        v = s.get("bytes_limit", s.get("bytes_reservable_limit"))
        if v:
            vals.append(int(v))
    cap = min(vals) if vals else None
    _capacity_memo.append(cap)
    return cap


def budget_bytes(config) -> Optional[int]:
    """The enforced training HBM budget: ``tpu_hbm_budget_bytes`` when
    explicitly set, else device capacity x ``tpu_hbm_budget_frac``;
    None when neither resolves (no explicit bytes AND a non-reporting
    backend) — an explicit budget is honored even on CPU so the whole
    planner/ladder surface is testable anywhere."""
    explicit = int(config.get("tpu_hbm_budget_bytes", 0) or 0)
    if explicit > 0:
        return explicit
    cap = device_capacity_bytes()
    if cap is None:
        return None
    frac = float(config.get("tpu_hbm_budget_frac", 0.9) or 0.9)
    return int(cap * max(min(frac, 1.0), 0.01))


def serving_budget_bytes(config) -> Optional[int]:
    """The serving-registry HBM budget (packed model tables + launch
    scratch): ``serving_hbm_budget_bytes`` when set, else the training
    budget resolution above."""
    explicit = int(config.get("serving_hbm_budget_bytes", 0) or 0)
    if explicit > 0:
        return explicit
    return budget_bytes(config)


def publish_budget_gauge(budget: Optional[int], scope: str) -> None:
    """Expose the resolved budget as `lgbm_hbm_budget_bytes{scope=}`
    (nothing is published when no budget resolves — no fictional 0)."""
    if budget is None:
        return
    from ..obs import REGISTRY

    REGISTRY.set_gauge("lgbm_hbm_budget_bytes", int(budget),
                       help="enforced device-memory budget "
                            "(tpu_hbm_budget_* / serving_hbm_*)",
                       scope=str(scope))


# ---------------------------------------------------------------------------
# preflight planning
# ---------------------------------------------------------------------------
class MemoryPlan:
    """An itemized HBM prediction vs a budget.

    `fits` is True/False against a resolved budget, None when no budget
    exists (nothing to enforce).  `format_table()` renders the named
    itemization every refusal and every ``perf_probe mem`` read."""

    def __init__(self, components: Dict[str, int],
                 budget: Optional[int], scope: str):
        self.components = {k: int(v) for k, v in components.items()}
        self.budget = None if budget is None else int(budget)
        self.scope = str(scope)

    @property
    def total(self) -> int:
        return sum(self.components.values())

    @property
    def headroom(self) -> Optional[int]:
        return None if self.budget is None else self.budget - self.total

    @property
    def fits(self) -> Optional[bool]:
        return None if self.budget is None else self.total <= self.budget

    def format_table(self) -> str:
        width = max([len(k) for k in self.components] + [10])
        lines = [f"{'component':<{width}s} {'bytes':>14s}"]
        for name, b in sorted(self.components.items(),
                              key=lambda kv: -kv[1]):
            lines.append(f"{name:<{width}s} {b:>14,d}")
        lines.append(f"{'TOTAL':<{width}s} {self.total:>14,d}")
        if self.budget is not None:
            lines.append(f"{'budget':<{width}s} {self.budget:>14,d}")
            lines.append(f"{'headroom':<{width}s} {self.headroom:>14,d}")
        return "\n".join(lines)

    def refuse_message(self, what: str) -> str:
        return (f"{what} needs a predicted {self.total:,d} device bytes "
                f"against a {self.budget:,d}-byte {self.scope} HBM "
                f"budget (headroom {self.headroom:,d}); itemized plan:\n"
                f"{self.format_table()}")

    def to_dict(self) -> Dict:
        return {"components": dict(self.components), "total": self.total,
                "budget": self.budget, "headroom": self.headroom,
                "fits": self.fits, "scope": self.scope}


#: stats-plane layout per histogram precision: (rows, itemsize bytes)
#: — pack_stats emits [5, n] bf16 for hilo, [3, n] otherwise
#: (ops/histogram.py)
_STATS_PLANES = {"hilo": (5, 2), "bf16": (3, 2), "f32": (3, 4),
                 "f64": (3, 8), "int8": (3, 1), "int16": (3, 2)}


def _pool_bytes(learner, config) -> int:
    """The [L, G/P, B, 3] histogram pool's PER-DEVICE bytes.  Anchored
    to the live donated buffer when one exists (exact); the scatter
    aggregation leaves each data shard only its 1/P column slice."""
    pool = getattr(learner, "_pool", None)
    spec = getattr(learner, "_pool_spec", None)
    if pool is not None:
        total = int(pool.nbytes)
    elif spec is not None:
        shape, pdt, _sh = spec
        total = int(math.prod(shape)) * pdt.itemsize
    else:
        # pool lives inside the grow program (donation off / voting):
        # same closed form, from the learner's own padded axes
        from ..ops.grower import pool_dtype

        import jax.numpy as jnp

        L = int(learner.params.num_leaves)
        B = int(learner.meta_np["num_bin"].max()) if hasattr(
            learner, "meta_np") else 256
        total = (L * int(getattr(learner, "g_pad", 1)) * B * 3
                 * jnp.dtype(pool_dtype(learner.params.precision)).itemsize)
    d = max(int(getattr(learner, "d_shards", 1)), 1)
    agg = str(config.get("tpu_hist_agg", "auto") or "auto")
    eff = getattr(learner, "hist_agg", "psum")
    # a not-yet-applied scatter override still shrinks the PLAN — the
    # degrade preflight iterates config overrides before any rebuild
    scatter = (eff == "scatter") or (agg == "scatter" and d > 1)
    return total // (d if scatter and d > 1 else 1)


def packed_forest_bytes(num_trees: int, num_leaves: int) -> int:
    """Closed-form packed-forest table bytes (ops/predict.pack_trees):
    7 int32 node columns of width L-1, the [T, L] f32 leaf values, the
    init-node column, plus the (tiny) shared bitset pool word."""
    L = max(int(num_leaves), 2)
    per_tree = 7 * (L - 1) * 4 + L * 4 + 4
    return max(int(num_trees), 0) * per_tree + 4


def stream_config_blockers(config) -> List[str]:
    """Config-visible reasons the streamed layout (ops/stream.py) cannot
    serve this run — shared by the auto layout selection and the OOM
    ladder's final rung, so neither proposes a layout the streamed
    learner would reject at construction.  Dataset-derived blockers
    (categorical columns discovered by auto detection) are caught by
    select_layout when train_data is in hand, and loudly by the learner
    otherwise."""
    reasons = []
    try:
        from ..parallel.strategies import resolve_tree_learner

        strategy = resolve_tree_learner(
            str(config.get("tree_learner", "serial")))
    except Exception:
        strategy = str(config.get("tree_learner", "serial"))
    if strategy != "serial":
        reasons.append(f"tree_learner={strategy}")
    if float(config.get("tpu_sparse_threshold", 0.0) or 0.0) > 0.0:
        reasons.append("sparse COO storage (tpu_sparse_threshold)")
    if str(config.get("forcedsplits_filename", "") or ""):
        reasons.append("forced splits")
    if float(config.get("feature_fraction_bynode", 1.0) or 1.0) < 1.0:
        reasons.append("feature_fraction_bynode")
    coupled = [float(v) for v in
               config.get("cegb_penalty_feature_coupled", []) or []]
    lazy = [float(v) for v in
            config.get("cegb_penalty_feature_lazy", []) or []]
    if (any(v != 0.0 for v in coupled) or any(v != 0.0 for v in lazy)
            or float(config.get("cegb_penalty_split", 0.0) or 0.0) != 0.0):
        reasons.append("CEGB penalties")
    if str(config.get("categorical_feature", "") or ""):
        reasons.append("categorical features")
    return reasons


def select_layout(config, train_data=None) -> str:
    """Resolve ``tpu_stream_mode`` to the concrete training layout:
    "resident" or "streamed".

    Explicit modes are honored as-is (a streamed pin that the streamed
    learner cannot serve raises there, loudly).  auto keeps the classic
    resident layout unless (a) the run is streamable and (b) the
    closed-form binned-matrix estimate would eat more than half the
    enforced HBM budget — the matrix is the dominant resident and the
    plan's other components (pool, stats planes, scores, scratch) need
    the rest."""
    mode = str(config.get("tpu_stream_mode", "auto") or "auto").lower()
    if mode == "streamed":
        return "streamed"
    if mode == "resident":
        return "resident"
    if mode != "auto":
        raise ValueError("tpu_stream_mode must be auto|resident|streamed,"
                         f" got {mode!r}")
    if stream_config_blockers(config):
        return "resident"
    budget = budget_bytes(config)
    if budget is None or train_data is None:
        return "resident"
    try:
        if train_data.feature_arrays()["is_categorical"].any():
            return "resident"
        n = int(train_data.num_data)
        F = int(train_data.num_features)
        item = 1 if int(train_data.feature_arrays()["num_bin"].max()) \
            <= 256 else 4
    except Exception:
        return "resident"
    if n * F * item > budget // 2:
        return "streamed"
    return "resident"


def plan_training(config, learner, num_class: int) -> MemoryPlan:
    """Itemized pre-iteration-0 HBM prediction for one training run,
    anchored to the LIVE learner buffers where they exist (the binned
    matrix and donated pool components are exact — the planner-vs-array
    tests pin that) and closed-form elsewhere."""
    d = max(int(getattr(learner, "d_shards", 1)), 1)
    n_pad = int(getattr(learner, "n_pad", 0))
    k = max(int(num_class), 1)
    comps: Dict[str, int] = {}
    bins_t = getattr(learner, "bins_t", None)
    streamed = (bool(getattr(learner, "stream_layout", False))
                or str(config.get("tpu_stream_mode", "auto")) == "streamed")
    if streamed:
        # streamed layout: the matrix stays host-resident; the device
        # cost is TWO double-buffered block slots.  Live host blocks are
        # exact; a pending rebuild into streamed (the ladder's final
        # rung re-plans BEFORE the learner is reconstructed) estimates
        # the slot closed-form from the same sizing rule the learner
        # will use
        blocks = getattr(learner, "_host_blocks", None)
        if blocks:
            slot = max(int(b.nbytes) for b in blocks)
        else:
            from ..ops.stream import resolve_stream_rows

            per_row = (int(bins_t.nbytes) // max(n_pad, 1)
                       if bins_t is not None
                       else max(int(getattr(learner, "g_pad", 1)), 1))
            rows = resolve_stream_rows(
                int(config.get("tpu_stream_block_rows", 0) or 0), n_pad,
                per_row,
                int(config.get("tpu_block_rows", 0) or 0) or 16384,
                budget_bytes(config))
            slot = rows * per_row
        comps["stream_slots"] = 2 * slot
    elif bins_t is not None:
        comps["binned_matrix"] = int(bins_t.nbytes) // d
    comps["histogram_pool"] = _pool_bytes(learner, config)
    precision = str(getattr(learner.params, "precision", "hilo"))
    planes, item = _STATS_PLANES.get(precision, (3, 4))
    comps["stats_planes"] = planes * n_pad * item // d
    n_rows = int(getattr(learner, "n", n_pad))
    # live scores + the pre-donation copy the fused step snapshots
    donate = 2 if getattr(learner, "_donate", False) else 1
    comps["score_buffers"] = k * n_rows * 4 * donate
    # row -> leaf partition state ([n] int32 per class pass)
    comps["row_partition"] = n_pad * 4 // d
    # packed forest for score replay / valid updates over the full run
    comps["packed_forest"] = packed_forest_bytes(
        int(config.get("num_iterations", 100)) * k,
        int(config.get("num_leaves", 31)))
    F = int(getattr(learner, "num_features", 0)) or 1
    if str(getattr(learner.params, "hist_impl", "xla")) == "fused":
        # fused frontier (ops/fused.py): the device split-record buffer
        # ([2K, F, PF_RECORD_WIDTH] f32) plus the flattened parent-hist
        # operand the kernel streams alongside the accumulator ([F*Bp,
        # K*S] int32) — the in-kernel scan scratch itself is VMEM, not
        # HBM, so these two HBM-visible pieces are the whole delta
        kf = max(int(getattr(learner.params, "split_batch", 16)), 1)
        bf = -(-int(getattr(learner.params, "num_bins", 256)) // 8) * 8
        g_pad = int(getattr(learner, "g_pad", F)) or F
        comps["fused_records"] = 2 * kf * g_pad * 8 * 4
        comps["fused_parent_hist"] = g_pad * bf * kf * 3 * 4
    if str(config.get("tpu_autotune", "off")) != "off":
        # autotune probe scratch (utils/autotune.tune_entry): synthetic
        # bins + packed stats + one probe histogram, capped tune rows
        comps["autotune_scratch"] = min(n_pad or 131072, 131072) * (F + 16)
    # chunked ingest scratch: (hi, lo) key planes + the out matrix
    ingest_chunk = int(config.get("tpu_ingest_chunk_rows", 65536))
    comps["ingest_scratch"] = ingest_chunk * F * 9
    # chunked predict scratch: [chunk, F] int32 bins + [k, chunk] f32
    predict_chunk = int(config.get("tpu_predict_chunk_rows", 65536))
    comps["predict_scratch"] = predict_chunk * (F * 4 + k * 4)
    return MemoryPlan(comps, budget_bytes(config), "training")


def plan_model_load(booster, config) -> Optional[MemoryPlan]:
    """Serving-side preflight: predicted device bytes of loading one
    model — packed table bytes from the HOST pack (nothing uploaded
    yet) plus the per-launch bins/score scratch of the largest warmed
    bucket.  None when the model has no device path to plan."""
    from ..config import parse_tristate

    drv = booster._driver
    drv._materialize()
    if drv._pred_context() is None or booster.num_trees() == 0:
        return None
    # an explicit tpu_predict_device=false stays a walker-only entry
    # (ModelEntry.device_on mirrors this): it uploads nothing, so
    # planning packed bytes for it would refuse — and evict real
    # device-backed models for — a load that costs zero HBM
    if parse_tristate(booster.params.get("tpu_predict_device",
                                         "auto")) == "false":
        return None
    pf = drv._packed_forest()       # host pack only; upload is lazy
    host = pf._host or {}
    count = pf._count
    # quantized serving tables (ISSUE 19): price what will actually
    # land on each device — the preflight and the registry's post-load
    # accounting must agree, or a bf16/int16 load would be refused
    # against its f32 size
    precision = str(config.get("serving_table_precision", "f32"))
    if precision != "f32" and host:
        from ..ops.predict import quantize_tables

        host = quantize_tables(
            {k: (v if k == "cat_words" else v[:count])
             for k, v in host.items()}, precision)
        count = -1  # already sliced above
    table_bytes = 0
    for key, arr in host.items():
        view = arr if (key == "cat_words" or count < 0) else arr[:count]
        table_bytes += int(view.nbytes)
    comps = {"packed_tables": table_bytes}
    chunk = drv.predict_chunk_rows()
    rows = min(int(config.get("serving_max_batch_rows", 4096)), chunk)
    F = int(booster.num_feature())
    k = max(int(drv.num_tree_per_iteration), 1)
    comps["launch_scratch"] = rows * (F * 4 + k * 4)
    return MemoryPlan(comps, serving_budget_bytes(config), "serving")


def ledger_cross_check(plan: MemoryPlan, site: str = "grower"
                       ) -> Optional[Dict]:
    """Cross-check the plan against the CompileLedger's independent
    ``memory_analysis()`` oracle (ISSUE 12): the largest captured
    program whose site contains `site` must have argument bytes no
    larger than the plan total plus slack (XLA counts the same buffers
    from the other side).  Returns the comparison dict, or None when no
    analyzed program exists (capture off / nothing compiled)."""
    from .compile_ledger import LEDGER

    rows = [r for r in LEDGER.cost_table(memory=True)
            if site in r["site"] and r.get("argument_bytes") is not None]
    if not rows:
        return None
    biggest = max(rows, key=lambda r: r["argument_bytes"])
    return {"site": biggest["site"],
            "ledger_argument_bytes": int(biggest["argument_bytes"]),
            "ledger_temp_bytes": biggest.get("temp_bytes"),
            "plan_total": plan.total,
            "covered": plan.total >= int(biggest["argument_bytes"])}


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------
class DegradationLadder:
    """The deterministic, logged descent a classified OOM retries down.

    `next_step(config)` returns ``(step_name, param_overrides)`` for
    the next applicable step — or None when exhausted.  The order is
    fixed (chunk shrink to the floor, then the scatter aggregation
    switch, then unfusing the frontier megakernel, then the fine bucket
    policy) so two runs hitting OOM at the
    same point settle at the SAME configuration; every knob is
    bitwise-invisible to model bytes (PRs 3/5/6 prove each), which is
    what makes the settled model byte-identical to an undisturbed run
    at the settled config."""

    def __init__(self):
        self.steps_taken: List[Tuple[str, Dict[str, Any]]] = []

    def next_step(self, config) -> Optional[Tuple[str, Dict[str, Any]]]:
        step = self._propose(config)
        if step is not None:
            self.steps_taken.append(step)
        return step

    def _propose(self, config) -> Optional[Tuple[str, Dict[str, Any]]]:
        ingest = int(config.get("tpu_ingest_chunk_rows", 65536))
        predict = int(config.get("tpu_predict_chunk_rows", 65536))
        overrides: Dict[str, Any] = {}
        if ingest > CHUNK_FLOOR:
            overrides["tpu_ingest_chunk_rows"] = max(ingest // 2,
                                                     CHUNK_FLOOR)
        if predict > CHUNK_FLOOR:
            overrides["tpu_predict_chunk_rows"] = max(predict // 2,
                                                      CHUNK_FLOOR)
        if overrides:
            return "shrink_chunk_rows", overrides
        learner_kind = str(config.get("tree_learner", "serial"))
        sharded = (learner_kind in ("data", "data_parallel", "voting",
                                    "voting_parallel", "data_feature",
                                    "feature_data",
                                    "data_feature_parallel")
                   and int(config.get("num_machines", 1)) > 1)
        if sharded and str(config.get("tpu_hist_agg", "auto")) == "psum":
            # 'auto' already resolves to scatter on a real data axis —
            # only an explicit psum pin has this step to give
            return "hist_agg_scatter", {"tpu_hist_agg": "scatter"}
        if str(config.get("tpu_hist_impl", "auto")) == "fused":
            # the fused frontier kernel carries the device split-record
            # buffers and a wider VMEM working set than the plain
            # perfeature contraction; unfusing to pallas2 + the host
            # select() is bitwise-invisible (tests/test_fused_grow.py
            # pins fused == unfused model bytes), so it is a legitimate
            # ladder rung.  Only an explicit fused pin descends here —
            # "auto" re-resolves per backend and never needs unpinning
            return "fused_unfuse", {"tpu_hist_impl": "pallas2"}
        if str(config.get("tpu_bucket_policy", "wide")) == "wide":
            return "bucket_policy_fine", {"tpu_bucket_policy": "fine"}
        # the last rung: give up device residency of the binned matrix
        # and stream it from host RAM (ops/stream.py).  Only under
        # tpu_stream_mode=auto (an explicit resident pin — or an
        # already-streamed run — has nothing left to give) and only when
        # the configuration is streamable; NOT bitwise-invisible for
        # float histogram precisions (the int precisions stay bitwise —
        # int32 block sums are associative)
        if (str(config.get("tpu_stream_mode", "auto")) == "auto"
                and not stream_config_blockers(config)):
            return "stream_layout", {"tpu_stream_mode": "streamed"}
        return None

    def describe(self) -> List[str]:
        return [name for name, _ in self.steps_taken]


def note_ladder_step(site: str, step: str, overrides: Dict[str, Any],
                     recovery: bool = True) -> None:
    """One ladder descent: counters + a flight-recorder transition (the
    blackbox of a struggling run shows every step it took).

    recovery=False (preflight degrade) counts only the step — no OOM
    happened, so the recoveries counter (documented as rollback-and-
    retry events) must not tick."""
    from ..obs import REGISTRY, flightrecorder

    if recovery:
        REGISTRY.inc("lgbm_oom_recoveries_total", site=str(site),
                     help="OOM recoveries: rollbacks that descended "
                          "the degradation ladder and retried")
    REGISTRY.inc("lgbm_oom_ladder_steps_total", step=str(step),
                 help="degradation-ladder steps taken, by step name")
    flightrecorder.note("oom", "ladder_step", site=str(site), step=step,
                        **{k: str(v) for k, v in overrides.items()})
