"""Phase timers — the TIMETAG subsystem analog.

The reference accumulates per-phase wall time behind a compile-time flag
(reference src/treelearner/serial_tree_learner.cpp:21-48 init/hist/
find-split/split buckets, gpu_tree_learner.cpp:352-532 transfer timing,
linkers.h:169 network_time_).  Here timing is always compiled in and
gated by an env var at runtime, and device phases can additionally be
captured with jax.profiler traces:

* `PHASE("binning")` context blocks accumulate wall time per named phase;
* `print_summary()` (atexit when LIGHTGBM_TPU_TIMETAG=1) prints the
  table, like the reference's Log::Info TIMETAG dumps;
* `trace(dir)` wraps a block in jax.profiler.trace for xprof/tensorboard
  inspection of the on-device schedule.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

from .log import Log

_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)
_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


@contextlib.contextmanager
def PHASE(name: str) -> Iterator[None]:
    """Accumulate wall time under `name` (no-op unless enabled)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _acc[name] += time.perf_counter() - t0
        _cnt[name] += 1


def add(name: str, seconds: float) -> None:
    if _enabled:
        _acc[name] += seconds
        _cnt[name] += 1


def summary() -> Dict[str, float]:
    return dict(_acc)


def reset() -> None:
    _acc.clear()
    _cnt.clear()


def print_summary() -> None:
    if not _acc:
        return
    width = max(len(k) for k in _acc)
    Log.info("phase timings:")
    for name, secs in sorted(_acc.items(), key=lambda kv: -kv[1]):
        Log.info(f"  {name:<{width}}  {secs:9.3f}s  x{_cnt[name]}")


if _enabled:
    atexit.register(print_summary)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/lightgbm_tpu_trace") -> Iterator[None]:
    """jax.profiler trace around a block (view with xprof/tensorboard)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
