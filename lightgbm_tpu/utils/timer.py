"""Phase timers — the TIMETAG subsystem analog, backed by the registry.

The reference accumulates per-phase wall time behind a compile-time flag
(reference src/treelearner/serial_tree_learner.cpp:21-48 init/hist/
find-split/split buckets, gpu_tree_learner.cpp:352-532 transfer timing,
linkers.h:169 network_time_).  Here every `PHASE` block feeds the
unified telemetry layer (`lightgbm_tpu.obs`):

* phase walls accumulate into the process-global registry as
  ``lgbm_phase_seconds_total{phase=...}`` / ``lgbm_phase_runs_total``
  whenever telemetry (`tpu_telemetry=metrics|trace`) OR the legacy
  LIGHTGBM_TPU_TIMETAG switch is on — `summary()` reads the registry,
  so bench and the Prometheus export see the SAME numbers;
* under ``tpu_telemetry=trace`` each block is additionally a structured
  span (Chrome-trace/Perfetto export + xprof mirror via obs.span);
* `print_summary()` (atexit when LIGHTGBM_TPU_TIMETAG=1) prints the
  table, like the reference's Log::Info TIMETAG dumps;
* `trace(dir)` wraps a block in jax.profiler.trace for xprof/tensorboard
  inspection of the on-device schedule.

When everything is off a PHASE block costs one flag check.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import time
from typing import Dict, Iterator

from ..obs import REGISTRY, span
from ..obs import metrics_on as _obs_metrics_on
from ..obs import resources as _resources
from .log import Log

_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

_SECONDS = "lgbm_phase_seconds_total"
_RUNS = "lgbm_phase_runs_total"


def enabled() -> bool:
    return _enabled or _obs_metrics_on()


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def _record(name: str, seconds: float) -> None:
    REGISTRY.inc(_SECONDS, seconds,
                 help="accumulated wall seconds per lifecycle phase",
                 phase=name)
    REGISTRY.inc(_RUNS, 1, phase=name)


# phases whose wall bracket doubles as a device-memory watermark
# bracket (obs/resources.py phase_peak): the binning phase IS the
# ingest HBM phase — the chunked device matrix and key planes live
# inside it
_MEM_PHASE = {"binning": "ingest"}


@contextlib.contextmanager
def PHASE(name: str) -> Iterator[None]:
    """Accumulate wall time under `name` (no-op unless enabled); a span
    under tpu_telemetry=trace; a device-memory watermark bracket for
    the phases in `_MEM_PHASE`."""
    if not (_enabled or _obs_metrics_on()):
        yield
        return
    sp = span(name)
    mem_phase = _MEM_PHASE.get(name)
    mem = (_resources.phase_peak(mem_phase) if mem_phase
           else contextlib.nullcontext())
    t0 = time.perf_counter()
    try:
        with mem, sp:
            yield
    finally:
        _record(name, time.perf_counter() - t0)


def add(name: str, seconds: float) -> None:
    if _enabled or _obs_metrics_on():
        _record(name, seconds)


def summary() -> Dict[str, float]:
    return {p: REGISTRY.value(_SECONDS, phase=p)
            for p in REGISTRY.label_values(_SECONDS, "phase")}


def counts() -> Dict[str, int]:
    return {p: int(REGISTRY.value(_RUNS, phase=p))
            for p in REGISTRY.label_values(_RUNS, "phase")}


def reset() -> None:
    """Zero the phase accumulation (bench reuses the process).  The
    registry holds phases beside unrelated metric families, so only the
    phase families reset."""
    REGISTRY.clear_family(_SECONDS)
    REGISTRY.clear_family(_RUNS)


def print_summary() -> None:
    acc = summary()
    if not acc:
        return
    cnt = counts()
    width = max(len(k) for k in acc)
    Log.info("phase timings:")
    for name, secs in sorted(acc.items(), key=lambda kv: -kv[1]):
        Log.info(f"  {name:<{width}}  {secs:9.3f}s  x{cnt.get(name, 0)}")


if _enabled:
    atexit.register(print_summary)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/lightgbm_tpu_trace") -> Iterator[None]:
    """jax.profiler trace around a block (view with xprof/tensorboard)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
