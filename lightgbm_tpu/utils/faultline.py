"""Fault-injection harness: named points the runtime fires on its hot
paths and tests (or `tools/perf_probe.py faults`) can arm.

The production code calls `fire("<point>")` at each instrumented site;
unarmed points take NO lock — one GIL-atomic dict increment — so the
hot serving/predict paths never serialize on the harness.  Arming a
point
makes the matching `fire` either raise (simulating a device/runtime
error at exactly that site) or return an action string the site knows
how to apply:

* ``raise``    — raise the armed exception (default `FaultInjected`);
  the site's normal error handling (iteration rollback, serving
  fallback, checkpoint-write recovery) must contain it.
* ``poison``   — the site corrupts its own output (the `grow_step`
  point NaN-poisons the iteration's scores) so the numeric guardrails
  (`tpu_guard_numerics`) can be exercised deterministically.
* ``truncate`` — the site writes only half its payload (the
  `checkpoint_write` point produces a torn file whose manifest CRC
  cannot match) so recovery-from-corruption paths are testable.
* ``hang``     — the site simulates an unresponsive peer: the
  collective watchdog (`parallel/collective.py`) turns it into a
  deterministic `CollectiveTimeout` so hung-peer degradation paths are
  testable without an actually-hung process.
* ``oom``      — raises a realistic ``RESOURCE_EXHAUSTED``-shaped
  device error (jaxlib's own runtime-error type when available) so the
  `utils/membudget.py` OOM classifier and recovery ladder are
  exercised through exactly the path a real HBM exhaustion takes.
  The ``device_alloc`` point fires inside `membudget.oom_guard` at
  every guarded device site (train step, ingest chunk, chunked
  predict, score replay, registry load/warmup, serving dispatch).

Points are process-global and thread-safe; `reset()` disarms
everything.  Hit counters count every `fire` since the last reset, so
"arm at the k-th hit" addresses a specific iteration/request without
the site threading indices through.

Distributed addressing (ISSUE 8): multihost chaos runs must be
reproducible, so a spec can pin BOTH coordinates of a distributed
event:

* ``host=k``        — the spec only matches on the process whose
  `host_index()` is k (every other host counts the hit but never
  fires).  `host_index()` resolves, in order: an explicit
  `set_host_index()` override (single-process chaos sweeps simulating
  a fleet), the LIGHTGBM_TPU_FAULT_HOST env var, `jax.process_index()`
  when jax is already imported, else 0.
* ``absolute=True`` — `at` addresses the N-th hit since the last
  `reset()` (an absolute per-process call index) instead of the N-th
  hit after `arm()`.  Since every host runs the same program, the
  (host, call-index) pair names one collective call in the whole
  group's execution, independent of when the harness armed it.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Dict, List, Optional

POINTS = ("grow_step", "h2d_copy", "checkpoint_write", "serve_dispatch",
          "collective_sync", "binning_allgather", "host_drop",
          "device_alloc",
          # continual-learning stage boundaries (ISSUE 17): buffer
          # ingest, retrain launch, shadow candidate load, alias swap
          "continual_ingest", "continual_retrain",
          "continual_shadow_load", "continual_promote")

_ACTIONS = ("raise", "poison", "truncate", "hang", "oom")


class FaultInjected(RuntimeError):
    """The default exception an armed ``raise`` point throws."""


def resource_exhausted_error(point: str, **info) -> BaseException:
    """A realistic RESOURCE_EXHAUSTED-shaped device error — what the
    ``oom`` action raises.  Built from jaxlib's own runtime-error type
    when available so `membudget.is_oom_error` classifies the injected
    error through EXACTLY the path a real HBM exhaustion takes; the
    fallback class carries the same name and message shape."""
    detail = ", ".join(f"{k}={v}" for k, v in info.items())
    msg = ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
           "2147483648 bytes (injected by faultline "
           f"{point!r}{': ' + detail if detail else ''})")
    try:
        from jax._src.lib import xla_client

        return xla_client.XlaRuntimeError(msg)
    except Exception:  # pragma: no cover - jaxlib layout drift
        err_cls = type("XlaRuntimeError", (RuntimeError,), {})
        return err_cls(msg)


class _Spec:
    __slots__ = ("action", "exc", "at", "times", "host", "end", "where")

    def __init__(self, action: str, exc, at: int, times: int,
                 host: Optional[int] = None, end: Optional[int] = None,
                 where: Optional[Dict] = None):
        self.action = action
        self.exc = exc
        self.at = int(at)
        self.times = int(times)
        self.host = None if host is None else int(host)
        # exclusive upper hit bound (absolute specs only): the spec
        # fires on hits [at, end) or NEVER — an absolute coordinate
        # armed after its call has passed must not drift onto a later
        # call, or the (host, call-index) pair stops naming one event
        self.end = None if end is None else int(end)
        # field filter: the spec only matches fires whose `info` kwargs
        # carry every (key, value) pair — how a fleet chaos run kills
        # ONE device's dispatches (`where={"device": 3}`) while its
        # siblings keep serving
        self.where = dict(where) if where else None


_lock = threading.Lock()
_armed: Dict[str, List[_Spec]] = {}
_hits: Dict[str, int] = {}
_host_override: Optional[int] = None


def _check_point(point: str) -> None:
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}; known: {POINTS}")


def set_host_index(host: Optional[int]) -> None:
    """Override this process's host identity for `host=`-addressed specs
    (single-process chaos sweeps simulate a fleet by iterating it)."""
    global _host_override
    _host_override = None if host is None else int(host)


def host_index() -> int:
    """This process's position in the host group, for `host=` matching.
    set_host_index() override > LIGHTGBM_TPU_FAULT_HOST env >
    jax.process_index() (only when jax is already imported — the fault
    harness must never force backend init) > 0."""
    if _host_override is not None:
        return _host_override
    env = os.environ.get("LIGHTGBM_TPU_FAULT_HOST", "")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            from jax._src import xla_bridge

            # only CONSULT an already-initialized backend: process_index
            # would otherwise force backend init — fatal when the fault
            # harness fires inside the multihost rendezvous itself
            # (gloo collectives need the distributed client FIRST)
            if not xla_bridge.backends_are_initialized():
                return 0
            return int(jax_mod.process_index())
        except Exception:  # pragma: no cover - backend not ready
            return 0
    return 0


def arm(point: str, action: str = "raise", exc=None, at: int = 1,
        times: int = 1, host: Optional[int] = None,
        absolute: bool = False, where: Optional[Dict] = None) -> None:
    """Arm `point`: starting at its `at`-th hit from now, apply `action`
    for the next `times` hits.  With `absolute=True` the window is
    EXACT: hits `[at, at + times)` counted since the last `reset()` —
    a coordinate that already passed never fires (it must not drift
    onto a later call, or the (host, call-index) pair stops naming one
    event).  `host=k` restricts the spec to the process whose
    `host_index()` is k, so a multihost chaos run can kill host k at
    call-index i reproducibly.  `where={"device": 3}` restricts it to
    fires whose info kwargs match every pair — single-device chaos in a
    replicated serving fleet.  `exc` (an exception instance or class)
    overrides the default `FaultInjected` for ``raise`` actions."""
    _check_point(point)
    if action not in _ACTIONS:
        raise ValueError(f"unknown fault action {action!r}; known: {_ACTIONS}")
    if exc is None:
        exc = FaultInjected(f"injected fault at {point!r}")
    with _lock:
        base = 0 if absolute else _hits.get(point, 0)
        start = base + max(int(at), 1)
        times = max(int(times), 1)
        _armed.setdefault(point, []).append(
            _Spec(action, exc, start, times, host=host,
                  end=start + times if absolute else None, where=where))


def disarm(point: Optional[str] = None) -> None:
    with _lock:
        if point is None:
            _armed.clear()
        else:
            _armed.pop(point, None)


def reset() -> None:
    """Disarm everything, zero the hit counters, and clear any host
    override — the absolute (host, call-index) coordinate origin."""
    global _host_override
    with _lock:
        _armed.clear()
        _hits.clear()
        _host_override = None


def hits(point: str) -> int:
    _check_point(point)
    with _lock:
        return _hits.get(point, 0)


def fire(point: str, **info) -> Optional[str]:
    """One hit on `point`.  Raises when an armed ``raise`` spec matches;
    otherwise returns the matched action string ("poison"/"truncate")
    or None.  `info` kwargs are attached to raised FaultInjected
    exceptions for diagnostics.

    Unarmed fast path: no lock.  The counter update is a single dict
    store (GIL-atomic in CPython); exact hit accounting under heavy
    cross-thread contention only matters while a point is armed, and
    armed points take the locked path."""
    if point not in _armed:
        _hits[point] = _hits.get(point, 0) + 1
        return None
    with _lock:
        hit = _hits.get(point, 0) + 1
        _hits[point] = hit
        specs = _armed.get(point)
        if not specs:
            return None
        me = host_index()
        matched = None
        for spec in specs:
            if spec.host is not None and spec.host != me:
                continue  # addressed to another host: count, never fire
            if spec.where is not None and any(
                    info.get(k) != v for k, v in spec.where.items()):
                continue  # addressed to another device/entity: skip
            if spec.times > 0 and hit >= spec.at \
                    and (spec.end is None or hit < spec.end):
                spec.times -= 1
                matched = spec
                break
        if matched is not None and not any(
                s.times > 0 and (s.end is None or hit < s.end)
                for s in specs):
            del _armed[point]
    if matched is None:
        return None
    # a FIRED fault is rare and always worth counting; lazy import keeps
    # the harness importable before the package (and cycle-free)
    from ..obs import flightrecorder
    from ..obs.metrics import REGISTRY

    REGISTRY.inc("lgbm_fault_injections_total",
                 help="armed faultline specs that actually fired",
                 point=point, action=matched.action)
    # the blackbox of a chaos run must show the injection that killed it
    flightrecorder.note("fault", point, action=matched.action, **info)
    if matched.action == "raise":
        exc = matched.exc
        if isinstance(exc, type):
            exc = exc(f"injected fault at {point!r}")
        if isinstance(exc, FaultInjected) and info:
            exc.args = (f"{exc.args[0] if exc.args else point} "
                        f"({', '.join(f'{k}={v}' for k, v in info.items())})",)
        raise exc
    if matched.action == "oom":
        # a realistic RESOURCE_EXHAUSTED so the membudget classifier —
        # not a test-only code path — turns it into DeviceOutOfMemory
        raise resource_exhausted_error(point, **info)
    return matched.action


@contextlib.contextmanager
def armed(point: str, action: str = "raise", exc=None, at: int = 1,
          times: int = 1):
    """Context-managed arm/disarm of one point."""
    arm(point, action=action, exc=exc, at=at, times=times)
    try:
        yield
    finally:
        disarm(point)
