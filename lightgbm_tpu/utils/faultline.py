"""Fault-injection harness: named points the runtime fires on its hot
paths and tests (or `tools/perf_probe.py faults`) can arm.

The production code calls `fire("<point>")` at each instrumented site;
unarmed points take NO lock — one GIL-atomic dict increment — so the
hot serving/predict paths never serialize on the harness.  Arming a
point
makes the matching `fire` either raise (simulating a device/runtime
error at exactly that site) or return an action string the site knows
how to apply:

* ``raise``    — raise the armed exception (default `FaultInjected`);
  the site's normal error handling (iteration rollback, serving
  fallback, checkpoint-write recovery) must contain it.
* ``poison``   — the site corrupts its own output (the `grow_step`
  point NaN-poisons the iteration's scores) so the numeric guardrails
  (`tpu_guard_numerics`) can be exercised deterministically.
* ``truncate`` — the site writes only half its payload (the
  `checkpoint_write` point produces a torn file whose manifest CRC
  cannot match) so recovery-from-corruption paths are testable.

Points are process-global and thread-safe; `reset()` disarms
everything.  Hit counters count every `fire` since the last reset, so
"arm at the k-th hit" addresses a specific iteration/request without
the site threading indices through.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional

POINTS = ("grow_step", "h2d_copy", "checkpoint_write", "serve_dispatch")

_ACTIONS = ("raise", "poison", "truncate")


class FaultInjected(RuntimeError):
    """The default exception an armed ``raise`` point throws."""


class _Spec:
    __slots__ = ("action", "exc", "at", "times")

    def __init__(self, action: str, exc, at: int, times: int):
        self.action = action
        self.exc = exc
        self.at = int(at)
        self.times = int(times)


_lock = threading.Lock()
_armed: Dict[str, List[_Spec]] = {}
_hits: Dict[str, int] = {}


def _check_point(point: str) -> None:
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}; known: {POINTS}")


def arm(point: str, action: str = "raise", exc=None, at: int = 1,
        times: int = 1) -> None:
    """Arm `point`: starting at its `at`-th hit from now, apply `action`
    for the next `times` hits.  `exc` (an exception instance or class)
    overrides the default `FaultInjected` for ``raise`` actions."""
    _check_point(point)
    if action not in _ACTIONS:
        raise ValueError(f"unknown fault action {action!r}; known: {_ACTIONS}")
    if exc is None:
        exc = FaultInjected(f"injected fault at {point!r}")
    with _lock:
        base = _hits.get(point, 0)
        _armed.setdefault(point, []).append(
            _Spec(action, exc, base + max(int(at), 1), max(int(times), 1)))


def disarm(point: Optional[str] = None) -> None:
    with _lock:
        if point is None:
            _armed.clear()
        else:
            _armed.pop(point, None)


def reset() -> None:
    """Disarm everything and zero the hit counters."""
    with _lock:
        _armed.clear()
        _hits.clear()


def hits(point: str) -> int:
    _check_point(point)
    with _lock:
        return _hits.get(point, 0)


def fire(point: str, **info) -> Optional[str]:
    """One hit on `point`.  Raises when an armed ``raise`` spec matches;
    otherwise returns the matched action string ("poison"/"truncate")
    or None.  `info` kwargs are attached to raised FaultInjected
    exceptions for diagnostics.

    Unarmed fast path: no lock.  The counter update is a single dict
    store (GIL-atomic in CPython); exact hit accounting under heavy
    cross-thread contention only matters while a point is armed, and
    armed points take the locked path."""
    if point not in _armed:
        _hits[point] = _hits.get(point, 0) + 1
        return None
    with _lock:
        hit = _hits.get(point, 0) + 1
        _hits[point] = hit
        specs = _armed.get(point)
        if not specs:
            return None
        matched = None
        for spec in specs:
            if spec.times > 0 and hit >= spec.at:
                spec.times -= 1
                matched = spec
                break
        if matched is not None and not any(s.times > 0 for s in specs):
            del _armed[point]
    if matched is None:
        return None
    if matched.action == "raise":
        exc = matched.exc
        if isinstance(exc, type):
            exc = exc(f"injected fault at {point!r}")
        if isinstance(exc, FaultInjected) and info:
            exc.args = (f"{exc.args[0] if exc.args else point} "
                        f"({', '.join(f'{k}={v}' for k, v in info.items())})",)
        raise exc
    return matched.action


@contextlib.contextmanager
def armed(point: str, action: str = "raise", exc=None, at: int = 1,
          times: int = 1):
    """Context-managed arm/disarm of one point."""
    arm(point, action=action, exc=exc, at=at, times=times)
    try:
        yield
    finally:
        disarm(point)
