"""Backend health plumbing for tunneled-TPU environments.

The TPU attachment in this environment is a remote tunnel exposed as the
`axon` jax backend.  When the tunnel is down, *any* jax call that triggers
backend initialization either raises RuntimeError or — worse — hangs
indefinitely inside the plugin's client construction (the failure modes of
the round-1 proof artifacts: BENCH_r01 rc=1, MULTICHIP_r01 rc=124).

Two defenses, used by bench.py / __graft_entry__ / __main__ /
tests/conftest.py:

* `probe_default_backend()` — initialize jax in a THROWAWAY SUBPROCESS with
  a hard timeout, so a hung plugin can never take the caller with it.
  Returns the platform name on success, None on failure.
* `pin_cpu_backend()` — force the current process onto the CPU backend,
  even though (a) the axon sitecustomize imports jax at interpreter start
  and latches JAX_PLATFORMS=axon into jax.config, and (b) the plugin
  ignores JAX_PLATFORMS.  Works post-import as long as no backend has been
  initialized yet: update jax.config and drop the axon backend factory.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time
from typing import Optional


def backend_health() -> str:
    """Classify the default-backend failure risk without initializing it.

    * 'ok'     — no tunneled backend in play; default init is safe.
    * 'probe'  — the tunneled `axon` factory is registered: init may hang
                 on a dead tunnel; callers must probe out-of-process.
    * 'broken' — jax_platforms requests a platform with NO registered
                 factory (e.g. the sitecustomize latched JAX_PLATFORMS=axon
                 but the plugin skipped registration — observed when
                 XLA_FLAGS forces host-platform device counts): init fails
                 fast and deterministically; pin CPU directly.
    """
    try:
        import jax
        import jax._src.xla_bridge as _xb

        factories = set(_xb._backend_factories)
        if "axon" in factories:
            return "probe"
        requested = [p for p in str(jax.config.jax_platforms or "").split(",")
                     if p]
        # only the axon name is judged here: other platforms may register
        # lazily via plugin discovery or be aliases (gpu->cuda), so their
        # absence from the factory table proves nothing
        if "axon" in requested:
            return "broken"
        return "ok"
    except Exception:  # pragma: no cover - jax internals moved
        return "probe"  # be conservative


_PROBE_SRC = r"""
import jax, sys
import jax.numpy as jnp
x = jnp.ones((128, 128))
y = (x @ x).sum()
y.block_until_ready()
sys.stdout.write(jax.devices()[0].platform)
"""


def probe_default_backend(timeout_s: float = 120.0, retries: int = 1,
                          retry_sleep_s: float = 10.0) -> Optional[str]:
    """Platform name of the default jax backend, probed out-of-process.

    A hung backend init (dead tunnel) hits the subprocess timeout instead of
    hanging the caller.  Fast failures (nonzero exit) get bounded retries;
    a TIMEOUT does not retry — a hung tunnel stays hung, and burning
    retries*timeout of dead time risks tripping the caller's own deadline
    (the round-1 rc=124 failure mode).
    """
    for attempt in range(retries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, timeout=timeout_s, text=True)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            return None
        if attempt < retries:
            time.sleep(retry_sleep_s)
    return None


def ensure_backend_or_cpu(probe_timeout_env: str = "LGBM_BACKEND_PROBE_TIMEOUT",
                          default_timeout_s: float = 60.0) -> None:
    """Probe the default backend out-of-process; pin CPU when it is
    dead or hung.  Shared by entry points that may be the FIRST jax
    consumer in a process (CLI __main__, embedded C API): without this a
    dead tunnel hangs the process inside backend init.  Probe results are
    cached in the environment so child processes skip re-probing."""
    health = backend_health()
    if health == "ok":
        return
    if health == "probe":
        cached = os.environ.get("LGBM_BACKEND_PROBE_RESULT")
        if cached == "ok":
            return
        if cached != "failed":
            timeout_s = float(os.environ.get(probe_timeout_env,
                                             default_timeout_s))
            platform = probe_default_backend(timeout_s=timeout_s, retries=0)
            os.environ["LGBM_BACKEND_PROBE_RESULT"] = (
                "failed" if platform is None else "ok")
            if platform is not None:
                return
    pin_cpu_backend()
    from .log import Log

    Log.warning(f"accelerator backend unavailable (backend {health}); "
                "falling back to CPU")


def host_sync(x):
    """Barrier on device compute via a host fetch.

    The tunneled axon backend's `block_until_ready` can return before the
    device actually finishes, which silently turns timing loops into
    dispatch-rate measurements.  A host fetch is the one barrier the tunnel
    honors; every bench/profiling script must use this (and pay the
    transfer OUTSIDE its timed region when possible).  Returns the fetched
    numpy array."""
    import numpy as _np

    return _np.asarray(x)


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_secs: float = 1.0) -> None:
    """Turn on JAX's persistent compilation cache.

    The whole-tree grower is one large XLA program; a cold compile costs
    minutes (the analog hit does not exist in the reference, whose C++ is
    AOT-compiled).  The persistent cache amortizes it to one-time-per-
    (shape, params, platform): subsequent processes deserialize in seconds.
    Defaults to `<repo>/.jax_cache` so the cache survives across runs of
    bench.py / the CLI on the same checkout.

    min_compile_time_secs gates which programs get written: the implicit
    package-import default keeps jax's 1s floor (don't litter the repo
    cache with trivial jits), while the explicit `tpu_compile_cache_dir`
    config path passes 0 so EVERY program of a run replays warm — the
    whole point of opting in by hand.
    """
    if cache_dir is None:
        cache_dir = os.environ.get(
            "LIGHTGBM_TPU_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"))
    if os.environ.get("LIGHTGBM_TPU_CPU_PINNED") or _cpu_is_only_backend():
        # CPU-destined processes get a host-fingerprinted subdir: XLA:CPU
        # cache keys do NOT include the host's CPU features, so an AOT
        # entry compiled on a machine with different vector extensions
        # deserializes and ABORTS (SIGILL) — observed when the checkout's
        # .jax_cache travels between build hosts.  TPU entries target the
        # device and stay shared at the cache root.
        cache_dir = os.path.join(cache_dir, f"cpu-{_host_fingerprint()}")
    try:
        import jax

        prev_dir = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        if prev_dir and prev_dir != cache_dir:
            # the cache singleton latches its directory at first use
            # (jax _initialize_cache runs at most once), so re-pointing
            # the config after any compile — e.g. the package-import
            # default cache already served the Dataset jits — silently
            # keeps writing to the OLD dir unless the singleton resets
            import jax._src.compilation_cache as _cc

            _cc.reset_cache()
    except Exception:  # pragma: no cover - config knobs moved
        pass


def _cpu_is_only_backend() -> bool:
    """True when only the cpu backend factory is registered — i.e. the
    default backend will be CPU even without an explicit pin.  Inspects
    the factory table WITHOUT initializing any backend (a dead tunnel
    hangs initialization; see probe_default_backend)."""
    try:
        import jax._src.xla_bridge as _xb

        return set(_xb._backend_factories) <= {"cpu"}
    except Exception:  # pragma: no cover - jax internals moved
        # the private table moved: the host-fingerprinted cache subdir
        # (the cross-host SIGILL guard) would otherwise disengage
        # SILENTLY.  Surface it and honor an explicit override — a wrong
        # True would cold-start the TPU cache, a wrong False risks a
        # SIGILL on CPU, so the decision goes to the operator rather
        # than a guess.
        import logging

        logging.getLogger("lightgbm_tpu").debug(
            "jax backend-factory introspection failed; set "
            "LGBM_CPU_ONLY_BACKEND=1 if this process is CPU-only")
        ov = os.environ.get("LGBM_CPU_ONLY_BACKEND")
        if ov is None:
            return False
        return ov.strip().lower() not in ("", "0", "false", "no", "off")


def _host_fingerprint() -> str:
    """Short stable id for this host's CPU feature set.

    Hashes the model-identity lines TOO, not just `flags`: XLA:CPU keys
    its AOT entries on LLVM's own feature detection, which distinguishes
    hosts whose /proc/cpuinfo flags lines hash identically (observed as
    "Compile machine features ... could lead to SIGILL" warnings loading
    a same-flags-different-microarch cache).  Two hosts only share a
    subdir when vendor/family/model/stepping AND flags all match —
    close enough to LLVM's view that foreign entries no longer load."""
    import hashlib

    keys = ("vendor_id", "cpu family", "model\t", "model name", "stepping",
            "flags")
    try:
        with open("/proc/cpuinfo") as f:
            ident = []
            for ln in f:
                if not ln.strip():
                    break  # first processor block only; all cores match
                if any(ln.startswith(k) for k in keys):
                    ident.append(ln)
        ident = "".join(ident)
    except OSError:  # pragma: no cover - non-linux
        import platform

        ident = platform.processor() or platform.machine()
    return hashlib.sha1(ident.encode()).hexdigest()[:12]


def pin_cpu_backend(force_device_count: Optional[int] = None) -> None:
    """Pin this process to the CPU backend; optionally force N virtual
    devices (must run before the first backend initialization)."""
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    os.environ.pop("JAX_PLATFORMS", None)
    # route any (later-enabled) persistent compilation cache to a
    # host-fingerprinted CPU subdir — see enable_compilation_cache
    os.environ["LIGHTGBM_TPU_CPU_PINNED"] = "1"
    try:
        import jax

        cur = jax.config.jax_compilation_cache_dir
        if cur and f"{os.sep}cpu-" not in cur:
            # cache was enabled before the pin: re-point it
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(cur, f"cpu-{_host_fingerprint()}"))
    except Exception:  # pragma: no cover
        pass
    if force_device_count is not None:
        flag = f"--xla_force_host_platform_device_count={force_device_count}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # rewrite an existing (possibly different) count, don't keep it
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags)
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - jax internals moved
        pass
