"""Retrace audit: a ledger of every XLA program this process compiles.

Compile latency is the single biggest wall-clock lever for training
restarts and serving cold starts (ROADMAP item 3: the one real TPU bench
spent 155 s compiling vs ~12 s/iter training).  The enemy is not one big
program but the *zoo*: every jit site that keys a new trace on a static
argument or a fresh closure silently multiplies the compile bill, and
nothing counted them — compile_s only showed the total.

`ledger_jit` wraps a `jax.jit` site so each DISTINCT compiled program
(new entry in the jit's own executable cache) is recorded once with:

* the site name (one per wrapped jit call site),
* the first-call wall time (lowering + XLA compile + first execution —
  for the big grower programs this is compile-dominated),
* a compact signature of the triggering call (static args + input
  shapes/dtypes), so `tools/perf_probe.py retrace` can attribute WHICH
  mode/shape variant added a program.

Overhead discipline: when the ledger is disabled (the default) the
wrapper costs one attribute check per call and computes nothing; when
enabled, cache growth is detected via the jit's own `_cache_size()` so
no per-call signature hashing happens on cache hits.  The wrapper is
transparent — `lower`, `_cache_size`, etc. delegate to the underlying
jitted callable, so call sites and tests that poke at jit internals
keep working.

The module-level `LEDGER` singleton is the process-wide audit surface:

    from lightgbm_tpu.utils.compile_ledger import LEDGER
    LEDGER.enable(); LEDGER.reset()
    ... train / predict / serve ...
    LEDGER.n_programs()        # the n_programs bench metric
    LEDGER.report()            # per-site breakdown
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import jax


def _describe_leaf(x: Any) -> str:
    """Compact aval-or-value description of one argument leaf."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(x, (bool, int, float, str, type(None))):
        return repr(x)
    if callable(x):
        return getattr(x, "__name__", "<fn>")
    return type(x).__name__


def _spec_leaf(x: Any) -> Any:
    """Array leaf -> ShapeDtypeStruct (re-lowerable after the original
    buffers are donated/freed); everything else passes through."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                    weak_type=bool(getattr(x, "weak_type",
                                                           False)))
    return x


def call_specs(args: tuple, kwargs: dict, static_argnums=(),
               static_argnames=()) -> tuple:
    """(args, kwargs) with every NON-STATIC array replaced by its
    ShapeDtypeStruct — the re-lowerable coordinates of one compiled
    program, captured BEFORE the call so donation cannot invalidate
    them.  Static args stay as their hashable values (a struct there
    would trace a different program)."""
    import jax.tree_util as jtu

    static_argnums = set(static_argnums or ())
    static_argnames = set(static_argnames or ())
    spec_args = tuple(
        a if i in static_argnums else jtu.tree_map(_spec_leaf, a)
        for i, a in enumerate(args))
    spec_kwargs = {
        k: (v if k in static_argnames else jtu.tree_map(_spec_leaf, v))
        for k, v in kwargs.items()}
    return spec_args, spec_kwargs


def call_signature(args: tuple, kwargs: dict) -> str:
    """One-line signature of a jit call: static values + array avals.

    Dict args (the grower's meta) list key=aval pairs so mode/shape
    variants are attributable from the retrace report alone."""
    parts: List[str] = []
    for a in args:
        if isinstance(a, dict):
            inner = ",".join(f"{k}={_describe_leaf(v)}"
                             for k, v in sorted(a.items(), key=lambda kv: kv[0]))
            parts.append("{" + inner + "}")
        elif isinstance(a, (tuple, list)):
            parts.append("(" + ",".join(_describe_leaf(v) for v in a) + ")")
        else:
            parts.append(_describe_leaf(a))
    for k in sorted(kwargs):
        parts.append(f"{k}={_describe_leaf(kwargs[k])}")
    return "(" + ", ".join(parts) + ")"


class CompileLedger:
    """Thread-safe registry of compiled programs across all wrapped sites."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._capture = False
        self._programs: List[Dict] = []

    # -- control -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> None:
        self._enabled = bool(on)

    @property
    def capture_costs(self) -> bool:
        return self._capture

    def enable_capture(self, on: bool = True) -> None:
        """Additionally capture each new program's re-lowerable call
        specs so `analyze()` can attach its static cost/memory analysis
        (ISSUE 12).  Off by default: spec capture is cheap but not
        free, and only resource-accounting callers (bench,
        perf_probe mem) read it."""
        self._capture = bool(on)

    def reset(self) -> None:
        with self._lock:
            self._programs = []

    # -- recording (called by LedgeredJit) ------------------------------
    def record(self, site: str, signature: str, wall_s: float,
               aot=None) -> None:
        with self._lock:
            self._programs.append({"site": site, "signature": signature,
                                   "first_call_s": wall_s,
                                   "t": time.time(), "_aot": aot})

    # -- reading --------------------------------------------------------
    def n_programs(self, site: Optional[str] = None) -> int:
        """Programs compiled while enabled (optionally for one site)."""
        with self._lock:
            if site is None:
                return len(self._programs)
            return sum(1 for p in self._programs if p["site"] == site)

    def programs(self) -> List[Dict]:
        with self._lock:
            return [{k: v for k, v in p.items() if k != "_aot"}
                    for p in self._programs]

    # -- static cost/memory analysis (ISSUE 12) -------------------------
    @staticmethod
    def _memory_default() -> bool:
        """memory_analysis needs a fresh AOT compile per program (jax
        gives no handle on the jit cache's own executable), so the
        auto policy pays it only where HBM numbers exist to read back;
        on CPU the table carries flops/bytes from the (compile-free)
        lowered analysis and None for the memory fields."""
        try:
            return jax.devices()[0].platform != "cpu"
        except Exception:  # pragma: no cover - backend init failure
            return False

    def analyze(self, memory: Optional[bool] = None) -> List[Dict]:
        """Attach each captured program's `cost_analysis()` (flops,
        bytes accessed — from the lowering, no compile) and, when
        `memory` (default: auto — True off-CPU), its compiled
        `memory_analysis()` (argument / output / temp / generated-code
        bytes).  Idempotent; failures record None per field rather than
        raising — a program that cannot re-lower (mesh-sharded specs,
        exotic statics) still keeps its ledger entry."""
        if memory is None:
            memory = self._memory_default()
        with self._lock:
            # re-analyze when memory is requested but a prior pass
            # (auto: memory=False on CPU) SKIPPED it — "mem" absent
            # means not yet attempted; "mem": None means a real attempt
            # FAILED and must not be re-paid (a failing re-lower would
            # otherwise re-run its AOT attempt on every call)
            todo = [p for p in self._programs
                    if p.get("_aot") is not None
                    and ("cost" not in p or (memory and "mem" not in p))]
        for p in todo:
            fn, spec_args, spec_kwargs = p["_aot"]
            cost = None
            lowered = None
            try:
                lowered = fn.lower(*spec_args, **spec_kwargs)
                ca = lowered.cost_analysis() or {}
                cost = {"flops": float(ca.get("flops", 0.0)),
                        "bytes_accessed": float(
                            ca.get("bytes accessed", 0.0))}
            except Exception:
                cost = None
            updates = {"cost": cost}
            if lowered is None:
                updates["mem"] = None          # can never re-lower
            elif memory:
                try:
                    ms = lowered.compile().memory_analysis()
                    updates["mem"] = {
                        "argument_bytes": int(ms.argument_size_in_bytes),
                        "output_bytes": int(ms.output_size_in_bytes),
                        "temp_bytes": int(ms.temp_size_in_bytes),
                        "alias_bytes": int(ms.alias_size_in_bytes),
                        "generated_code_bytes": int(
                            ms.generated_code_size_in_bytes),
                    }
                except Exception:
                    updates["mem"] = None      # attempted and failed
            with self._lock:
                p.update(updates)
        return self.programs()

    def cost_table(self, memory: Optional[bool] = None) -> List[Dict]:
        """Per-program cost rows for the bench JSON / perf_probe mem
        table: site, flops, bytes accessed, and the memory-analysis
        byte fields (None where unavailable — explicitly null on CPU
        rather than silently absent)."""
        rows = []
        for p in self.analyze(memory=memory):
            cost, mem = p.get("cost"), p.get("mem")
            rows.append({
                "site": p["site"],
                "signature": p["signature"][:160],
                "first_call_s": round(p["first_call_s"], 3),
                "flops": None if cost is None else cost["flops"],
                "bytes_accessed": (None if cost is None
                                   else cost["bytes_accessed"]),
                "argument_bytes": None if mem is None
                else mem["argument_bytes"],
                "output_bytes": None if mem is None
                else mem["output_bytes"],
                "temp_bytes": None if mem is None else mem["temp_bytes"],
                "generated_code_bytes": (None if mem is None
                                         else mem["generated_code_bytes"]),
            })
        return rows

    def report(self) -> List[Dict]:
        """Per-site rollup sorted by total first-call wall, descending."""
        agg: Dict[str, Dict] = {}
        for p in self.programs():
            a = agg.setdefault(p["site"], {"site": p["site"], "programs": 0,
                                           "first_call_s": 0.0,
                                           "signatures": []})
            a["programs"] += 1
            a["first_call_s"] += p["first_call_s"]
            a["signatures"].append(p["signature"])
        return sorted(agg.values(), key=lambda a: -a["first_call_s"])

    def format_report(self) -> str:
        lines = [f"{'site':<28s} {'programs':>8s} {'first-call s':>12s}"]
        total_n = total_s = 0
        for a in self.report():
            lines.append(f"{a['site']:<28s} {a['programs']:>8d} "
                         f"{a['first_call_s']:>12.2f}")
            total_n += a["programs"]
            total_s += a["first_call_s"]
        lines.append(f"{'TOTAL (n_programs)':<28s} {total_n:>8d} "
                     f"{total_s:>12.2f}")
        return "\n".join(lines)


LEDGER = CompileLedger()


class LedgeredJit:
    """`jax.jit` plus per-program ledger recording.

    New-program detection uses the jitted callable's own `_cache_size()`
    (the executable cache the jit keys on static args + avals), so the
    ledger can never disagree with what jax actually compiled.  When
    `_cache_size` is unavailable (older jax), every call while enabled
    falls back to signature bookkeeping in the ledger itself.
    """

    def __init__(self, fn, site: Optional[str] = None, **jit_kwargs):
        self._fn = jax.jit(fn, **jit_kwargs)
        self.site = site or getattr(fn, "__name__", "<fn>")
        def _as_tuple(v):
            if v is None:
                return ()
            return (v,) if isinstance(v, (int, str)) else tuple(v)

        self._static_argnums = _as_tuple(jit_kwargs.get("static_argnums"))
        self._static_argnames = _as_tuple(
            jit_kwargs.get("static_argnames"))
        self._seen_sigs = set()
        # serializes the (cache-size, call, cache-size) window while the
        # ledger is ENABLED: without it, a thread's cache-hit call that
        # overlaps another thread's compile observes the cache growing
        # and double-records the program.  The disabled path (default,
        # production serving) never touches the lock.
        self._lock = threading.Lock()

    def _cache_len(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return None

    def _capture_specs(self, args, kwargs):
        """Re-lowerable specs of one call, built only on the RARE
        new-program branch (never on cache hits — a per-call pytree
        walk under the lock would tax every timed loop the bench
        gates).  Safe AFTER the call: shape/dtype metadata stays
        readable on donated-and-deleted arrays."""
        if not LEDGER.capture_costs:
            return None
        try:
            specs = call_specs(args, kwargs, self._static_argnums,
                               self._static_argnames)
        except Exception:  # pragma: no cover - exotic pytree
            return None
        return (self._fn, *specs)

    def __call__(self, *args, **kwargs):
        if not LEDGER.enabled:
            return self._fn(*args, **kwargs)
        with self._lock:
            before = self._cache_len()
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            after = self._cache_len()
            if before is None:
                sig = call_signature(args, kwargs)
                if sig not in self._seen_sigs:
                    self._seen_sigs.add(sig)
                    LEDGER.record(self.site, sig,
                                  time.perf_counter() - t0,
                                  aot=self._capture_specs(args, kwargs))
            elif after is not None and after > before:
                LEDGER.record(self.site, call_signature(args, kwargs),
                              time.perf_counter() - t0,
                              aot=self._capture_specs(args, kwargs))
        return out

    def __getattr__(self, name):
        # transparent delegation (lower/_cache_size/clear_cache/...)
        return getattr(self._fn, name)


def ledger_jit(fn=None, *, site: Optional[str] = None, **jit_kwargs):
    """Drop-in `jax.jit` replacement that records programs in LEDGER.

    Usable as a decorator (`@ledger_jit(site=..., static_argnames=...)`)
    or a call (`ledger_jit(f, site=...)`)."""
    if fn is None:
        def deco(f):
            return LedgeredJit(f, site=site, **jit_kwargs)
        return deco
    return LedgeredJit(fn, site=site, **jit_kwargs)
