"""Retrace audit: a ledger of every XLA program this process compiles.

Compile latency is the single biggest wall-clock lever for training
restarts and serving cold starts (ROADMAP item 3: the one real TPU bench
spent 155 s compiling vs ~12 s/iter training).  The enemy is not one big
program but the *zoo*: every jit site that keys a new trace on a static
argument or a fresh closure silently multiplies the compile bill, and
nothing counted them — compile_s only showed the total.

`ledger_jit` wraps a `jax.jit` site so each DISTINCT compiled program
(new entry in the jit's own executable cache) is recorded once with:

* the site name (one per wrapped jit call site),
* the first-call wall time (lowering + XLA compile + first execution —
  for the big grower programs this is compile-dominated),
* a compact signature of the triggering call (static args + input
  shapes/dtypes), so `tools/perf_probe.py retrace` can attribute WHICH
  mode/shape variant added a program.

Overhead discipline: when the ledger is disabled (the default) the
wrapper costs one attribute check per call and computes nothing; when
enabled, cache growth is detected via the jit's own `_cache_size()` so
no per-call signature hashing happens on cache hits.  The wrapper is
transparent — `lower`, `_cache_size`, etc. delegate to the underlying
jitted callable, so call sites and tests that poke at jit internals
keep working.

The module-level `LEDGER` singleton is the process-wide audit surface:

    from lightgbm_tpu.utils.compile_ledger import LEDGER
    LEDGER.enable(); LEDGER.reset()
    ... train / predict / serve ...
    LEDGER.n_programs()        # the n_programs bench metric
    LEDGER.report()            # per-site breakdown
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import jax


def _describe_leaf(x: Any) -> str:
    """Compact aval-or-value description of one argument leaf."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(x, (bool, int, float, str, type(None))):
        return repr(x)
    if callable(x):
        return getattr(x, "__name__", "<fn>")
    return type(x).__name__


def call_signature(args: tuple, kwargs: dict) -> str:
    """One-line signature of a jit call: static values + array avals.

    Dict args (the grower's meta) list key=aval pairs so mode/shape
    variants are attributable from the retrace report alone."""
    parts: List[str] = []
    for a in args:
        if isinstance(a, dict):
            inner = ",".join(f"{k}={_describe_leaf(v)}"
                             for k, v in sorted(a.items(), key=lambda kv: kv[0]))
            parts.append("{" + inner + "}")
        elif isinstance(a, (tuple, list)):
            parts.append("(" + ",".join(_describe_leaf(v) for v in a) + ")")
        else:
            parts.append(_describe_leaf(a))
    for k in sorted(kwargs):
        parts.append(f"{k}={_describe_leaf(kwargs[k])}")
    return "(" + ", ".join(parts) + ")"


class CompileLedger:
    """Thread-safe registry of compiled programs across all wrapped sites."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._programs: List[Dict] = []

    # -- control -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> None:
        self._enabled = bool(on)

    def reset(self) -> None:
        with self._lock:
            self._programs = []

    # -- recording (called by LedgeredJit) ------------------------------
    def record(self, site: str, signature: str, wall_s: float) -> None:
        with self._lock:
            self._programs.append({"site": site, "signature": signature,
                                   "first_call_s": wall_s,
                                   "t": time.time()})

    # -- reading --------------------------------------------------------
    def n_programs(self, site: Optional[str] = None) -> int:
        """Programs compiled while enabled (optionally for one site)."""
        with self._lock:
            if site is None:
                return len(self._programs)
            return sum(1 for p in self._programs if p["site"] == site)

    def programs(self) -> List[Dict]:
        with self._lock:
            return [dict(p) for p in self._programs]

    def report(self) -> List[Dict]:
        """Per-site rollup sorted by total first-call wall, descending."""
        agg: Dict[str, Dict] = {}
        for p in self.programs():
            a = agg.setdefault(p["site"], {"site": p["site"], "programs": 0,
                                           "first_call_s": 0.0,
                                           "signatures": []})
            a["programs"] += 1
            a["first_call_s"] += p["first_call_s"]
            a["signatures"].append(p["signature"])
        return sorted(agg.values(), key=lambda a: -a["first_call_s"])

    def format_report(self) -> str:
        lines = [f"{'site':<28s} {'programs':>8s} {'first-call s':>12s}"]
        total_n = total_s = 0
        for a in self.report():
            lines.append(f"{a['site']:<28s} {a['programs']:>8d} "
                         f"{a['first_call_s']:>12.2f}")
            total_n += a["programs"]
            total_s += a["first_call_s"]
        lines.append(f"{'TOTAL (n_programs)':<28s} {total_n:>8d} "
                     f"{total_s:>12.2f}")
        return "\n".join(lines)


LEDGER = CompileLedger()


class LedgeredJit:
    """`jax.jit` plus per-program ledger recording.

    New-program detection uses the jitted callable's own `_cache_size()`
    (the executable cache the jit keys on static args + avals), so the
    ledger can never disagree with what jax actually compiled.  When
    `_cache_size` is unavailable (older jax), every call while enabled
    falls back to signature bookkeeping in the ledger itself.
    """

    def __init__(self, fn, site: Optional[str] = None, **jit_kwargs):
        self._fn = jax.jit(fn, **jit_kwargs)
        self.site = site or getattr(fn, "__name__", "<fn>")
        self._seen_sigs = set()
        # serializes the (cache-size, call, cache-size) window while the
        # ledger is ENABLED: without it, a thread's cache-hit call that
        # overlaps another thread's compile observes the cache growing
        # and double-records the program.  The disabled path (default,
        # production serving) never touches the lock.
        self._lock = threading.Lock()

    def _cache_len(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        if not LEDGER.enabled:
            return self._fn(*args, **kwargs)
        with self._lock:
            before = self._cache_len()
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            after = self._cache_len()
            if before is None:
                sig = call_signature(args, kwargs)
                if sig not in self._seen_sigs:
                    self._seen_sigs.add(sig)
                    LEDGER.record(self.site, sig,
                                  time.perf_counter() - t0)
            elif after is not None and after > before:
                LEDGER.record(self.site, call_signature(args, kwargs),
                              time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        # transparent delegation (lower/_cache_size/clear_cache/...)
        return getattr(self._fn, name)


def ledger_jit(fn=None, *, site: Optional[str] = None, **jit_kwargs):
    """Drop-in `jax.jit` replacement that records programs in LEDGER.

    Usable as a decorator (`@ledger_jit(site=..., static_argnames=...)`)
    or a call (`ledger_jit(f, site=...)`)."""
    if fn is None:
        def deco(f):
            return LedgeredJit(f, site=site, **jit_kwargs)
        return deco
    return LedgeredJit(fn, site=site, **jit_kwargs)
