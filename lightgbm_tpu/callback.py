"""Training callbacks (reference python-package/lightgbm/callback.py:55-219)."""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score: List):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


# back-compat alias matching the reference's print_evaluation
print_evaluation = log_evaluation


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(item[2])
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"length of list {key!r} must equal num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[List] = []
    cmp_op: List[Callable] = []
    enabled: List[bool] = [True]
    first_metric: List[str] = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            if verbose:
                print("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one validation set is required")
        if verbose:
            print(f"Training until validation scores don't improve for "
                  f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1]
        for item in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if item[3]:  # higher is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        for i, item in enumerate(env.evaluation_result_list):
            score = item[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != item[1]:
                continue
            if item[0] == "training" and len(env.evaluation_result_list) > 1:
                continue  # train metric doesn't trigger early stop when valids exist
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t"
                          + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print(f"Did not meet early stopping. Best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t"
                          + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
