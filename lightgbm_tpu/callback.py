"""Training callbacks.

Plays the role of reference python-package/lightgbm/callback.py (the
engine's per-iteration hook system), re-derived for this framework:
callbacks here are small callable OBJECTS carrying their own state, not
closure bundles.  The engine contract is shared with the reference so user
callbacks port over unchanged:

* a callback is called with a `CallbackEnv` after (or, when its
  `before_iteration` attribute is true, before) every boosting iteration;
* `order` sorts multiple callbacks within one iteration;
* raising `EarlyStopException` stops the training loop, carrying the best
  iteration + its evaluation snapshot.

Evaluation entries are `(dataset_name, metric_name, value,
higher_is_better)` tuples, with a 5th stdv element in cv runs.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Callable, Dict, List, Optional

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score: List):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _entry_text(entry, show_stdv: bool = True) -> str:
    """One eval tuple -> report text (cv entries carry a trailing stdv)."""
    name, metric, value = entry[0], entry[1], entry[2]
    if len(entry) == 5 and show_stdv:
        return f"{name}'s {metric}: {value:g} + {entry[4]:g}"
    if len(entry) not in (4, 5):
        raise ValueError("Wrong metric value")
    return f"{name}'s {metric}: {value:g}"


def _report(entries, show_stdv: bool = True) -> str:
    return "\t".join(_entry_text(e, show_stdv) for e in entries)


class _LogEvaluation:
    order = 10
    before_iteration = False

    def __init__(self, period: int, show_stdv: bool):
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        done = env.iteration + 1
        if done % self.period == 0:
            print(f"[{done}]\t"
                  f"{_report(env.evaluation_result_list, self.show_stdv)}")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Reference-era alias of log_evaluation (callback.py:55
    print_evaluation)."""
    return log_evaluation(period, show_stdv)


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    return _LogEvaluation(period, show_stdv)


# back-compat alias matching the reference's print_evaluation
print_evaluation = log_evaluation


class _RecordEvaluation:
    order = 20
    before_iteration = False

    def __init__(self, target: Dict[str, Dict[str, List[float]]]):
        self.target = target

    def __call__(self, env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list:
            series = self.target.setdefault(
                entry[0], collections.OrderedDict()).setdefault(entry[1], [])
            series.append(entry[2])


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")
    eval_result.clear()
    return _RecordEvaluation(eval_result)


class _ResetParameter:
    order = 10
    before_iteration = True

    def __init__(self, schedules: Dict[str, Any]):
        self.schedules = schedules

    def __call__(self, env: CallbackEnv) -> None:
        step = env.iteration - env.begin_iteration
        updates = {}
        for key, sched in self.schedules.items():
            if isinstance(sched, list):
                if len(sched) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"length of list {key!r} must equal num_boost_round")
                updates[key] = sched[step]
            elif callable(sched):
                updates[key] = sched(step)
        if updates:
            env.model.reset_parameter(updates)


def reset_parameter(**kwargs: Any) -> Callable:
    return _ResetParameter(kwargs)


class _SeriesState:
    """Best-so-far tracker for one (dataset, metric) series."""

    __slots__ = ("maximize", "value", "round", "snapshot")

    def __init__(self, maximize: bool):
        self.maximize = maximize
        self.value = -math.inf if maximize else math.inf
        self.round = 0
        self.snapshot = None

    def offer(self, value: float, iteration: int, entries) -> None:
        better = (value > self.value) if self.maximize else (value < self.value)
        if self.snapshot is None or better:
            self.value = value
            self.round = iteration
            self.snapshot = list(entries)


class _EarlyStopping:
    """Stop when no watched series improves for `stopping_rounds` rounds.

    Matches the reference semantics (dart disables stopping; the training
    set's own metrics never trigger a stop while validation sets exist;
    `first_metric_only` restricts triggering to the first metric) without
    its code shape: state lives in per-series `_SeriesState` objects
    created on the first evaluated iteration.

    snapshot_state/restore_state (keyed "early_stopping") ride the
    training checkpoint bundle so a resumed run keeps the best-so-far
    rounds and stops at the SAME iteration an uninterrupted run would.
    """

    order = 30
    before_iteration = False
    state_key = "early_stopping"

    def __init__(self, stopping_rounds: int, first_metric_only: bool,
                 verbose: bool):
        if stopping_rounds <= 0:
            raise ValueError("stopping_rounds must be > 0")
        self.patience = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.series: List[_SeriesState] = []
        self.active = True
        self.primed = False
        self.first_metric = ""

    def _say(self, text: str) -> None:
        if self.verbose:
            print(text)

    def _prime(self, env: CallbackEnv) -> None:
        self.primed = True
        booster_mode = next(
            (env.params[a] for a in ("boosting", "boosting_type", "boost")
             if a in env.params), "gbdt")
        if booster_mode == "dart":
            self.active = False
            self._say("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one validation set is required")
        self.first_metric = env.evaluation_result_list[0][1]
        self.series = [_SeriesState(maximize=bool(e[3]))
                       for e in env.evaluation_result_list]
        self._say(f"Training until validation scores don't improve for "
                  f"{self.patience} rounds")

    def _halt(self, state: _SeriesState, reason: str) -> None:
        self._say(f"{reason}\n[{state.round + 1}]\t"
                  f"{_report(state.snapshot)}")
        raise EarlyStopException(state.round, state.snapshot)

    def __call__(self, env: CallbackEnv) -> None:
        if not self.primed:
            self._prime(env)
        if not self.active:
            return
        last_round = env.iteration == env.end_iteration - 1
        for state, entry in zip(self.series, env.evaluation_result_list):
            state.offer(entry[2], env.iteration, env.evaluation_result_list)
            if self.first_metric_only and entry[1] != self.first_metric:
                continue
            if entry[0] == "training" and len(self.series) > 1:
                # train metrics are reported but never trigger a stop
                # while real validation sets exist
                continue
            if env.iteration - state.round >= self.patience:
                self._halt(state, "Early stopping, best iteration is:")
            if last_round:
                self._halt(state,
                           "Did not meet early stopping. Best iteration is:")


    # -- checkpoint round trip (utils/checkpoint.py) -------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "primed": self.primed,
            "active": self.active,
            "first_metric": self.first_metric,
            "series": [{"maximize": s.maximize, "value": s.value,
                        "round": s.round,
                        "snapshot": ([list(e) for e in s.snapshot]
                                     if s.snapshot is not None else None)}
                       for s in self.series],
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self.primed = bool(state.get("primed", False))
        self.active = bool(state.get("active", True))
        self.first_metric = str(state.get("first_metric", ""))
        self.series = []
        for d in state.get("series", []):
            s = _SeriesState(maximize=bool(d["maximize"]))
            s.value = float(d["value"])
            s.round = int(d["round"])
            s.snapshot = ([tuple(e) for e in d["snapshot"]]
                          if d.get("snapshot") is not None else None)
            self.series.append(s)


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    return _EarlyStopping(stopping_rounds, first_metric_only, verbose)


class _Checkpoint:
    """Write an atomic training checkpoint every `interval` iterations
    (and at the final one).  Runs AFTER early stopping (order) so a
    bundle never snapshots a half-evaluated iteration; sibling callbacks
    exposing snapshot_state/restore_state (early stopping) ride the
    bundle via `peers`."""

    order = 40
    before_iteration = False

    def __init__(self, directory: Optional[str] = None, interval: int = 1,
                 keep: int = 3, manager=None):
        from .utils.checkpoint import make_manager

        if manager is None:
            # host-aware: in a jax.distributed group each process writes
            # its own host-<k>/ bundles and rank 0 commits the global
            # manifest after the all-hosts-durable barrier
            manager = make_manager(directory, keep=keep)
        self.manager = manager
        self.interval = max(int(interval), 1)
        self.peers: list = []  # sibling callbacks; engine.train fills it

    def __call__(self, env: CallbackEnv) -> None:
        from .utils.checkpoint import save_checkpoint

        done = env.iteration + 1
        if done % self.interval == 0 or done == env.end_iteration:
            save_checkpoint(env.model, self.manager, callbacks=self.peers)


def checkpoint(directory: str, interval: int = 1, keep: int = 3) -> Callable:
    """Create the atomic-checkpoint callback (the engine adds one
    automatically when `tpu_checkpoint_dir` is configured)."""
    return _Checkpoint(directory, interval=interval, keep=keep)
