"""Data-parallel tree growth: row shards + histogram psum over the mesh.

The reference DataParallelTreeLearner (reference src/treelearner/
data_parallel_tree_learner.cpp:149-163) reduce-scatters packed histogram
buffers so each machine owns global histograms for a feature block, then
allreduces the best split.  The TPU formulation is simpler and stronger:
`lax.psum` of the [F, B, 3] histogram tensor inside shard_map gives every
shard the global histograms (XLA lowers this to reduce-scatter+all-gather
over ICI on its own), so every shard runs the identical split search and
identical tree — no SyncUpGlobalBestSplit step is needed, exactly like the
reference's feature-parallel trick of making decisions reproducible on all
machines.

Kept as a thin alias of the 'data' strategy in strategies.py so older
callers (and the driver dry run) exercise the SAME code path the tree
learner uses.
"""

from __future__ import annotations

from jax.sharding import Mesh

from ..ops.grower import GrowerParams
from .strategies import make_strategy_grower


def make_data_parallel_grower(params: GrowerParams, num_features: int,
                              mesh: Mesh):
    """Whole-tree grower sharded over mesh axis 'data'.

    Inputs are globally-shaped arrays sharded along rows; outputs: records
    are replicated, leaf_ids stay row-sharded.
    """
    return make_strategy_grower(params, num_features, "data", mesh)
