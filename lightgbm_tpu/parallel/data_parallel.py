"""Data-parallel tree growth: row shards + histogram psum over the mesh.

The reference DataParallelTreeLearner (reference src/treelearner/
data_parallel_tree_learner.cpp:149-163) reduce-scatters packed histogram
buffers so each machine owns global histograms for a feature block, then
allreduces the best split.  The TPU formulation is simpler and stronger:
`lax.psum` of the [F, B, 3] histogram tensor inside shard_map gives every
shard the global histograms (XLA lowers this to reduce-scatter+all-gather
over ICI on its own), so every shard runs the identical split search and
identical tree — no SyncUpGlobalBestSplit step is needed, exactly like the
reference's feature-parallel trick of making decisions reproducible on all
machines.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops.grower import GrowerParams, make_grower


def make_data_parallel_grower(params: GrowerParams, num_features: int,
                              mesh: Mesh):
    """Whole-tree grower sharded over mesh axis 'data'.

    Inputs are globally-shaped arrays sharded along rows; outputs: records
    are replicated, leaf_ids stay row-sharded.
    """
    grow = make_grower(params, num_features, data_axis="data", jit=False)

    def wrapped(bins_pad, grad, hess, row_mask, feature_mask, meta):
        out = grow(bins_pad, grad, hess, row_mask, feature_mask, meta)
        # records / leaf stats are identical on every shard (computed from
        # psum'ed histograms); mark them replicated for shard_map
        return out

    meta_spec = {k: P() for k in ("num_bin", "missing_type", "default_bin",
                                  "monotone", "penalty")}
    sharded = shard_map(
        wrapped, mesh=mesh,
        in_specs=(P("data", None), P("data"), P("data"), P("data"),
                  P(), meta_spec),
        out_specs={"records": P(), "leaf_ids": P("data"),
                   "leaf_output": P(), "leaf_cnt": P(), "leaf_sum_h": P()},
        check_rep=False)
    return jax.jit(sharded)
