"""Collective watchdogs: deadline + bounded retry around every
host-level collective.

The reference's socket collectives time out per-link (``Network``
config ``time_out``, reference include/LightGBM/config.h network
section) so one dead machine fails the group loudly.  The jax
equivalents (`multihost_utils.process_allgather`,
`jax.distributed.initialize`) block forever when a peer diverged or
died — `io/distributed_binning.py`'s own docstring calls out the
deadlocked-allgather failure mode — which turns one lost host into a
silently hung pod.  `guarded_collective` restores the reference's
semantics:

* **deadline** — the transport runs on a watchdog thread; if it has
  not returned after ``timeout_s`` a structured `CollectiveTimeout`
  raises on the caller.  The abandoned thread keeps blocking in the
  dead collective (jax gives no way to cancel it) — acceptable because
  the caller's job is now to degrade: roll the iteration back
  (`GBDT._iter_snapshot`), flush a final checkpoint, and surface a
  usable booster before the process exits.
* **bounded retry** — a collective that RAISES (transient DCN errors,
  a preempted-and-restarted coordinator) is retried up to ``retries``
  times with exponential backoff.  This leans on jax collectives
  failing SYMMETRICALLY (a transport error surfaces the op's failure
  on every rank, so all ranks retry the same op together); an error
  genuinely local to one rank would desync the retried op against its
  peers' next collective — set ``tpu_collective_retries=0`` on
  transports without that property.  Timeouts and host-drops are NOT
  retried under any setting: after a deadline expiry the group's
  collective streams are provably no longer aligned, and re-entering
  would desync ranks (the same reason the reference tears the whole
  Network down on a link error).
* **fault injection** — every call fires its faultline point (default
  ``collective_sync``; the binning path uses ``binning_allgather``)
  plus ``host_drop``, so chaos runs can kill host k at call-index i
  deterministically (`faultline.arm(..., host=k, at=i,
  absolute=True)`).  An armed ``hang`` simulates an unresponsive peer
  through the real deadline machinery; an armed ``host_drop`` raise
  becomes `HostDropped` on the addressed host (and, on its peers, the
  hang->timeout they would observe in a real drop).

Defaults are process-global (`configure`, the reference's
Network::Init analog) and wired from `tpu_collective_timeout_s` /
`tpu_collective_retries` at learner/dataset init; ``timeout_s=0``
disables the deadline (today's block-forever behavior) while keeping
injection and retry live.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from .. import obs
from ..utils import faultline
from ..utils.log import Log

# collective wait-time buckets: ICI syncs are sub-ms, DCN barriers can
# legitimately take seconds
_WAIT_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                 1.0, 5.0, 15.0, 60.0)


def _note_wait(name: str, seconds: float) -> None:
    if obs.metrics_on():
        obs.REGISTRY.observe(
            "lgbm_collective_wait_seconds", seconds, buckets=_WAIT_BUCKETS,
            help="wall seconds blocked in host-level collectives",
            name=name)


class CollectiveTimeout(RuntimeError):
    """A host-level collective missed its watchdog deadline."""

    def __init__(self, name: str, timeout_s: float, attempts: int,
                 host: int):
        self.name = name
        self.timeout_s = float(timeout_s)
        self.attempts = int(attempts)
        self.host = int(host)
        super().__init__(
            f"collective {name!r} timed out after {timeout_s:g}s on host "
            f"{host} (attempt {attempts}); a peer likely diverged or died "
            "— rolling back to the last complete iteration")


class HostDropped(faultline.FaultInjected):
    """Injected death of this host at a collective call site."""


_DEFAULTS: Dict[str, float] = {"timeout_s": 0.0, "retries": 1,
                               "backoff_s": 0.25}
_defaults_lock = threading.Lock()


def configure(timeout_s: Optional[float] = None,
              retries: Optional[int] = None,
              backoff_s: Optional[float] = None) -> None:
    """Set the process-global watchdog defaults (Network::Init analog).
    Called at learner/dataset init from `tpu_collective_timeout_s` /
    `tpu_collective_retries`; explicit per-call arguments win."""
    with _defaults_lock:
        if timeout_s is not None:
            _DEFAULTS["timeout_s"] = max(float(timeout_s), 0.0)
        if retries is not None:
            _DEFAULTS["retries"] = max(int(retries), 0)
        if backoff_s is not None:
            _DEFAULTS["backoff_s"] = max(float(backoff_s), 0.0)


def defaults() -> Dict[str, float]:
    with _defaults_lock:
        return dict(_DEFAULTS)


def configure_from_config(config) -> None:
    """Apply `tpu_collective_timeout_s`/`tpu_collective_retries` from a
    Config.  The registry default -1 means UNSET — a booster
    constructed without these params never disturbs the process policy
    another live booster armed — while an explicit 0 really disables
    (deadline off / no retries).  The single owner of that convention;
    both wiring sites (GBDT init, distributed-dataset init) route
    through here."""
    t = float(config.tpu_collective_timeout_s)
    r = int(config.tpu_collective_retries)
    configure(timeout_s=t if t >= 0 else None,
              retries=r if r >= 0 else None)


def _run_with_deadline(fn: Callable, args, kwargs, name: str,
                       timeout_s: float, attempt: int) -> Any:
    """Run `fn` on a watchdog thread; raise CollectiveTimeout when it
    misses the deadline.  The thread is a daemon: a genuinely hung
    collective cannot be cancelled, only abandoned."""
    box: list = []

    def _target():
        try:
            box.append(("ok", fn(*args, **kwargs)))
        except BaseException as exc:  # noqa: BLE001 - re-raised on caller
            box.append(("err", exc))

    t = threading.Thread(target=_target, daemon=True,
                         name=f"collective-{name}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise CollectiveTimeout(name, timeout_s, attempt,
                                faultline.host_index())
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


def guarded_collective(fn: Callable, *args,
                       name: str = "collective",
                       point: Optional[str] = "collective_sync",
                       timeout_s: Optional[float] = None,
                       retries: Optional[int] = None,
                       backoff_s: Optional[float] = None,
                       local: bool = False,
                       **kwargs) -> Any:
    """Run one host-level collective under the watchdog.

    `local=True` marks a call that degenerated to an in-process
    identity (world size 1): the deadline thread is skipped — an
    identity cannot hang — but injection (hang -> simulated
    CollectiveTimeout, host_drop -> HostDropped) and retry stay live so
    single-process chaos runs exercise the same failure surface.
    `timeout_s`/`retries`/`backoff_s` default to the `configure`d
    process globals; timeout_s=0 disables the deadline."""
    cfg = defaults()
    timeout_s = cfg["timeout_s"] if timeout_s is None else float(timeout_s)
    retries = int(cfg["retries"] if retries is None else retries)
    backoff_s = float(cfg["backoff_s"] if backoff_s is None else backoff_s)
    me = faultline.host_index()
    attempt = 0
    while True:
        attempt += 1
        # flight recorder (always-on): the begin entry is what names
        # the hung site in a blackbox dump — a collective that never
        # returns leaves a span_begin with no span_end
        obs.flightrecorder.note("span_begin", f"collective/{name}",
                                attempt=attempt, host=me)
        try:
            try:
                drop = faultline.fire("host_drop", name=name, host=me)
            except (HostDropped, KeyboardInterrupt, SystemExit):
                raise
            except BaseException as exc:
                # normalize ANY armed host_drop exception — including a
                # custom exc= like ConnectionError — to the structured
                # type: a dropped host is never a transient failure, so
                # it must bypass the retry loop below
                raise HostDropped(str(exc)) from None
            if drop is not None:
                raise HostDropped(
                    f"injected host drop at collective {name!r} "
                    f"(host {me})")
            action = None
            if point is not None:
                action = faultline.fire(point, name=name, host=me)
            if action == "hang":
                if local or timeout_s <= 0:
                    # nothing real can hang (identity call) or no
                    # deadline is armed: simulate the expiry directly —
                    # a real hang with timeout_s=0 would block forever,
                    # which is exactly what the watchdog param exists
                    # to prevent
                    raise CollectiveTimeout(name, timeout_s, attempt, me)
                # exercise the REAL deadline machinery: a sleeper that
                # outlives the deadline stands in for the hung peer
                slack = timeout_s + 1.0
                return _run_with_deadline(
                    lambda: time.sleep(slack), (), {}, name, timeout_s,
                    attempt)
            t_wait = time.perf_counter()
            with obs.span(f"collective/{name}", attempt=attempt):
                if local or timeout_s <= 0:
                    result = fn(*args, **kwargs)
                else:
                    result = _run_with_deadline(fn, args, kwargs, name,
                                                timeout_s, attempt)
            _note_wait(name, time.perf_counter() - t_wait)
            obs.flightrecorder.note("span_end", f"collective/{name}",
                                    attempt=attempt, host=me)
            if attempt > 1:
                # a retried collective that finally succeeded is a
                # RECOVERY — the event PR 8's watchdogs had no way to
                # surface after the fact
                obs.REGISTRY.inc("lgbm_collective_recoveries_total",
                                 name=name)
                obs.event("collective_recovered", name=name,
                          attempts=attempt)
            return result
        except (CollectiveTimeout, HostDropped, KeyboardInterrupt,
                SystemExit) as exc:
            if isinstance(exc, CollectiveTimeout):
                obs.REGISTRY.inc(
                    "lgbm_collective_timeouts_total",
                    help="watchdog deadline expiries", name=name)
                obs.event("collective_timeout", name=name,
                          timeout_s=timeout_s, attempt=attempt)
                # the evidence of WHAT hung must outlive the process:
                # ring the transition and flush the blackbox before the
                # structured error starts unwinding the train loop
                obs.flightrecorder.note("watchdog", "collective_timeout",
                                        name=name, timeout_s=timeout_s,
                                        attempt=attempt, host=me)
                obs.flightrecorder.dump("collective_timeout", exc=exc)
            elif isinstance(exc, HostDropped):
                obs.REGISTRY.inc("lgbm_collective_host_drops_total",
                                 name=name)
                obs.event("host_dropped", name=name, host=me)
                obs.flightrecorder.note("watchdog", "host_dropped",
                                        name=name, host=me)
                obs.flightrecorder.dump("host_dropped", exc=exc)
            raise
        except Exception as exc:  # noqa: BLE001 - transient transport error
            obs.flightrecorder.note("watchdog", "collective_error",
                                    name=name, attempt=attempt,
                                    error=type(exc).__name__)
            if attempt > retries:
                raise
            obs.REGISTRY.inc("lgbm_collective_retries_total",
                             help="transient collective errors retried",
                             name=name)
            wait = backoff_s * (2 ** (attempt - 1))
            Log.warning(
                f"collective {name!r} failed on host {me} "
                f"({type(exc).__name__}: {exc}); retry {attempt}/{retries} "
                f"in {wait:g}s")
            if wait > 0:
                time.sleep(wait)
