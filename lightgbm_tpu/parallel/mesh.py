"""Device mesh plumbing: the TPU-native replacement for src/network.

The reference builds an all-to-all TCP/MPI mesh with hand-written
Bruck/recursive-halving/ring collectives (reference src/network/
network.cpp:68-318).  On TPU the transport and algorithm selection belong to
XLA: we declare a `jax.sharding.Mesh` with axes

  * 'hosts'   — the process/DCN tier (parallel/topology.py)
  * 'data'    — row shards (the reference's data_parallel machines)
  * 'feature' — feature shards (the reference's feature_parallel machines)

and express the collectives through the axis-addressed vocabulary in
`parallel/topology.py`, inside shard_map'ped growers.  `num_machines`/
`machines` config maps to the mesh shape; ICI vs DCN placement follows
the hosts axis.  This module keeps the process-group plumbing
(rendezvous, global/local array placement) and the ring cost models the
psum-vs-scatter decision is priced with.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_distributed_initialized = False
# a timed-out rendezvous cannot be re-entered: the watchdog abandons a
# thread that may STILL complete jax.distributed.initialize later, and
# jax refuses a second initialize() in the same process — so a failed
# init is terminal for this process, recorded here to fail retries with
# a structured message instead of jax's confusing "only once" error
_distributed_init_failed: Optional[str] = None


def init_multihost(machines: str = "", local_listen_port: int = 0,
                   num_machines: int = 1) -> bool:
    """Map the reference's machine-list network config onto jax.distributed.

    The reference rendezvouses an all-to-all TCP mesh from `machines` =
    "ip1:port1,ip2:port2,..." (reference src/network/linkers_socket.cpp:
    165-220).  The TPU equivalent: every host runs the same program and
    calls `jax.distributed.initialize(coordinator, num_processes,
    process_id)`; afterwards jax.devices() spans all hosts and the SAME
    mesh/shard_map code runs globally — collectives ride ICI within a
    slice and DCN across slices, placed by XLA instead of hand-built
    Bruck/recursive-halving rings.

    The first machine-list entry is the coordinator; this host's position
    in the list (matched by LIGHTGBM_TPU_HOST_IP or the entry whose port
    matches local_listen_port when unambiguous) is its process id.
    Returns True if distributed init ran.  Single-process setups (CI, one
    host) skip it — the in-process virtual mesh covers them.
    """
    global _distributed_initialized, _distributed_init_failed
    if _distributed_initialized:
        return True
    if _distributed_init_failed is not None:
        raise RuntimeError(
            "a previous multi-host rendezvous failed in this process "
            f"({_distributed_init_failed}); jax.distributed cannot be "
            "re-initialized — restart the process to rejoin the group")
    entries = [m.strip() for m in str(machines).split(",") if m.strip()]
    if len(entries) <= 1 or num_machines <= 1:
        return False
    import os

    coordinator = entries[0]
    my_ip = os.environ.get("LIGHTGBM_TPU_HOST_IP", "")
    pid = None
    if my_ip:
        for i, e in enumerate(entries):
            if e.split(":")[0] == my_ip:
                pid = i
                break
    if pid is None:
        env_pid = os.environ.get("LIGHTGBM_TPU_PROCESS_ID", "")
        if env_pid:
            pid = int(env_pid)
    if pid is None:
        raise ValueError(
            "multi-host init: cannot determine this host's position in "
            "`machines`; set LIGHTGBM_TPU_HOST_IP or "
            "LIGHTGBM_TPU_PROCESS_ID")
    from .collective import guarded_collective

    # the rendezvous is the group's first collective: a host that never
    # shows up would otherwise hang every peer in initialize() forever.
    # retries=0 — a torn partial rendezvous cannot be re-entered (the
    # coordinator keeps half-joined state); the timeout surfaces it as
    # a structured failure instead, and the failure is recorded as
    # TERMINAL for this process (see _distributed_init_failed)
    try:
        guarded_collective(
            jax.distributed.initialize, name="init_multihost", retries=0,
            coordinator_address=coordinator, num_processes=len(entries),
            process_id=pid)
    except BaseException as exc:
        _distributed_init_failed = f"{type(exc).__name__}: {exc}"
        raise
    _distributed_initialized = True
    return True


def available_devices() -> int:
    return len(jax.devices())


def put_global(arr, sharding: NamedSharding):
    """device_put that also works when the mesh spans PROCESSES.

    Single-process: plain `jax.device_put`.  Multi-process (after
    `init_multihost`): `jax.device_put` rejects non-fully-addressable
    shardings, so build the global array from a callback — every process
    holds the same FULL host array (the reference's all-data-on-all-
    machines ingest; pre-partitioned loading shards earlier, at bin time)
    and contributes the shards its local devices own.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def put_local(local_arr, sharding: NamedSharding, global_shape) -> "jax.Array":
    """Build a global array from PER-PROCESS local shards.

    The pre-partitioned ingest (reference loader pre_partition: each
    machine holds only its own rows, dataset_loader.cpp row
    distribution): every process passes just the rows its devices own,
    laid out in its local order; jax maps them onto the process's
    addressable shards of the global array.  Complements `put_global`,
    whose contract is the opposite (every process holds the FULL host
    array)."""
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(local_arr), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_arr), global_shape)


def make_mesh(num_data_shards: int = 1, num_feature_shards: int = 1,
              devices: Optional[Sequence] = None,
              num_hosts: int = 0) -> Mesh:
    """The (hosts, data, feature) mesh — compatibility shim over
    `topology.make_topology`; new call sites should build the Topology
    directly and keep it (the mesh alone loses the shard counts)."""
    from .topology import make_topology

    return make_topology(num_data_shards=num_data_shards,
                         num_feature_shards=num_feature_shards,
                         num_hosts=num_hosts, devices=devices).mesh


def shard_rows(n: int, num_shards: int) -> int:
    """Rows per shard, padded so every shard is equal-size."""
    return (n + num_shards - 1) // num_shards


# --------------------------------------------------------------------------
# Elastic-resume placement (ISSUE 8): a checkpoint taken at P hosts holds
# per-host slices of the GLOBAL row axis; resuming at P' hosts needs (a)
# the global row offset of every checkpointed host to reassemble the
# global buffers, and (b) this process's offset in the NEW topology to
# slice its local rows back out.  Row order is process order in both
# directions (the put_local contract), so a reassemble+slice round trip
# is byte-exact.
# --------------------------------------------------------------------------

def row_offsets(rows_per_host: Sequence[int]) -> Tuple[np.ndarray, int]:
    """Per-host global row offsets (process order) and the total count."""
    rows = np.asarray(list(rows_per_host), np.int64)
    offsets = np.concatenate([[0], np.cumsum(rows)[:-1]]).astype(np.int64)
    return offsets, int(rows.sum())


def local_row_offset(local_n: int) -> Tuple[int, int]:
    """(this process's global row offset, global total rows) in the LIVE
    topology — an allgather of the per-process local row counts, ridden
    through the collective watchdog.  Identity (0, local_n) when the
    process group is 1."""
    import jax

    if jax.process_count() == 1:
        return 0, int(local_n)
    from .topology import host_allgather

    lens = host_allgather(np.asarray([int(local_n)], np.int64),
                          name="row_offsets")[:, 0]
    offsets, total = row_offsets(lens)
    return int(offsets[jax.process_index()]), total


# --------------------------------------------------------------------------
# Aggregation cost model (tpu_hist_agg): predicted per-shard ICI receive
# bytes for the two histogram aggregation modes.  Bandwidth-optimal ring
# algorithms (the form XLA lowers to on ICI, and the reference's own
# Network::ReduceScatter / recursive-halving implementations,
# src/network/network.cpp:68-318) move:
#
#   all-reduce (psum)          2 * (P-1)/P * nbytes   per shard
#       = reduce-scatter + all-gather; every shard RECEIVES the whole
#       aggregated array again in the second phase
#   reduce-scatter (scatter)       (P-1)/P * nbytes   per shard
#       = the first phase alone; each shard keeps only its 1/P slice
#
# so scatter halves the wire traffic AND shrinks what lands in HBM by P.
# tools/perf_probe.py comm prints these next to measured wall times; the
# PERF_NOTES round-9 bytes-moved model cites them.
# --------------------------------------------------------------------------

def allreduce_recv_bytes(nbytes: int, shards: int) -> int:
    """Per-shard receive bytes of a ring all-reduce (psum) of `nbytes`."""
    if shards <= 1:
        return 0
    return 2 * (shards - 1) * nbytes // shards


def reduce_scatter_recv_bytes(nbytes: int, shards: int) -> int:
    """Per-shard receive bytes of a ring reduce-scatter (psum_scatter)."""
    if shards <= 1:
        return 0
    return (shards - 1) * nbytes // shards


# --------------------------------------------------------------------------
# Tiered (ICI vs DCN) cost model: a reduction over ROW_AXES on an
# (hosts, data, feature) mesh lowers hierarchically — reduce-scatter
# inside each host's ICI ring, the cross-host leg over DCN on the 1/D
# partials, then an ICI all-gather to rebuild the full array where the
# op is an all-reduce.  Splitting the predicted receive bytes by tier
# prices the psum-vs-scatter decision per topology: DCN bandwidth is
# ~an order of magnitude below ICI, so the DCN leg dominates wall time
# even though it moves the fewest bytes.  perf_probe comm prints both
# legs next to measured walls.
# --------------------------------------------------------------------------

def tiered_allreduce_recv_bytes(nbytes: int, hosts: int,
                                devices_per_host: int) -> Tuple[int, int]:
    """(ICI, DCN) per-shard receive bytes of a hierarchical all-reduce:
    ICI reduce-scatter + DCN all-reduce of the 1/D partials + ICI
    all-gather.  Degenerates to the flat ring models at either tier=1."""
    d, h = max(devices_per_host, 1), max(hosts, 1)
    # ICI reduce-scatter (d-1)/d + ICI all-gather (d-1)/d = the flat
    # all-reduce ring's bytes; the DCN tier all-reduces the 1/d partials
    ici = allreduce_recv_bytes(nbytes, d)
    dcn = allreduce_recv_bytes(nbytes // d, h)
    return ici, dcn


def tiered_reduce_scatter_recv_bytes(nbytes: int, hosts: int,
                                     devices_per_host: int) -> Tuple[int, int]:
    """(ICI, DCN) per-shard receive bytes of a hierarchical
    reduce-scatter: the ICI phase, then the DCN reduce-scatter of each
    host's 1/D partials down to the final 1/(H*D) slices."""
    d, h = max(devices_per_host, 1), max(hosts, 1)
    ici = reduce_scatter_recv_bytes(nbytes, d)
    dcn = reduce_scatter_recv_bytes(nbytes // d, h)
    return ici, dcn
