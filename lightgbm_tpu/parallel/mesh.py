"""Device mesh plumbing: the TPU-native replacement for src/network.

The reference builds an all-to-all TCP/MPI mesh with hand-written
Bruck/recursive-halving/ring collectives (reference src/network/
network.cpp:68-318).  On TPU the transport and algorithm selection belong to
XLA: we declare a `jax.sharding.Mesh` with axes

  * 'data'    — row shards (the reference's data_parallel machines)
  * 'feature' — feature shards (the reference's feature_parallel machines)

and express the collectives as `lax.psum` / `lax.all_gather` inside
shard_map'ped growers.  `num_machines`/`machines` config maps to the mesh
shape; ICI vs DCN placement is XLA's concern.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_data_shards: int = 1, num_feature_shards: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = num_data_shards * num_feature_shards
    if need > len(devices):
        raise ValueError(
            f"mesh {num_data_shards}x{num_feature_shards} needs {need} "
            f"devices, have {len(devices)}")
    dev = np.array(devices[:need]).reshape(num_data_shards, num_feature_shards)
    return Mesh(dev, ("data", "feature"))


def shard_rows(n: int, num_shards: int) -> int:
    """Rows per shard, padded so every shard is equal-size."""
    return (n + num_shards - 1) // num_shards
