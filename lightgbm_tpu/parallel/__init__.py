from .mesh import make_mesh, shard_rows
from .data_parallel import make_data_parallel_grower
from .strategies import (make_strategy_grower, resolve_tree_learner,
                         bins_sharding, rows_sharding)
