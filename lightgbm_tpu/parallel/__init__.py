from .mesh import make_mesh, shard_rows
from .data_parallel import make_data_parallel_grower
