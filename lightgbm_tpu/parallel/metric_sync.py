"""Cross-process reduction of metric sufficient statistics.

TPU-native analog of the reference's ``Network::GlobalSyncUpBySum``
helpers (``/root/reference/include/LightGBM/network.h:168-275``) behind
SURVEY §2.6's "metrics are distribution-aware" posture.  In a
``jax.distributed`` run each process may hold only its local rows of a
(pre-partitioned) train or validation set; a metric computed from the
host-local score vector then disagrees across ranks, and early stopping
can fire at different iterations on different ranks — which diverges the
ensemble or deadlocks the next collective.  Metrics therefore reduce
their SUFFICIENT STATISTICS across processes before the final division:

  - averaged losses reduce the (weighted loss sum, weight sum) pair
    (`sync_sums`);
  - AUC / auc_mu need global rank statistics, reduced by an exact merge
    of the per-rank (score, label, weight) arrays (`sync_concat` — the
    ragged allgather below);
  - rank metrics reduce (per-position weighted DCG sums, query-weight
    sum), again plain sums.

Every helper is an identity when ``jax.process_count() == 1`` — the
single-process hot path pays one attribute read.  The reduction is also
SAFE in the all-data-on-all-machines ingest mode (`put_global`'s
replicated-host contract) for RATIO statistics: duplicating a full
sample P times changes neither a weighted average (numerator and
denominator both scale by P) nor a pairwise/positional rank statistic,
so ranks agree either way.  SUM-type metrics (no denominator — e.g.
``gamma_deviance``'s 2x summed deviance) are the exception: summing the
local sums of P replicated ranks reports P x the true value, so they
must reduce only when each rank actually holds a DISTINCT row shard.
That predicate is ``topology.rows_partitioned()`` — derived from where
the live learner placed its rows (put_local vs put_global), not from
echoing the ``pre_partition`` config flag, so a topology change cannot
silently desynchronize the gate from reality.

Collective discipline: these are process-level collectives — every rank
must call them in the same order.  The engine's eval cadence is
config-driven and identical on all ranks; ad-hoc single-rank calls of
``Booster.eval*`` inside a live multi-process group would deadlock, the
same contract as the reference's ``Network::Allreduce``.  Custom
``feval`` callables run host-local and are NOT reduced.

Every entry point runs under the `collective.guarded_collective`
watchdog (ISSUE 8): a hung peer becomes a structured
`CollectiveTimeout` after `tpu_collective_timeout_s` instead of a
silent group-wide hang, transient transport errors retry with backoff,
and the ``collective_sync``/``host_drop`` fault points fire once per
logical collective — ALSO on the world-size-1 identity path, so
single-process chaos runs exercise the same failure surface.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from .collective import guarded_collective


def process_count() -> int:
    import jax

    return jax.process_count()


def _allgather(arr: np.ndarray) -> np.ndarray:
    """Stack a same-shaped host array from every process: [P, *shape].

    Module-level indirection so tests can monkeypatch a fake world.
    The transport is the topology layer's bitsafe gather: 64-bit
    payloads ride uint32 views (process_allgather's jnp transport would
    demote f64/i64 to 32 bits whenever jax_enable_x64 is off), so the
    exact-merge contract holds regardless of x64 mode.
    """
    from .topology import _bitsafe_gather

    return _bitsafe_gather(np.ascontiguousarray(arr))


def sync_sums(vals: Sequence[float]) -> np.ndarray:
    """Elementwise sum across processes of a small f64 vector."""
    v = np.asarray(vals, np.float64)
    if process_count() == 1:
        return guarded_collective(lambda: v, name="sync_sums", local=True)
    return guarded_collective(lambda: _allgather(v).sum(axis=0),
                              name="sync_sums")


def sync_concat(*arrays: np.ndarray) -> Tuple[np.ndarray, ...]:
    """Concatenate per-rank 1-D arrays across processes, rank order.

    Ranks may hold DIFFERENT lengths (pre-partitioned shards are rarely
    equal): lengths are allgathered first, every array is padded to the
    max, and the pads are stripped after the gather — allgather itself
    requires congruent shapes.  All inputs must share this rank's local
    length (they are parallel columns of one local table).
    """
    if process_count() == 1:
        return guarded_collective(
            lambda: tuple(np.asarray(a, np.float64).ravel()
                          for a in arrays),
            name="sync_concat", local=True)
    arrs = [np.ascontiguousarray(np.asarray(a, np.float64).ravel())
            for a in arrays]
    n_local = arrs[0].shape[0]
    for a in arrs[1:]:
        if a.shape[0] != n_local:
            raise ValueError("sync_concat inputs must share the local "
                             f"length: {a.shape[0]} != {n_local}")

    def _merge() -> Tuple[np.ndarray, ...]:
        lens = _allgather(np.asarray([n_local], np.int64))[:, 0]
        n_max = int(lens.max()) if len(lens) else 0
        out = []
        for a in arrs:
            padded = np.zeros(n_max, np.float64)
            padded[:n_local] = a
            g = _allgather(padded)  # [P, n_max]
            out.append(np.concatenate([g[p, :int(lens[p])]
                                       for p in range(len(lens))])
                       if n_max else np.zeros(0, np.float64))
        return tuple(out)

    # one watchdog spans the whole ragged merge: its inner allgathers
    # are one logical collective (ranks must enter/leave together), so
    # a retry must redo the lens+payload sequence from the top
    return guarded_collective(_merge, name="sync_concat")
