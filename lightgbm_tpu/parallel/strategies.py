"""Parallel tree-learner strategies over a device mesh.

The reference selects its learner in a factory keyed on (tree_learner,
device_type) (reference src/treelearner/tree_learner.cpp:13-36) and the
parallel learners are templates over the base learner (parallel_tree_
learner.h:25-187) so device x {feature,data,voting} compose.  Here the
device learner IS the base grower and each strategy is a shard_map wrapping
of the same grower body over a `jax.sharding.Mesh` axis:

  serial   — plain jit, one device
  data     — rows sharded over 'data'; histogram aggregation per
             GrowerParams.hist_agg: full psum, or reduce-scattered
             feature slices + best-split sync
             (DataParallelTreeLearner, data_parallel_tree_learner.cpp:149)
  feature  — features sharded over 'feature'; all_gather + shared
             tie-break of per-shard bests (FeatureParallelTreeLearner,
             feature_parallel_tree_learner.cpp:23-75)
  voting   — rows sharded; top-k voted features' histograms psum'ed (or
             psum_scatter'ed under hist_agg=scatter)
             (VotingParallelTreeLearner, voting_parallel_tree_learner.cpp)

All four present the SAME call signature
    grow(bins_t, grad, hess, row_mask, feature_mask, meta, key) -> out dict
so the driver/learner code is strategy-agnostic.

Collectives dtype note: under the quantized histogram precisions
(tpu_hist_precision=int16|int8) the `data` axis psums int32 histograms.
Integer psum is associative, so data-parallel split decisions are
bit-identical across any shard count (the f32/hilo modes only promise
~ulp agreement); the per-shard contraction additionally reads a stats
operand 2-4x narrower than hilo's — see ops/histogram.py and
docs/USAGE.md "Quantized training".
"""

from __future__ import annotations

from typing import Optional

import inspect

import jax

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # older releases ship it under jax.experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SM_PARAMS = frozenset(inspect.signature(_shard_map_impl).parameters)


def shard_map(f, **kwargs):
    """Version-compat shard_map: newer jax renamed check_rep->check_vma;
    translate so one spelling works against either signature."""
    if "check_vma" in kwargs and "check_vma" not in _SM_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map_impl(f, **kwargs)


import functools

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.grower import GrowerParams, make_grower
from ..utils.compile_ledger import ledger_jit
from .topology import FEATURE, ROW_AXES

META_KEYS = ("num_bin", "missing_type", "default_bin", "monotone", "penalty",
             "is_categorical", "cegb_coupled", "cegb_lazy", "bundle_idx",
             "bin_offset", "needs_fix", "mode_flags")

_CANON = {
    "serial": "serial",
    "data": "data", "data_parallel": "data",
    "feature": "feature", "feature_parallel": "feature",
    "voting": "voting", "voting_parallel": "voting",
    # 2-D composition (the reference's device x parallel template nesting,
    # parallel_tree_learner.h:25-187): rows on 'data' x features on
    # 'feature' in one mesh
    "data_feature": "data_feature", "feature_data": "data_feature",
    "data_feature_parallel": "data_feature",
}


def resolve_tree_learner(name: str) -> str:
    """Canonical strategy name (reference tree_learner config aliases,
    src/io/config.cpp ParseTreeLearnerType)."""
    try:
        return _CANON[str(name).strip().lower()]
    except KeyError:
        raise ValueError(f"unknown tree_learner {name!r}") from None


def pool_partition_spec(strategy: str, scatter: bool) -> P:
    """Partition spec of the GLOBAL [L, G, B, 3] histogram pool under
    `strategy` — the donated external pool's placement.  The column axis
    shards exactly like the slices the grower keeps per shard: the full
    width under psum (replicated), the contiguous G/P slice under
    scatter, the feature slice under feature sharding (feature-major /
    data-minor in the 2-D mesh).  Row shards address the (hosts, data)
    axis PRODUCT — the linearized index equals the old flat data-axis
    index, so placement is unchanged on a 1-host mesh."""
    if strategy in ("data", "voting"):
        return P(None, ROW_AXES) if scatter else P()
    if strategy == "feature":
        return P(None, FEATURE)
    if strategy == "data_feature":
        return (P(None, (FEATURE,) + ROW_AXES) if scatter
                else P(None, FEATURE))
    return P()


def make_strategy_grower(params: GrowerParams, num_features: int,
                         strategy: str, mesh: Optional[Mesh] = None,
                         voting_k: int = 20,
                         num_columns: Optional[int] = None,
                         debug_hist: bool = False,
                         external_pool: bool = False):
    """Grower for `strategy`; num_features is the GLOBAL (padded) count;
    num_columns the bin-matrix column count (< num_features under EFB).

    debug_hist adds a "root_hist" output (the GPU_DEBUG_COMPARE analog,
    reference gpu_tree_learner.cpp:995-1020): per-shard LOCAL in voting
    mode (out axis 0 stacks shards), psum'd/replicated in data mode, the
    feature slice stacked to global width in feature modes.

    external_pool adds the donated 8th `pool` argument (ops/grower.py
    make_grower) — the global [L, G, B, 3] pool placed per
    `pool_partition_spec` and rewritten in place every call.  Strategy
    growers are memoized like the base grower: an identical configuration
    returns the SAME jitted callable, so repeat Booster constructions
    reuse compiled executables instead of re-tracing."""
    return _build_strategy_grower(params, num_features, strategy, mesh,
                                  voting_k, num_columns, debug_hist,
                                  external_pool)


def _strategy_jit(fn, strategy: str, external_pool: bool):
    """The ledgered jit site for one sharded strategy (donating the
    external pool when present)."""
    kw = {"donate_argnums": (7,)} if external_pool else {}
    return ledger_jit(fn, site=f"grower.{strategy}", **kw)


# bounded like ops/grower.py:_build_grower: the key pins Mesh/device
# objects and shape-derived params, so cap retention instead of growing
# one compiled strategy grower per distinct shape forever
@functools.lru_cache(maxsize=64)
def _build_strategy_grower(params, num_features, strategy, mesh,
                           voting_k, num_columns, debug_hist,
                           external_pool):
    if strategy == "serial" or mesh is None:
        return make_grower(params, num_features, num_columns=num_columns,
                           debug_hist=debug_hist,
                           external_pool=external_pool)

    meta_spec = {k: P() for k in META_KEYS}
    base_out = {"records": P(), "leaf_output": P(), "leaf_cnt": P(),
                "leaf_sum_h": P()}
    if params.has_cegb:
        # coupled CEGB composes with the parallel learners (the split
        # decisions are globally identical, so `used` stays replicated);
        # lazy CEGB is serial-only and never reaches here
        meta_spec["cegb_used"] = P()
        base_out["cegb_used"] = P()
    if params.has_sparse:
        # the per-shard COO tables shard their LEADING axis over 'data'
        # (each device holds only its own [1, Gs, M] block — replicating
        # a feature whose purpose is saving HBM would defeat it); the
        # small per-feature vectors replicate
        for k in ("is_sparse", "sparse_slot", "dense_col", "dense_ref",
                  "hist_perm"):
            meta_spec[k] = P()
        meta_spec["sparse_idx"] = P(ROW_AXES)
        meta_spec["sparse_bin"] = P(ROW_AXES)
    scatter = params.hist_agg == "scatter"
    if scatter and params.has_bundles:
        # static shard -> feature-ids table for the scattered EFB search
        # (bundle columns != features); tiny, replicated
        meta_spec["scatter_feat"] = P()
    pool_spec = pool_partition_spec(strategy, scatter)
    if strategy in ("data", "voting"):
        nshards = mesh.shape["hosts"] * mesh.shape["data"]
        grow = make_grower(
            params, num_features, data_axis=ROW_AXES,
            voting_k=(voting_k if strategy == "voting" else 0),
            num_shards=nshards, jit=False, num_columns=num_columns,
            debug_hist=debug_hist, external_pool=external_pool)
        out_specs = {**base_out, "leaf_ids": P(ROW_AXES)}
        if external_pool:
            out_specs["pool"] = pool_spec
        if debug_hist:
            # voting keeps pools local -> stack shards on axis 0; data
            # mode under psum replicates the full histogram on every
            # shard, under scatter each shard holds its contiguous
            # feature slice (stacking over 'data' reassembles the global
            # histogram — and the per-shard slice width IS the
            # no-global-histogram assertion hook for tests)
            out_specs["root_hist"] = (P(ROW_AXES)
                                      if strategy == "voting" or scatter
                                      else P())
        in_specs = (P(None, ROW_AXES), P(ROW_AXES), P(ROW_AXES),
                    P(ROW_AXES), P(), meta_spec, P())
        if external_pool:
            in_specs = in_specs + (pool_spec,)
        fn = shard_map(
            grow, mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False)
        return _strategy_jit(fn, strategy, external_pool)
    if strategy == "feature":
        nshards = mesh.shape["feature"]
        if num_features % nshards != 0:
            raise ValueError(
                f"feature count {num_features} must be padded to a multiple "
                f"of the feature-shard count {nshards}")
        f_local = num_features // nshards
        grow = make_grower(params, f_local, feature_axis=FEATURE,
                           jit=False, debug_hist=debug_hist,
                           external_pool=external_pool)
        # bins REPLICATED (P()), like the reference feature-parallel mode
        # where every machine holds all data (feature_parallel_tree_
        # learner.cpp:55-71): each shard histograms only its own feature
        # slice but partitions rows from the full local matrix, so no
        # per-split column broadcast is needed — the only collective left
        # is the all_gather of per-shard best gains
        out_specs = {**base_out, "leaf_ids": P()}
        if external_pool:
            out_specs["pool"] = pool_spec
        if debug_hist:
            out_specs["root_hist"] = P(FEATURE)
        in_specs = (P(), P(), P(), P(), P(), meta_spec, P())
        if external_pool:
            in_specs = in_specs + (pool_spec,)
        fn = shard_map(
            grow, mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False)
        return _strategy_jit(fn, strategy, external_pool)
    if strategy == "data_feature":
        f_shards = mesh.shape["feature"]
        d_shards = mesh.shape["hosts"] * mesh.shape["data"]
        if num_features % f_shards != 0:
            raise ValueError(
                f"feature count {num_features} must be padded to a multiple "
                f"of the feature-shard count {f_shards}")
        f_local = num_features // f_shards
        grow = make_grower(params, f_local, data_axis=ROW_AXES,
                           feature_axis=FEATURE, num_shards=d_shards,
                           jit=False, debug_hist=debug_hist,
                           external_pool=external_pool)
        # rows shard over (hosts, data); the bin matrix is [F_global,
        # n_local] per device (features replicated within a row shard so
        # the partition reads the full matrix, like the 1-D feature
        # mode); histograms psum over the row axes, bests all_gather
        # over 'feature'
        out_specs = {**base_out, "leaf_ids": P(ROW_AXES)}
        if external_pool:
            out_specs["pool"] = pool_spec
        if debug_hist:
            # stack feature slices to global; under scatter each feature
            # shard's slice is further scattered over the row axes
            # (feature-major, row-minor — exactly the global feature
            # order)
            out_specs["root_hist"] = (P((FEATURE,) + ROW_AXES) if scatter
                                      else P(FEATURE))
        in_specs = (P(None, ROW_AXES), P(ROW_AXES), P(ROW_AXES),
                    P(ROW_AXES), P(), meta_spec, P())
        if external_pool:
            in_specs = in_specs + (pool_spec,)
        fn = shard_map(
            grow, mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False)
        return _strategy_jit(fn, strategy, external_pool)
    raise ValueError(f"unknown strategy {strategy!r}")


def bins_sharding(mesh: Mesh, strategy: str) -> NamedSharding:
    """Sharding for the transposed [F, n_pad] bin matrix under `strategy`."""
    if strategy in ("data", "voting", "data_feature"):
        return NamedSharding(mesh, P(None, ROW_AXES))
    if strategy == "feature":
        # replicated: every shard partitions rows from the full matrix
        # (the reference's all-data-on-all-machines feature mode)
        return NamedSharding(mesh, P())
    raise ValueError(strategy)


def rows_sharding(mesh: Mesh, strategy: str) -> NamedSharding:
    """Sharding for [n_pad] per-row vectors under `strategy`."""
    if strategy in ("data", "voting", "data_feature"):
        return NamedSharding(mesh, P(ROW_AXES))
    return NamedSharding(mesh, P())
