"""One declarative topology: the (hosts, data, feature) mesh and the
complete collective vocabulary, each written exactly once.

The reference framework talks to its network through one `Network`
facade (reference include/LightGBM/network.h): every tree learner calls
the same Allreduce/Allgather entry points and the transport underneath
is a detail.  Before this module the TPU graft had drifted into two
parallel stacks — single-host `shard_map` strategies whose collectives
named a bare "data" axis, and a bolted-on multihost `pre_partition`
path of hand-rolled `process_allgather` calls — so the same logical
reduction was written once per call site and the multihost path had to
refuse whatever the single-host path happened to express differently
(feature sharding, EFB).  This module is the single Network analog:

* **The mesh.**  `make_topology` builds one `jax.sharding.Mesh` over
  named axes ``("hosts", "data", "feature")``.  The hosts axis is the
  process boundary (DCN); data and feature subdivide each host's local
  devices (ICI).  A single-process run simply has a size-1 hosts axis —
  the SAME specs, growers, and collectives lower for 1 host or a pod,
  which is what makes the (hosts x devices) bitwise grid testable on
  one CPU process.  Row-sharded arrays partition over the axis TUPLE
  ``ROW_AXES = ("hosts", "data")``: jax collectives accept tuple axis
  names and reduce/index over their product in row-major order, so the
  linearized row-shard index equals the old flat data-axis index and
  device placement is unchanged — bitwise contracts survive the
  relabeling by construction.

* **Device collectives** (`axis_psum`, `axis_psum_scatter`,
  `axis_all_gather`, `axis_index`, `axis_best_split_sync`): the traced
  vocabulary growers use inside shard_map.  These are the ONLY call
  sites of the raw `lax` collectives in the package — graftlint rule
  family T5xx (tools/graftlint/collectives.py) holds every other module
  to that, the same way J2xx holds jit sites to the CompileLedger.
  Traced ops cannot hang a watchdog thread (the deadline belongs to the
  dispatch that runs the program), so the host-side entry points below
  carry the guard instead.

* **Host collectives** (`host_allgather`, `host_sum`,
  `ragged_all_gather`): the process-level exchanges (bin finding, EFB
  planning, metric sync, checkpoint barriers, leaf-id reassembly), each
  wrapped ONCE by the PR-8 `guarded_collective` watchdog — callers name
  the logical collective and fault point but never re-wrap.  64-bit
  payloads travel as uint32 views (bit-exact; `process_allgather` rides
  jnp arrays, which demote f64/i64 whenever x64 is off), and
  `ragged_all_gather` owns the lens-then-padded-block idiom that
  `find_bundles_multihost`, `gather_row_samples`, and `sync_concat`
  each used to hand-roll.

* **Row ownership.**  The learner `activate()`s its topology; derived
  predicates (`rows_partitioned`) replace configuration reads — a
  metric asking "does each rank hold a distinct row shard?" gets the
  answer from where the rows were actually placed (`put_local` vs
  `put_global`), not from echoing the `pre_partition` flag, so the
  `gamma_deviance` class of over-reduction bugs cannot recur when a
  new axis changes what the flag implies.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .collective import guarded_collective

# the canonical axis names; every PartitionSpec and collective in the
# package addresses these
HOSTS = "hosts"
DATA = "data"
FEATURE = "feature"
# row-sharded arrays partition over the (hosts, data) product: hosts is
# the DCN tier, data the ICI tier within each host
ROW_AXES: Tuple[str, str] = (HOSTS, DATA)

AxisNames = Union[str, Tuple[str, ...]]


class Topology(NamedTuple):
    """One resolved training topology.

    `data_shards` is the TOTAL row-shard count (= hosts x per-host row
    shards) — the number the collectives reduce over and the histogram
    column axis pads to; `mesh` splits it as (hosts, data) so the DCN
    tier is addressable by name.
    """
    mesh: Mesh
    hosts: int
    data_shards: int        # total row shards across all hosts
    feature_shards: int
    partitioned_rows: bool  # rows placed per-process (put_local)

    @property
    def local_data_shards(self) -> int:
        """Row shards per host (the mesh's 'data' axis size)."""
        return self.data_shards // self.hosts


def resolve_hosts(num_hosts: int = 0) -> int:
    """The hosts-axis size: an explicit positive value wins (simulated
    multihost grids on one process), else the live process count."""
    if num_hosts > 0:
        return int(num_hosts)
    return jax.process_count()


def make_topology(num_data_shards: int = 1, num_feature_shards: int = 1,
                  num_hosts: int = 0, partitioned_rows: bool = False,
                  devices: Optional[Sequence] = None) -> Topology:
    """Build the (hosts, data, feature) mesh over the leading devices.

    jax.devices() is process-major, so reshaping to (hosts, data,
    feature) gives each host a contiguous (data, feature) block of its
    own local devices — exactly the layout `put_local` needs for
    pre-partitioned rows, and the identical device order the old flat
    (data, feature) mesh produced.
    """
    hosts = resolve_hosts(num_hosts)
    if num_data_shards % hosts != 0:
        raise ValueError(
            f"num_machines={num_data_shards * num_feature_shards} row "
            f"shards must split evenly across the {hosts} hosts "
            f"(row shards {num_data_shards} % hosts {hosts} != 0)")
    devices = list(devices if devices is not None else jax.devices())
    need = num_data_shards * num_feature_shards
    if need > len(devices):
        raise ValueError(
            f"mesh {num_data_shards}x{num_feature_shards} needs {need} "
            f"devices, have {len(devices)}")
    dev = np.array(devices[:need]).reshape(
        hosts, num_data_shards // hosts, num_feature_shards)
    return Topology(mesh=Mesh(dev, (HOSTS, DATA, FEATURE)),
                    hosts=hosts,
                    data_shards=int(num_data_shards),
                    feature_shards=int(num_feature_shards),
                    partitioned_rows=bool(partitioned_rows))


# --------------------------------------------------------------------------
# active topology: the learner registers what it built so row-ownership
# questions are answered from placement, not configuration
# --------------------------------------------------------------------------

_ACTIVE: Optional[Topology] = None


def activate(topology: Optional[Topology]) -> None:
    """Register the live training topology (learner init; None clears)."""
    global _ACTIVE
    _ACTIVE = topology


def active() -> Optional[Topology]:
    return _ACTIVE


def rows_partitioned() -> bool:
    """Does each PROCESS hold a distinct row shard (so cross-rank sums
    of row statistics are partial and must reduce)?  Derived from how
    the live learner placed its rows; False with no live topology or a
    single process — replicated ranks already hold global sums."""
    t = _ACTIVE
    return bool(t is not None and t.partitioned_rows
                and jax.process_count() > 1)


# --------------------------------------------------------------------------
# device collectives: the traced vocabulary (inside shard_map).  Thin by
# design — the value is the single site (T5xx) and the axis-tuple
# contract, not abstraction.
# --------------------------------------------------------------------------

def axis_psum(x, axes: AxisNames):
    """All-reduce sum over the named axes (their product for a tuple)."""
    return jax.lax.psum(x, axes)


def axis_psum_scatter(x, axes: AxisNames, scatter_dimension: int,
                      tiled: bool = True):
    """Reduce-scatter over the named axes: each shard keeps only its
    1/P slice of `scatter_dimension` — half the all-reduce's receive
    bytes, 1/P of its HBM (parallel/mesh.py cost models)."""
    return jax.lax.psum_scatter(x, axes,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)


def axis_pmax(x, axes: AxisNames):
    """All-reduce max over the named axes (quantization scale sync)."""
    return jax.lax.pmax(x, axes)


def axis_all_gather(x, axes: AxisNames, **kwargs):
    """All-gather over the named axes (stacks on a new leading axis by
    default, jax.lax.all_gather semantics)."""
    return jax.lax.all_gather(x, axes, **kwargs)


def axis_index(axes: AxisNames):
    """This shard's linearized index along the named axes (row-major
    over a tuple — for ROW_AXES that is the flat row-shard id, equal to
    the old single-axis 'data' index)."""
    return jax.lax.axis_index(axes)


def axis_size(axes: AxisNames) -> int:
    """Static size of the named axes' product under the ambient mesh
    (the classic psum-of-ones spelling; constant-folds at trace time)."""
    return jax.lax.psum(1, axes)


def axis_best_split_sync(axes: AxisNames, gain, feature, threshold,
                         payload: Any):
    """SyncUpGlobalBestSplit over named axes (reference
    parallel_tree_learner.h:190-213): all-gather ONE tiny per-shard best
    record, pick the winner with the shared deterministic tie-break
    (split.argbest: highest gain, then lowest feature id, then lowest
    threshold bin), and broadcast the winner's payload leaves from the
    owning shard via masked psum.  Returns (gain, feature, threshold,
    payload) of the winner; payload is any pytree of per-shard arrays.
    """
    from ..ops.split import argbest

    gains = axis_all_gather(gain, axes)                       # [P]
    feats = axis_all_gather(jnp.asarray(feature).astype(jnp.int32), axes)
    thrs = axis_all_gather(threshold, axes)
    winner = argbest(gains, feats, thrs)
    own = axis_index(axes) == winner

    def pick(x):
        return axis_psum(jnp.where(own, x, jnp.zeros_like(x)), axes)

    picked = jax.tree_util.tree_map(pick, payload)
    return gains[winner], feats[winner], thrs[winner], picked


# --------------------------------------------------------------------------
# host collectives: process-level exchanges, each under ONE watchdog
# --------------------------------------------------------------------------

def host_count() -> int:
    return jax.process_count()


def _bitsafe_gather(arr: np.ndarray) -> np.ndarray:
    """process_allgather preserving 64-bit payloads bit-exactly.

    The transport rides jnp arrays, which demote f64/i64 to 32 bits
    whenever jax_enable_x64 is off (the default outside deterministic
    mode) — so 8-byte dtypes travel as uint32 views (last axis doubled)
    and reassemble on arrival.  Returns [P, *shape].
    """
    from jax.experimental import multihost_utils

    arr = np.ascontiguousarray(arr)
    if arr.dtype.itemsize == 8:
        wide = arr.reshape(arr.shape or (1,))
        out = np.asarray(multihost_utils.process_allgather(
            wide.view(np.uint32)))
        out = np.ascontiguousarray(out).view(arr.dtype)
        return out.reshape((out.shape[0],) + arr.shape)
    return np.asarray(multihost_utils.process_allgather(arr))


def host_allgather(arr: np.ndarray, *, name: str,
                   point: Optional[str] = "collective_sync",
                   tiled: bool = False) -> np.ndarray:
    """Gather one same-shaped host array from every process under the
    watchdog: [P, *shape] (or concatenated along axis 0 when `tiled`).
    World-size-1 groups take the identity path but still fire the fault
    point, so single-process chaos runs exercise this surface."""
    arr = np.ascontiguousarray(arr)
    if jax.process_count() == 1:
        out = guarded_collective(lambda: arr, name=name, point=point,
                                 local=True)
        return out if tiled else out[None]
    out = guarded_collective(lambda: _bitsafe_gather(arr), name=name,
                             point=point)
    return np.concatenate(list(out)) if tiled else out


def host_sum(vals, *, name: str,
             point: Optional[str] = "collective_sync") -> np.ndarray:
    """Elementwise sum across processes of a small f64 vector."""
    v = np.asarray(vals, np.float64)
    if jax.process_count() == 1:
        return guarded_collective(lambda: v, name=name, point=point,
                                  local=True)
    return guarded_collective(lambda: _bitsafe_gather(v).sum(axis=0),
                              name=name, point=point)


def host_device_allgather(x, *, name: str,
                          point: Optional[str] = "collective_sync"):
    """Gather a (possibly non-addressable) device array's global value
    onto every host, tiled along axis 0 — the leaf-id reassembly path.
    Unlike `host_allgather` the payload is a jax.Array, so transport
    dtype is the array's own (no x64 demotion hazard for f32/i32)."""
    from jax.experimental import multihost_utils

    return guarded_collective(
        lambda: multihost_utils.process_allgather(x, tiled=True),
        name=name, point=point, local=jax.process_count() == 1)


def ragged_all_gather(arr: np.ndarray, *, name: str,
                      point: Optional[str] = "collective_sync",
                      split: bool = False):
    """Gather per-process arrays of DIFFERING leading length into one
    identical global view on every host, process order — concatenated
    by default, a per-process list under `split=True` (payloads whose
    boundaries matter, e.g. serialized mapper blobs).

    The fixed-width transport idiom `find_bundles_multihost` /
    `gather_row_samples` / `sync_concat` each hand-rolled, written once:
    allgather the per-host lengths, zero-pad every payload to the max,
    allgather the congruent block, slice each host's contribution back
    out.  The lens+payload pair is ONE logical collective under ONE
    watchdog (ranks enter/leave together; a retry redoes the sequence
    from the top — the historical deadlocked-allgather failure mode).
    Trailing dimensions must agree across processes; dtype is preserved
    bit-exactly (64-bit payloads ride uint32 views).
    """
    arr = np.ascontiguousarray(arr)
    if jax.process_count() == 1:
        out = guarded_collective(lambda: arr, name=name, point=point,
                                 local=True)
        return [out] if split else out

    def _merge():
        lens = _bitsafe_gather(np.asarray([arr.shape[0]], np.int64))[:, 0]
        mx = max(int(lens.max()), 1)
        buf = np.zeros((mx,) + arr.shape[1:], arr.dtype)
        buf[:arr.shape[0]] = arr
        g = _bitsafe_gather(buf)                  # [P, mx, ...]
        parts = [g[p, :int(lens[p])] for p in range(len(lens))]
        return parts if split else (
            np.concatenate(parts) if parts else buf[:0])

    return guarded_collective(_merge, name=name, point=point)
