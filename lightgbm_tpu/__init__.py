"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

A from-scratch reimplementation of the capabilities of LightGBM v2.3.2
(reference: jpkoponen/LightGBM) designed for TPUs: the compute core
(histogram construction, split search, partitioning) runs as JAX/XLA
programs over fixed-shape tensors, and distribution uses `jax.sharding`
meshes with XLA collectives instead of socket/MPI allreduce.

Public API mirrors the reference Python package
(reference python-package/lightgbm/__init__.py):
  Dataset, Booster, train, cv, and sklearn-style wrappers.
"""

from .utils.backend import enable_compilation_cache as _enable_cache

# persistent XLA compilation cache: the grower is one big program whose
# cold compile costs minutes; cached compiles load in seconds.  Opt out
# with LIGHTGBM_TPU_CACHE=off; override the location (default
# <repo>/.jax_cache) with LIGHTGBM_TPU_CACHE_DIR.
if __import__("os").environ.get("LIGHTGBM_TPU_CACHE", "") != "off":
    _enable_cache()

from .version import __version__
from .config import Config
from .basic import Dataset, Booster
from .utils.log import LightGBMError
from .engine import train, cv, CVBooster
from .callback import (
    checkpoint,
    early_stopping,
    log_evaluation,
    print_evaluation,
    record_evaluation,
    reset_parameter,
    EarlyStopException,
)
from .plotting import (
    create_tree_digraph,
    plot_importance,
    plot_metric,
    plot_split_value_histogram,
    plot_tree,
)
# serving runtime (registry + micro-batched inference) stays a lazy
# submodule: `from lightgbm_tpu.serving import ServingSession`

__all__ = [
    "__version__",
    "Config",
    "Dataset",
    "Booster",
    "LightGBMError",
    "train",
    "cv",
    "CVBooster",
    "checkpoint",
    "early_stopping",
    "log_evaluation",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
    "EarlyStopException",
    "plot_importance",
    "plot_metric",
    "plot_split_value_histogram",
    "plot_tree",
    "create_tree_digraph",
]

try:  # sklearn wrappers are optional (scikit-learn may be absent)
    from .sklearn import LGBMModel, LGBMClassifier, LGBMRegressor, LGBMRanker
    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass
