"""scikit-learn API wrappers (reference python-package/lightgbm/sklearn.py).

Implemented in the API-surface milestone; importing this module requires
scikit-learn.
"""

raise ImportError("sklearn wrappers not yet available")
