"""scikit-learn API wrappers (reference python-package/lightgbm/sklearn.py).

`LGBMModel` / `LGBMRegressor` / `LGBMClassifier` / `LGBMRanker` with the
reference constructor surface (sklearn.py:172-180) and fit/predict
semantics, driving the TPU booster through `lightgbm_tpu.train`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Dataset
from .booster import Booster
from .engine import train


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, weight[, group]]) to the
    engine's fobj(scores, dataset) contract
    (reference sklearn.py:21-97 _ObjectiveFunctionWrapper)."""

    def __init__(self, func):
        self.func = func

    def __call__(self, scores, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        preds = scores.reshape(-1)
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2-4 "
                            f"arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt feval(y_true, y_pred[, weight[, group]]) -> (name, value,
    is_higher_better) (reference sklearn.py:100-166)."""

    def __init__(self, func):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2-4 "
                        f"arguments, got {argc}")


class LGBMModel:
    """Implementation of the scikit-learn API for the TPU framework
    (reference sklearn.py:169)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Any] = None,
                 class_weight: Optional[Any] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = {}
        self._Booster: Optional[Booster] = None
        self._evals_result: Optional[Dict] = None
        self._best_score: Optional[Dict] = None
        self._best_iteration: Optional[int] = None
        self._n_features: Optional[int] = None
        self._classes = None
        self._n_classes: Optional[int] = None
        self._objective = objective
        self._fobj = None
        self.set_params(**kwargs)

    # -- sklearn plumbing ----------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample, "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "silent": self.silent, "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    # -- fitting -------------------------------------------------------
    def _prepare_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        params.pop("importance_type", None)
        params.pop("silent", None)
        params.pop("n_jobs", None)
        obj = params.pop("objective", None)
        if callable(obj):
            self._fobj = _ObjectiveFunctionWrapper(obj)
            params["objective"] = "none"
        else:
            self._fobj = None
            params["objective"] = obj if obj is not None else self._objective
        if params.get("random_state") is None:
            params.pop("random_state", None)
        else:
            params["seed"] = params.pop("random_state")
        params["boosting"] = params.pop("boosting_type")
        params["learning_rate"] = self.learning_rate
        params["min_gain_to_split"] = params.pop("min_split_gain")
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight")
        params["min_data_in_leaf"] = params.pop("min_child_samples")
        params["bagging_fraction"] = params.pop("subsample")
        params["bagging_freq"] = params.pop("subsample_freq")
        params["feature_fraction"] = params.pop("colsample_bytree")
        params["lambda_l1"] = params.pop("reg_alpha")
        params["lambda_l2"] = params.pop("reg_lambda")
        params["bin_construct_sample_cnt"] = params.pop("subsample_for_bin")
        return params

    def _class_sample_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        from collections import Counter
        y = np.asarray(y)
        classes = np.unique(y)
        if self.class_weight == "balanced":
            counts = Counter(y.tolist())
            n = len(y)
            cw = {c: n / (len(classes) * counts[c]) for c in classes}
        elif isinstance(self.class_weight, dict):
            cw = {c: self.class_weight.get(c, 1.0) for c in classes}
        else:
            raise ValueError("class_weight must be 'balanced' or a dict")
        w = np.asarray([cw[c] for c in y], np.float64)
        if sample_weight is not None:
            w = w * np.asarray(sample_weight, np.float64)
        return w

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        params = self._prepare_params()
        if eval_metric is not None and not callable(eval_metric):
            metrics = eval_metric if isinstance(eval_metric, list) \
                else [eval_metric]
            named = [m for m in metrics if not callable(m)]
            if named:
                params["metric"] = named
        feval = None
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
        elif isinstance(eval_metric, list):
            fevals = [_EvalFunctionWrapper(m) for m in eval_metric
                      if callable(m)]
            if fevals:
                feval = lambda preds, ds: [f(preds, ds) for f in fevals]  # noqa: E731

        sample_weight = self._class_sample_weight(y, sample_weight)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(train_set.create_valid(
                        vx, label=vy, weight=vw, group=vg, init_score=vi))

        evals_result: Dict = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=eval_names,
            fobj=self._fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            verbose_eval=verbose, evals_result=evals_result,
            callbacks=callbacks)
        self._evals_result = evals_result if evals_result else None
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._n_features = np.asarray(X).shape[1]
        return self

    # -- prediction ----------------------------------------------------
    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        if self._Booster is None:
            raise ValueError("Estimator not fitted, call fit first")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib, **kwargs)

    # -- attributes ----------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise ValueError("No booster found, call fit first")
        return self._Booster

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def best_score_(self):
        return self._best_score

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def n_features_(self) -> int:
        if self._n_features is None:
            raise ValueError("No n_features found, call fit first")
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def objective_(self):
        return self._objective


class LGBMRegressor(LGBMModel):
    """LightGBM regressor (reference sklearn.py:733)."""

    def fit(self, X, y, **kwargs):
        if self.objective is None:
            self._objective = "regression"
        return super().fit(X, y, **kwargs)


class LGBMClassifier(LGBMModel):
    """LightGBM classifier (reference sklearn.py:760)."""

    def fit(self, X, y, sample_weight=None, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._class_map = {c: i for i, c in enumerate(self._classes)}
        y_enc = np.asarray([self._class_map[v] for v in y], np.float64)
        if self._n_classes > 2:
            if self.objective is None or not callable(self.objective):
                obj = self.objective or "multiclass"
                if obj not in ("multiclass", "multiclassova", "softmax",
                               "multiclass_ova", "ova", "ovr"):
                    obj = "multiclass"
                self._objective = obj
            self._other_params["num_class"] = self._n_classes
        elif self.objective is None:
            self._objective = "binary"
        if kwargs.get("eval_set") is not None:
            es = kwargs["eval_set"]
            if isinstance(es, tuple):
                es = [es]
            kwargs["eval_set"] = [
                (vx, np.asarray([self._class_map[v] for v in np.asarray(vy)],
                                np.float64)) for vx, vy in es]
        return super().fit(X, y_enc, sample_weight=sample_weight, **kwargs)

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 2:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(np.int64)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = LGBMModel.predict(self, X, raw_score=raw_score,
                                   num_iteration=num_iteration,
                                   pred_leaf=pred_leaf,
                                   pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes is not None and self._n_classes <= 2 \
                and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self):
        if self._classes is None:
            raise ValueError("No classes found, call fit first")
        return self._classes

    @property
    def n_classes_(self) -> int:
        if self._n_classes is None:
            raise ValueError("No classes found, call fit first")
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (reference sklearn.py:902)."""

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_at=(1, 2, 3, 4, 5), **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if kwargs.get("eval_set") is not None \
                and kwargs.get("eval_group") is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        if self.objective is None:
            self._objective = "lambdarank"
        self._other_params["eval_at"] = list(eval_at)
        return super().fit(X, y, sample_weight=sample_weight,
                           init_score=init_score, group=group, **kwargs)
