"""Typed training configuration with LightGBM-compatible parameter names/aliases.

The reference keeps a ~180-field `Config` struct whose alias table and setters are
code-generated from doc comments (reference include/LightGBM/config.h:41-79 and
src/config_auto.cpp:10).  Here the registry is a plain Python table: each entry is
(canonical name, type, default, aliases).  Parameters flow as `key=value` strings
through every layer, as in the reference (`Config::Str2Map`, config.h:41).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Parameter registry: canonical -> (type, default, aliases)
# Types: "int", "float", "bool", "str", "int_list", "float_list", "str_list"
# Mirrors reference include/LightGBM/config.h fields + config_auto.cpp alias table.
# ---------------------------------------------------------------------------

_P: Dict[str, Tuple[str, Any, Tuple[str, ...]]] = {
    # --- core ---
    "config": ("str", "", ("config_file",)),
    "task": ("str", "train", ("task_type",)),
    "objective": ("str", "regression", ("objective_type", "app", "application")),
    "boosting": ("str", "gbdt", ("boosting_type", "boost")),
    "data": ("str", "", ("train", "train_data", "train_data_file", "data_filename")),
    "valid": ("str_list", [], ("test", "valid_data", "valid_data_file", "test_data",
                               "test_data_file", "valid_filenames")),
    "num_iterations": ("int", 100, ("num_iteration", "n_iter", "num_tree", "num_trees",
                                    "num_round", "num_rounds", "num_boost_round",
                                    "n_estimators")),
    "learning_rate": ("float", 0.1, ("shrinkage_rate", "eta")),
    "num_leaves": ("int", 31, ("num_leaf", "max_leaves", "max_leaf")),
    "tree_learner": ("str", "serial", ("tree", "tree_type", "tree_learner_type")),
    "num_threads": ("int", 0, ("num_thread", "nthread", "nthreads", "n_jobs")),
    "device_type": ("str", "tpu", ("device",)),
    "seed": ("int", 0, ("random_seed", "random_state")),
    # --- learning control ---
    "max_depth": ("int", -1, ()),
    "min_data_in_leaf": ("int", 20, ("min_data_per_leaf", "min_data", "min_child_samples")),
    "min_sum_hessian_in_leaf": ("float", 1e-3, ("min_sum_hessian_per_leaf", "min_sum_hessian",
                                                "min_hessian", "min_child_weight")),
    "bagging_fraction": ("float", 1.0, ("sub_row", "subsample", "bagging")),
    "pos_bagging_fraction": ("float", 1.0, ("pos_sub_row", "pos_subsample", "pos_bagging")),
    "neg_bagging_fraction": ("float", 1.0, ("neg_sub_row", "neg_subsample", "neg_bagging")),
    "bagging_freq": ("int", 0, ("subsample_freq",)),
    "bagging_seed": ("int", 3, ("bagging_fraction_seed",)),
    "feature_fraction": ("float", 1.0, ("sub_feature", "colsample_bytree")),
    "feature_fraction_bynode": ("float", 1.0, ("sub_feature_bynode", "colsample_bynode")),
    "feature_fraction_seed": ("int", 2, ()),
    "early_stopping_round": ("int", 0, ("early_stopping_rounds", "early_stopping",
                                        "n_iter_no_change")),
    "first_metric_only": ("bool", False, ()),
    "max_delta_step": ("float", 0.0, ("max_tree_output", "max_leaf_output")),
    "lambda_l1": ("float", 0.0, ("reg_alpha",)),
    "lambda_l2": ("float", 0.0, ("reg_lambda", "lambda")),
    "min_gain_to_split": ("float", 0.0, ("min_split_gain",)),
    "drop_rate": ("float", 0.1, ("rate_drop",)),
    "max_drop": ("int", 50, ()),
    "skip_drop": ("float", 0.5, ()),
    "xgboost_dart_mode": ("bool", False, ()),
    "uniform_drop": ("bool", False, ()),
    "drop_seed": ("int", 4, ()),
    "top_rate": ("float", 0.2, ()),
    "other_rate": ("float", 0.1, ()),
    "min_data_per_group": ("int", 100, ()),
    "max_cat_threshold": ("int", 32, ()),
    "cat_l2": ("float", 10.0, ()),
    "cat_smooth": ("float", 10.0, ()),
    "max_cat_to_onehot": ("int", 4, ()),
    "top_k": ("int", 20, ("topk",)),
    "monotone_constraints": ("int_list", [], ("mc", "monotone_constraint")),
    "feature_contri": ("float_list", [], ("feature_contrib", "fc", "fp", "feature_penalty")),
    "forcedsplits_filename": ("str", "", ("fs", "forced_splits_filename", "forced_splits_file",
                                          "forced_splits")),
    "forcedbins_filename": ("str", "", ()),
    "refit_decay_rate": ("float", 0.9, ()),
    "cegb_tradeoff": ("float", 1.0, ()),
    "cegb_penalty_split": ("float", 0.0, ()),
    "cegb_penalty_feature_lazy": ("float_list", [], ()),
    "cegb_penalty_feature_coupled": ("float_list", [], ()),
    "verbosity": ("int", 1, ("verbose",)),
    "snapshot_freq": ("int", -1, ("save_period",)),
    # --- IO / dataset ---
    "max_bin": ("int", 255, ()),
    "max_bin_by_feature": ("int_list", [], ()),
    "min_data_in_bin": ("int", 3, ()),
    "bin_construct_sample_cnt": ("int", 200000, ("subsample_for_bin",)),
    "histogram_pool_size": ("float", -1.0, ("hist_pool_size",)),
    "data_random_seed": ("int", 1, ("data_seed",)),
    "output_model": ("str", "LightGBM_model.txt", ("model_output", "model_out")),
    "input_model": ("str", "", ("model_input", "model_in")),
    # task=convert_model: if-else C++ codegen of input_model (codegen.py)
    "convert_model": ("str", "gbdt_prediction.cpp", ("convert_model_file",)),
    "convert_model_language": ("str", "cpp", ()),
    "output_result": ("str", "LightGBM_predict_result.txt",
                      ("predict_result", "prediction_result", "predict_name",
                       "prediction_name", "pred_name", "name_pred")),
    "initscore_filename": ("str", "", ("init_score_filename", "init_score_file",
                                       "init_score", "input_init_score")),
    "valid_data_initscores": ("str_list", [], ("valid_data_init_scores",
                                               "valid_init_score_file", "valid_init_score")),
    # compatibility alias for the topology's partitioned-rows mode: rows
    # are already split per process, so ingest skips the global scatter
    # and sum-type metrics reduce across hosts.  Internally this is the
    # partitioned_rows flag of the (hosts, data, feature) topology —
    # consumers key on topology.rows_partitioned(), never on this bool
    "pre_partition": ("bool", False, ("is_pre_partition",)),
    "enable_bundle": ("bool", True, ("is_enable_bundle", "bundle")),
    "max_conflict_rate": ("float", 0.0, ()),
    "is_enable_sparse": ("bool", True, ("is_sparse", "enable_sparse", "sparse")),
    "sparse_threshold": ("float", 0.8, ()),
    "use_missing": ("bool", True, ()),
    "zero_as_missing": ("bool", False, ()),
    "two_round": ("bool", False, ("two_round_loading", "use_two_round_loading")),
    "save_binary": ("bool", False, ("is_save_binary", "is_save_binary_file")),
    "header": ("bool", False, ("has_header",)),
    "label_column": ("str", "", ("label",)),
    "weight_column": ("str", "", ("weight",)),
    "group_column": ("str", "", ("group", "group_id", "query_column", "query", "query_id")),
    "ignore_column": ("str", "", ("ignore_feature", "blacklist")),
    "categorical_feature": ("str", "", ("cat_feature", "categorical_column", "cat_column")),
    # --- predict ---
    "predict_raw_score": ("bool", False, ("is_predict_raw_score", "predict_rawscore",
                                          "raw_score")),
    "predict_leaf_index": ("bool", False, ("is_predict_leaf_index", "leaf_index")),
    "predict_contrib": ("bool", False, ("is_predict_contrib", "contrib")),
    "num_iteration_predict": ("int", -1, ()),
    "predict_disable_shape_check": ("bool", False, ()),
    "pred_early_stop": ("bool", False, ()),
    "pred_early_stop_freq": ("int", 10, ()),
    "pred_early_stop_margin": ("float", 10.0, ()),
    # --- serving (lightgbm_tpu/serving: registry + micro-batched inference) ---
    # rows the micro-batcher coalesces into one device predict; also the
    # largest row bucket the registry warmup pre-compiles
    "serving_max_batch_rows": ("int", 4096, ()),
    # how long the batcher holds an under-filled batch open for
    # coalescing before dispatching it anyway
    "serving_max_wait_ms": ("float", 2.0, ()),
    # admission control: total rows allowed in the queue; requests past
    # it are shed immediately with ServingQueueFull (HTTP 503)
    "serving_queue_rows": ("int", 65536, ()),
    # per-request wait budget; expiry raises ServingTimeout (HTTP 504)
    "serving_timeout_ms": ("float", 10000.0, ()),
    # model registry capacity: least-recently-used non-current versions
    # are evicted past this many resident models
    "serving_max_models": ("int", 4, ()),
    # pre-compile every row-bucket shape at load time so no request size
    # ever hits a cold jit compile
    "serving_warmup": ("bool", True, ()),
    # registry name the CLI `serve` task loads input_model under
    "serving_model_name": ("str", "default", ()),
    # HTTP/JSON endpoint bind address for `python -m lightgbm_tpu serve`
    "serving_host": ("str", "127.0.0.1", ()),
    "serving_port": ("int", 18080, ()),
    # rolling latency samples kept for the p50/p95/p99 stats
    "serving_stats_window": ("int", 4096, ()),
    # circuit breaker on the device predict path: this many consecutive
    # device failures OPEN the breaker (requests go straight to the
    # native walker, no device attempts)
    "serving_breaker_failures": ("int", 3, ()),
    # how long an OPEN breaker waits before letting ONE half-open probe
    # try the device path again (success closes it, failure re-opens)
    "serving_breaker_cooldown_ms": ("float", 2000.0, ()),
    # --- serving: adaptive admission / deadlines / drain (ISSUE 11) ---
    # latency SLO target: the admission controller AIMDs its admitted-
    # rows level so the projected request latency (recent queue-wait
    # p99 + dispatch p95, from the PR-10 histograms) stays inside it,
    # and the batcher's coalescing window narrows as load approaches it
    "serving_slo_ms": ("float", 50.0, ()),
    # adaptive admission on/off; off keeps only the hard
    # serving_queue_rows wall (the pre-ISSUE-11 behavior)
    "serving_admission": ("bool", True, ()),
    # how often the controller re-reads the histograms and moves the
    # level/window (lazy, on the admit path; no timer thread)
    "serving_aimd_interval_ms": ("float", 100.0, ()),
    # additive increase per interval while latency is comfortable
    "serving_aimd_step_rows": ("int", 512, ()),
    # multiplicative decrease when the projection exceeds the SLO
    "serving_aimd_backoff": ("float", 0.5, ()),
    # floor of the ADAPTIVE batch window (serving_max_wait_ms is its
    # ceiling): under SLO pressure batches dispatch after at most this
    "serving_min_wait_ms": ("float", 0.0, ()),
    # Retry-After carried by 429/503 shed responses
    "serving_retry_after_ms": ("float", 1000.0, ()),
    # dispatch watchdog: a device runner that neither returns nor
    # raises within this wall is abandoned, the batch fails over to the
    # native walker, and the entry's breaker records the failure
    # (0 = off: a wedged device hangs the dispatch worker, pre-ISSUE-11)
    "serving_dispatch_timeout_ms": ("float", 30000.0, ()),
    # default flush budget of the drain lifecycle (POST /drain, SIGTERM)
    "serving_drain_timeout_ms": ("float", 10000.0, ()),
    # --- serving: memory pressure (ISSUE 15) ---
    # serving-registry HBM budget in bytes (packed model tables +
    # launch scratch): a load whose predicted bytes would not fit first
    # evicts cold LRU models, then REFUSES with a structured 507
    # (ServingMemoryExhausted) instead of warming into a device crash.
    # 0 = inherit the training budget resolution (tpu_hbm_budget_bytes
    # / tpu_hbm_budget_frac x device capacity; unenforced on backends
    # that report no memory stats)
    "serving_hbm_budget_bytes": ("int", 0, ()),
    # sustained-pressure eviction threshold: once resident model bytes
    # exceed this fraction of the serving budget, cold (non-current)
    # LRU models are evicted ahead of demand so a dispatch never has
    # to OOM first
    "serving_hbm_pressure_frac": ("float", 0.85, ()),
    # --- serving: fleet-scale dispatch (ISSUE 19) ---
    # devices each model's packed forest replicates across (the batcher
    # grows one dispatch worker per device, least-loaded routed).
    # 0 = auto: every local device on accelerator backends, ONE on CPU
    # hosts (forced virtual CPU devices share the same physical cores —
    # replication there multiplies warmup compiles without adding
    # throughput).  Capped at the local device count
    "serving_devices": ("int", 0, ()),
    # packed-table storage precision for serving replicas:
    #   f32   — byte-identical to the training pack (default)
    #   bf16  — leaf values stored bfloat16 (identical decision path;
    #           per-leaf value error <= 2^-8 relative)
    #   int16 — node tables AND leaf values int16; leaves dequantize
    #           per-tree with an f32 scale (exact decision-path parity:
    #           bin-space thresholds are small ints that fit int16)
    "serving_table_precision": ("str", "f32", ()),
    # AOT executable cache directory: every bucket-ladder launch shape
    # is jit-lowered, compiled and serialized here at load time, so a
    # cold replica (process restart, continual-learning promotion, LRU
    # re-load) serves its first batch with ZERO new compiled programs.
    # "" = derive `<tpu_compile_cache_dir>/serving_aot` when the
    # persistent compile cache is configured, else AOT serving is off
    "serving_aot_cache_dir": ("str", "", ()),
    # --- serving: model & data health (ISSUE 14) ---
    # rows per predict batch the drift monitor stride-samples into its
    # accumulator (models carrying a tpu_feature_profile trailer only).
    # The tap is one bounded row copy on the dispatch path; binning,
    # PSI/JS and the score histogram run lazily at scrape time
    # (GET /drift, GET /metrics).  0 disables drift monitoring
    "serving_drift_sample_rows": ("int", 256, ()),
    # per-feature PSI threshold: crossing it records a flight-recorder
    # `psi_warn` event, a Log.warning, and the drift_warnings counter
    # (conventional PSI reading: <0.1 stable, 0.1-0.25 moderate,
    # >0.25 major shift)
    "serving_drift_psi_warn": ("float", 0.25, ()),
    # --- memory pressure (utils/membudget.py, ISSUE 15) ---
    # explicit device-memory budget in bytes the preflight planner and
    # the OOM recovery ladder enforce; 0 = auto (device capacity from
    # memory_stats()['bytes_limit'] scaled by tpu_hbm_budget_frac;
    # no enforcement on backends that report no memory stats).  An
    # explicit value is honored on EVERY backend, so budget behavior is
    # testable on CPU
    "tpu_hbm_budget_bytes": ("int", 0, ()),
    # fraction of reported device capacity the auto budget claims
    "tpu_hbm_budget_frac": ("float", 0.9, ()),
    # preflight policy before iteration 0: predict peak HBM from the
    # closed-form buffer models (binned matrix, [L, G/P, B, 3]
    # histogram pool, stats planes, scores, packed forest, chunk
    # scratch) and compare against the budget.
    #   off     - no preflight
    #   warn    - log the itemized over-budget plan and proceed
    #   raise   - refuse with the named, itemized plan
    #   degrade - auto-apply bitwise-invisible degradation-ladder steps
    #             (chunk shrink -> scatter aggregation -> fine bucket
    #             policy) until the plan fits, refusing if it never does
    "tpu_hbm_preflight": ("str", "warn", ()),
    # mid-train OOM recovery: a classified RESOURCE_EXHAUSTED at a
    # guarded device site rolls the iteration back (the PR-7 atomic
    # rollback), descends ONE deterministic, logged degradation-ladder
    # step, and retries; every step is bitwise-invisible, so the
    # settled run's model file is byte-identical to an undisturbed run
    # at the settled config.  Ladder exhaustion raises a structured
    # MemoryLadderExhausted after the final checkpoint flush +
    # blackbox dump.  false = classified OOMs propagate immediately
    # (multi-host process groups always propagate: a one-sided retry
    # would desynchronize the collective streams)
    "tpu_oom_recovery": ("bool", True, ()),
    # --- out-of-core streaming (ops/stream.py, ISSUE 16) ---
    # training layout: resident keeps the binned matrix device-resident
    # (the classic path); streamed keeps it host-resident and streams
    # fixed-size row blocks through double-buffered device slots each
    # iteration, so rows x features stops being capped by HBM.  auto
    # lets membudget.plan_training pick: resident when the itemized
    # plan fits the budget, streamed when the binned matrix pushes it
    # over.  int8/int16 streamed models are BYTE-IDENTICAL to resident
    # (int32 histogram sums are associative across blocks)
    "tpu_stream_mode": ("str", "auto", ()),
    # rows per streamed block (rounded to a multiple of the device
    # histogram scan block); 0 = auto (a block sized so two device
    # slots fit comfortably under ~1/8 of the HBM budget, floored at
    # 64k rows)
    "tpu_stream_block_rows": ("int", 0, ()),
    # overlap block i+1's H2D copy with block i's histogram contraction
    # via two device slots; false = one slot, fully serial copies
    # (debugging / host-memory ceiling)
    "tpu_stream_double_buffer": ("bool", True, ()),
    # GOSS-style gradient-based block sampling for the streamed layout:
    # keep the top fraction of blocks by sum(|grad*hess|) every
    # iteration...
    "tpu_stream_goss_top": ("float", 0.0, ()),
    # ...plus this fraction of the remaining blocks, drawn by a PCG
    # hash keyed on each block's first GLOBAL row index (invariant to
    # padding and shard count) and amplified by the standard GOSS
    # (1-top)/other weight.  Both 0.0 = stream every block.  Block
    # sampling changes which rows build each tree, so it trades the
    # bitwise-vs-resident guarantee for fewer H2D copies per iteration
    "tpu_stream_goss_other": ("float", 0.0, ()),
    # --- fault tolerance (utils/checkpoint.py + numeric guardrails) ---
    # atomic training checkpoints: bundle directory (empty = off).  Each
    # checkpoint holds the model string (with its bin-mapper trailer),
    # PRNG stream states, and the f32 score buffers, written via
    # temp-file + fsync + rename with a CRC'd manifest; resume with
    # lgb.train(..., resume=True) is BIT-IDENTICAL to an uninterrupted
    # run for quantized (int8/int16) precisions at any shard count
    "tpu_checkpoint_dir": ("str", "", ()),
    # boosting iterations between checkpoints
    "tpu_checkpoint_interval": ("int", 1, ()),
    # newest valid checkpoints retained (older ones are deleted)
    "tpu_checkpoint_keep": ("int", 3, ()),
    # collective watchdog (parallel/collective.py): seconds a host-level
    # collective (metric sync, distributed bin finding, multihost
    # rendezvous, checkpoint barrier) may block before a structured
    # CollectiveTimeout rolls the iteration back and flushes a final
    # checkpoint — a hung peer degrades to a usable booster instead of
    # silently hanging the group.  The setting is PROCESS-GLOBAL (the
    # reference's Network config): -1 (default) leaves the current
    # process policy untouched, 0 explicitly disables the deadline
    # (block forever, the pre-watchdog behavior), >0 arms it.  Fault
    # injection and retry stay live either way
    "tpu_collective_timeout_s": ("float", -1.0, ()),
    # bounded retries (exponential backoff) when a collective RAISES a
    # transient transport error; timeouts and host drops never retry
    # (after a missed deadline the group's collective streams are no
    # longer aligned).  Process-global like the timeout: -1 leaves the
    # current policy, 0 disables retry
    "tpu_collective_retries": ("int", -1, ()),
    # elastic resume: allow resuming a checkpoint taken at a different
    # shard/host topology (P data shards -> P', including 1).  Scores
    # are global f32 buffers and quantized rounding keys on the GLOBAL
    # row index, so int8/int16 resumes stay bit-identical across
    # topology changes; false refuses any topology delta
    "tpu_resume_elastic": ("bool", True, ()),
    # raise (instead of warn-and-proceed) when resume params differ
    # from the checkpointed run's beyond the topology set; the
    # differing keys are named either way
    "tpu_resume_strict": ("bool", False, ()),
    # numeric guardrails: per-iteration isfinite check on the updated
    # train scores plus an int32 histogram-headroom sentinel for
    # quantized precisions.  off = no checks (default; keeps the train
    # loop fully async); warn = log and continue; raise = roll the
    # poisoned iteration back and raise; skip = roll it back, re-bag,
    # and keep training (drops the iteration)
    "tpu_guard_numerics": ("str", "off", ()),
    # --- observability (lightgbm_tpu/obs: metrics registry + span tracer) ---
    # process-global telemetry mode.  "" (the registry default) means
    # UNSET — a booster/dataset constructed without the param never
    # disturbs a policy another layer armed (same convention as
    # tpu_collective_timeout_s); the effective initial mode is "off"
    # unless LIGHTGBM_TPU_TELEMETRY is set.  off = no instrumentation
    # (the train loop pays one flag check per site); metrics = phase
    # walls, counters and fixed-bucket histograms flow into the
    # process-global registry (scraped as Prometheus text via the
    # serving GET /metrics); trace = metrics PLUS nested structured
    # spans (per-iteration train lifecycle, collectives, checkpoints,
    # serving dispatch) exported as Chrome-trace-event JSON that loads
    # in Perfetto, mirrored into jax.profiler.TraceAnnotation so the
    # same names appear inside xprof device traces
    "tpu_telemetry": ("str", "", ()),
    # span/event sink for tpu_telemetry=trace: each host streams
    # events-host<k>.jsonl incrementally (a dying run keeps everything
    # up to the death) and train() dumps trace-host<k>.json on exit;
    # merge a multihost run's streams with tools/trace_merge.py.
    # "" = unset (in-memory span buffer only)
    "tpu_trace_dir": ("str", "", ()),
    # raw samples kept per metrics-registry histogram child (the bench's
    # repeat readback and the serving admission controller's
    # recent-window SLO projection both read this ring).  Readers that
    # must not silently under-count ask
    # histogram_samples(with_truncated=True).  0 = leave the process
    # default (256) untouched
    "tpu_obs_ring_samples": ("int", 0, ()),
    # flight-recorder depth: the last N spans / events / watchdog-guard-
    # breaker transitions kept in the ALWAYS-ON process-global ring
    # (obs/flightrecorder.py) and dumped to blackbox-host<k>.json on
    # unhandled exception, CollectiveTimeout, SIGTERM, or a guard raise.
    # 0 = leave the process default (512) untouched
    "tpu_obs_blackbox_events": ("int", 0, ()),
    # where blackbox-host<k>.json dumps land.  "" = unset: the
    # LIGHTGBM_TPU_BLACKBOX_DIR env var, then tpu_trace_dir, then the
    # working directory
    "tpu_obs_blackbox_dir": ("str", "", ()),
    # capture the training reference profile (per-feature bin occupancy
    # from BinMapper.cnt_in_bin, NaN/zero fractions, label stats, raw-
    # score histogram) and write it as the tpu_feature_profile: model-
    # string trailer — the reference every serving drift monitor and
    # model_report compares against.  false = no trailer (a loaded
    # model's existing profile still round-trips)
    "tpu_profile_capture": ("bool", True, ()),
    # bins of the profile's raw-score histogram (equal-width over the
    # end-of-training score range)
    "tpu_profile_score_bins": ("int", 32, ()),
    # --- continual learning (lightgbm_tpu/continual, ISSUE 17) ---
    # bounded retention window of the incremental ingest buffer: once
    # buffered rows exceed it, the OLDEST binned blocks are evicted
    # (the buffer is a sliding window over the live stream, not an
    # unbounded accumulator)
    "tpu_continual_buffer_rows": ("int", 262144, ()),
    # row-count retrain trigger: a retrain fires once this many fresh
    # rows have accumulated since the last one (0 = off)
    "tpu_continual_min_rows": ("int", 4096, ()),
    # wall-clock retrain cadence in seconds (0 = off)
    "tpu_continual_interval_s": ("float", 0.0, ()),
    # retrain policy: auto (drift trigger -> boost-K / re-sketch
    # escalation, row-count & cadence triggers -> leaf refit), or pin
    # one of refit | boost | resketch
    "tpu_continual_policy": ("str", "auto", ()),
    # K extra boosting rounds per warm-continue (init_model) retrain
    "tpu_continual_boost_rounds": ("int", 10, ()),
    # leaf-refit blend: new leaf = decay*old + (1-decay)*refit
    "tpu_continual_refit_decay": ("float", 0.9, ()),
    # shadow gate tolerance: promote iff candidate_loss <=
    # live_loss * (1 + tolerance) on the mirrored sample
    "tpu_continual_tolerance": ("float", 0.0, ()),
    # GOSS-style freshness weighting of buffered blocks in the boost-K
    # training set: a block's weight decays by this factor per
    # RETENTION-WINDOW age step (newest block = 1.0); 1.0 = unweighted
    "tpu_continual_fresh_decay": ("float", 0.7, ()),
    # re-sketch escalation threshold: when the drift trigger fires AND
    # at least this fraction of buffered rows landed in a feature's
    # overflow/tail bin, the binning itself is stale — the policy
    # escalates to a full re-sketch retrain instead of reusing the
    # frozen mappers
    "tpu_continual_resketch_tail_frac": ("float", 0.25, ()),
    # rows of mirrored live traffic the shadow gate scores a candidate
    # on before the promote/refuse verdict
    "tpu_continual_shadow_rows": ("int", 2048, ()),
    # controller state + mid-retrain checkpoints (PR-7 manager) land
    # here so a killed controller resumes; "" = stateless (no resume)
    "tpu_continual_dir": ("str", "", ()),
    # seconds between controller trigger polls in the run_forever loop
    "tpu_continual_poll_s": ("float", 10.0, ()),
    # --- objective ---
    "num_class": ("int", 1, ("num_classes",)),
    "is_unbalance": ("bool", False, ("unbalance", "unbalanced_sets")),
    "scale_pos_weight": ("float", 1.0, ()),
    "sigmoid": ("float", 1.0, ()),
    "boost_from_average": ("bool", True, ()),
    "reg_sqrt": ("bool", False, ()),
    "alpha": ("float", 0.9, ()),
    "fair_c": ("float", 1.0, ()),
    "poisson_max_delta_step": ("float", 0.7, ()),
    "tweedie_variance_power": ("float", 1.5, ()),
    "max_position": ("int", 20, ()),
    "lambdamart_norm": ("bool", True, ()),
    "label_gain": ("float_list", [], ()),
    "objective_seed": ("int", 5, ()),
    # --- metric ---
    "metric": ("str_list", [], ("metrics", "metric_types")),
    "metric_freq": ("int", 1, ("output_freq",)),
    "is_provide_training_metric": ("bool", False, ("training_metric", "is_training_metric",
                                                   "train_metric")),
    "eval_at": ("int_list", [1, 2, 3, 4, 5], ("ndcg_eval_at", "ndcg_at", "map_eval_at",
                                              "map_at")),
    "multi_error_top_k": ("int", 1, ()),
    "auc_mu_weights": ("float_list", [], ()),
    # --- network (mesh) ---
    "num_machines": ("int", 1, ("num_machine",)),
    "local_listen_port": ("int", 12400, ("local_port", "port")),
    "time_out": ("int", 120, ()),
    "machine_list_filename": ("str", "", ("machine_list_file", "machine_list", "mlist")),
    "machines": ("str", "", ("workers", "nodes")),
    # --- device (TPU analog of the reference's GPU block) ---
    "gpu_platform_id": ("int", -1, ()),
    "gpu_device_id": ("int", -1, ()),
    "gpu_use_dp": ("bool", False, ()),
    # TPU-specific: precision of histogram matmul accumulation.
    #   "hilo"   - bf16 hi/lo split stats, f32 accumulate (default; ~f32 accurate, MXU speed)
    #   "bf16"   - single bf16 stats pass (fastest, lossy)
    #   "f32"    - full f32 dots (XLA 'highest' precision)
    #   "int16"/"int8" - QUANTIZED gradients: per-iteration stochastic
    #   rounding onto an integer grid, narrow-int MXU dots with exact
    #   int32 accumulation.  Data-parallel split decisions are bit-
    #   identical for any shard count (int32 psum is associative) and
    #   the stats operand is 2-4x narrower than hilo's
    "tpu_hist_precision": ("str", "hilo", ("hist_precision",)),
    # gradient-grid rounding under tpu_hist_precision=int16|int8:
    # "stochastic" (unbiased, deterministic given `seed`, invariant to
    # row sharding) or "nearest"
    "tpu_quant_round": ("str", "stochastic", ()),
    # quantized training only: recompute final leaf outputs from the true
    # f32 grad/hess sums over each leaf's rows (split decisions stay
    # integer-exact; leaf values regain float precision — LightGBM
    # quantized training's renew-leaf).  Turn off for strictly bitwise
    # cross-shard model files
    "tpu_quant_refit_leaves": ("bool", True, ()),
    # persistent XLA compilation cache directory (empty = off): repeat
    # runs of same-shaped programs skip the cold compile tail.  Applied
    # at first device use (jax_compilation_cache_dir); CPU-destined
    # processes get a host-fingerprinted subdir (utils/backend.py)
    "tpu_compile_cache_dir": ("str", "", ()),
    # persisted perf autotuning (utils/autotune.py): off | load | tune.
    #   off  - every "auto" resolves from the built-in heuristics
    #   load - resolve "auto" (hist impl x block, hist_agg) from the
    #          measured profile file when a matching (backend, topology,
    #          shape-bucket) entry exists; a profile recorded on a
    #          DIFFERENT platform or device count is refused loudly
    #          (AutotuneStaleProfile), never silently applied
    #   tune - run the measurement sweep for this dataset's shape bucket
    #          first (tools/perf_probe.py's hist sweep), persist the
    #          winners, then resolve like load.  `perf_probe tune` runs
    #          the same sweep standalone
    "tpu_autotune": ("str", "off", ()),
    # autotune profile path; empty = autotune_profile.json beside the
    # persistent XLA compile cache (tpu_compile_cache_dir), or the
    # in-repo .lgbtpu_autotune.json when no cache dir is set
    "tpu_autotune_profile": ("str", "", ()),
    # rows per histogram scan block (device-side); 0 = auto (256 for the
    # pallas backend — its VMEM-resident accumulator wants short blocks —
    # 16384 for the xla scan, tuned for HBM streaming)
    "tpu_block_rows": ("int", 0, ()),
    # leaves split per grower round: >1 batches histogram work onto the MXU
    # (K*5 stat lanes -> 128-lane systolic tiles); 1 = strict reference
    # best-first split order for parity runs; 0 = auto (1 below 32 leaves,
    # num_leaves/16 up to 192, then 25 so K*5 fills one 128-lane tile):
    # batching stays a small fraction of the frontier, so the split order
    # tracks strict best-first closely even while histogramming K leaves
    # per pass
    "tpu_split_batch": ("int", 0, ()),
    # batched-histogram backend: auto | xla | pallas | pallas2 | fused.
    # auto picks the hardware-validated pallas kernel on TPU when its VMEM
    # working set fits (measured 1.9x over the xla scan on Higgs-1M: the
    # one-hot never round-trips to HBM), else xla.  pallas2 = per-feature
    # one-hot variant running 2-8k-row blocks.  fused = the grow
    # megakernel (ops/fused.py): pallas2's accumulator PLUS in-VMEM
    # sibling subtraction and the split gain scan, emitting per-feature
    # best-split records so split search never leaves the device.  The
    # in-kernel scan engages on serial quantized (int8/int16) plain dense
    # training — bit-identical models to the unfused path — and degrades
    # to pallas2 + device select() everywhere else.  auto promotes
    # int8/int16 to fused on TPU only after the runtime validation probe
    # (fused.fused_scan_ok) passes; a Mosaic failure falls back LOUDLY
    "tpu_hist_impl": ("str", "auto", ()),
    # data-axis histogram aggregation (tree_learner=data / voting /
    # data_feature): psum | scatter | auto.
    #   psum    - every shard receives the full aggregated [K, F, B, 3]
    #             histograms (XLA lowers to reduce-scatter + all-gather)
    #             and repeats the whole split search P times
    #   scatter - stop after the reduce-scatter (lax.psum_scatter): each
    #             shard keeps only its F/P feature slice of the
    #             aggregated histograms and pool, searches just that
    #             slice, and the global winner is ONE tiny best-split
    #             record (all_gather + shared deterministic tie-break) —
    #             the reference's Network::ReduceScatter +
    #             SyncUpGlobalBestSplit (data_parallel_tree_learner.cpp:
    #             149-163).  ~2× less ICI receive volume, ~P× less
    #             per-shard histogram-pool HBM, and the search runs once
    #             instead of P times; int8/int16 decisions stay
    #             bit-identical to psum at every shard count.  In voting
    #             mode the voted [k, B, 3] aggregation scatters instead.
    #   auto    - scatter whenever the data axis spans >1 device
    "tpu_hist_agg": ("str", "auto", ()),
    # f64 histogram accumulation everywhere (requires x64): serial and
    # data-parallel split decisions become reduction-order independent,
    # like the reference f64 HistogramBinEntry (bin.h:33-40)
    "deterministic": ("bool", False, ()),
    # only batch leaves whose gain >= alpha * the round's best gain (near
    # ties); keeps batched split order close to strict best-first
    "tpu_split_batch_alpha": ("float", 0.0, ()),
    # row-partition lowering: select | vselect | gather | kernel
    # (ops/grower.py GrowerParams.partition_impl; honored by every tree
    # learner).  vselect fuses the K unrolled select passes into one
    # [K, n] block — fewer program points, but its CATEGORICAL path
    # gathers per-row from a tiny table (the pattern select avoids);
    # prefer select on categorical-heavy data until vselect is
    # hardware-timed there.  kernel = the pallas row->leaf partition
    # (ops/fused.py partition_rows): vselect's exact integer math as one
    # VMEM pass over the row blocks instead of a separate XLA program
    # point — plain dense numerical columns only (no categoricals, EFB,
    # sparse storage, or 4-bit packing)
    "tpu_partition_impl": ("str", "select", ()),
    # frontier ramp: unrolled K'=1,2,4,... pre-rounds before the full-K
    # loop (bit-identical trees, removes early rounds' dead-slot MXU
    # work; see GrowerParams.ramp).  On v5e Higgs-1M it is worth ~10%
    # (docs/PERF_NOTES.md round-3 sweep: 3.14 vs 2.84 it/s at
    # pallas2/8192/K=25)
    "tpu_ramp": ("bool", True, ()),
    # feature shards in the 2-D tree_learner=data_feature mesh: the
    # num_machines devices factor as (num_machines/f, f) over
    # ('data', 'feature'); 0 = auto (2).  The analog of the reference's
    # device x parallel template nesting (parallel_tree_learner.h:25-187)
    "tpu_feature_shards": ("int", 0, ()),
    # hosts axis of the (hosts, data, feature) topology
    # (parallel/topology.py) — the process/DCN tier every row-axis
    # collective also reduces over.  0 = auto (the live jax process
    # count; the only valid setting on real multi-host meshes).  A
    # positive value pins the axis on a SINGLE process, laying the local
    # devices out exactly as that many hosts would — the simulated
    # multi-host grid the (hosts x devices) bitwise tests sweep
    "tpu_topology_hosts": ("int", 0, ()),
    # compile-cache shape policy: quantize the padded (rows, features)
    # axes so at most this many distinct shapes exist per power-of-2
    # octave — new datasets of similar size reuse cached XLA programs
    # instead of paying the cold remote compile.  Worst-case pad waste
    # is 2/buckets (~6% at the default 32).  0 = exact block-multiple
    # padding (maximum throughput; bench.py pins this)
    "tpu_shape_buckets": ("int", 32, ()),
    # pack two 4-bit bins per byte when max_bin<=16 (reference
    # dense_nbits_bin.hpp): halves the pallas histogram row sweep's DMA
    # traffic; automatically skipped when the layout can't support it
    # (EFB bundles, gather partition, xla hist impl)
    "tpu_pack_bins": ("bool", True, ()),
    # sparse train-time storage (reference OrderedSparseBin,
    # src/io/ordered_sparse_bin.hpp / sparse_bin.hpp:73): features whose
    # nonzero-bin row fraction is <= this threshold are stored as padded
    # COO (row-id, bin) pairs instead of dense [n] columns — wide very-
    # sparse datasets stop paying dense HBM for empty rows.  Histograms
    # come from a gather contraction over the stored entries with the
    # zero bin reconstructed from leaf totals (the FixHistogram trick,
    # dataset.cpp:1044-1063).  0 disables.  Requires tree_learner=serial,
    # data, or voting, and enable_bundle=false (EFB is the alternative
    # mitigation).
    "tpu_sparse_threshold": ("float", 0.0, ()),
    # device-resident forest prediction (ops/predict.py): jitted bin-space
    # traversal for valid-score updates, score replay, and device='tpu'
    # Booster.predict.
    #   auto  - score replay goes on-device above tpu_predict_min_rows;
    #           Booster.predict uses the device path only when the default
    #           jax backend is a TPU (the native OMP walker wins on CPU)
    #   true  - always use the device predictor where structurally possible
    #   false - host/native predictors everywhere (parity oracle path)
    "tpu_predict_device": ("str", "auto", ()),
    # rows per device-predict chunk: bounds the [rows, F] bin block and the
    # [k, rows] score block shipped per kernel launch; full-size chunks are
    # padded so multi-chunk predicts reuse ONE compiled program
    "tpu_predict_chunk_rows": ("int", 65536, ()),
    # below this row count the auto mode keeps score replay on the host
    # walker (jit dispatch + compile dominate tiny valid sets)
    "tpu_predict_min_rows": ("int", 4096, ()),
    # launch-shape bucket policy (ops/predict.py BUCKET_POLICIES) shared
    # by training-time score replay, the chunked device predict path,
    # serving warmup enumeration, and bench — every layer quantizes its
    # launch shapes through the SAME ladder, so warmup can pre-compile
    # exactly the set a request can trigger.
    #   wide - rows pad on a x4 ladder from a 4096 floor, depth trip
    #          counts floor at 8, and the grower's frontier ramp steps
    #          x4: strictly fewer distinct programs (a full predict-size
    #          sweep compiles 3 instead of 7 at the default chunk), at up
    #          to 4x padded rows on small batches
    #   fine - the pre-round-6 shapes: pow2 rows from a 1024 floor, exact
    #          pow2 depth buckets, x2 ramp — lowest small-batch predict
    #          latency, most programs
    "tpu_bucket_policy": ("str", "wide", ()),
    # donate the per-iteration score buffers and the [L, G/P, B, 3]
    # histogram pool to XLA (jit donate_argnums): the pool is threaded
    # through the grower and rewritten in place across iterations instead
    # of being re-allocated per tree, and the score update reuses the old
    # scores buffer.  Outputs are bit-identical with donation on or off;
    # turn off when debugging with retained references to per-iteration
    # device arrays (donated buffers are deleted at dispatch)
    "tpu_donate_buffers": ("bool", True, ()),
    # device-parallel dataset ingest (ops/binning.py): raw rows are
    # quantized on the accelerator in streamed chunks (host key prep for
    # chunk i+1 overlaps device binning of chunk i) and the [n, F] bin
    # matrix stays device-resident — the host copy materializes lazily,
    # only when a host consumer (EFB planning, get_data, save_binary)
    # asks.  Bins are bit-identical to the host path on every backend
    # (integer-key compares, never f32 float compares).
    #   auto  - device binning only when the default jax backend is an
    #           accelerator (host numpy wins on plain CPU)
    #   true  - always route ingest through the device kernel
    #   false - host numpy binning everywhere (the reference path)
    "tpu_ingest_device": ("str", "auto", ()),
    # rows per ingest chunk: bounds the [chunk, F] key-plane upload and
    # the kernel's compare working set; every chunk reuses ONE compiled
    # program (the last partial chunk pads up to this size)
    "tpu_ingest_chunk_rows": ("int", 65536, ()),
    # below this row count ingest stays on the host even in auto mode
    # (kernel dispatch overhead dominates tiny matrices)
    "tpu_ingest_min_rows": ("int", 16384, ()),
}

_ALIAS: Dict[str, str] = {}
for _name, (_t, _d, _aliases) in _P.items():
    _ALIAS[_name] = _name
    for _a in _aliases:
        _ALIAS[_a] = _name


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "t", "yes", "on", "+"):
        return True
    if s in ("false", "0", "f", "no", "off", "-"):
        return False
    raise ValueError(f"cannot parse bool from {v!r}")


def parse_tristate(v: Any) -> str:
    """'true' / 'false' / 'auto' from a bool-ish or mode string — the ONE
    spelling authority for tri-state params like tpu_predict_device, so
    predict routing and training-time replay can never disagree on a
    value.  Unrecognized spellings raise: a typo silently mapped to
    'auto' would run the opposite of the requested configuration."""
    s = str(v).strip().lower()
    if s == "auto":
        return "auto"
    return "true" if _parse_bool(s) else "false"


def _coerce(typ: str, v: Any) -> Any:
    if typ == "int":
        return int(float(v)) if not isinstance(v, int) else v
    if typ == "float":
        return float(v)
    if typ == "bool":
        return _parse_bool(v)
    if typ == "str":
        return str(v)
    if typ in ("int_list", "float_list", "str_list"):
        if isinstance(v, (list, tuple)):
            items: List[Any] = list(v)
        else:
            s = str(v).strip()
            items = [x for x in s.replace(";", ",").split(",") if x != ""]
        if typ == "int_list":
            return [int(float(x)) for x in items]
        if typ == "float_list":
            return [float(x) for x in items]
        return [str(x) for x in items]
    raise ValueError(f"unknown param type {typ}")


OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg", "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


@dataclasses.dataclass
class Config:
    """Resolved training configuration.

    Construct with `Config(params_dict)` or `Config.from_string("k1=v1 k2=v2")`.
    Unknown keys are kept in `extra` (and warned about) so callers can pass
    through framework-specific knobs.
    """

    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        self.params = {k: (list(v) if isinstance(v, list) else v)
                       for k, (t, v, _a) in _P.items()}
        self.extra = {}
        if params:
            self.update(params)
        self._check_conflicts()

    # -- mapping-ish access ------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        params = self.__dict__.get("params")
        if params is not None and name in params:
            return params[name]
        raise AttributeError(name)

    def __getitem__(self, name: str) -> Any:
        return self.params[_ALIAS.get(name, name)]

    def get(self, name: str, default: Any = None) -> Any:
        return self.params.get(_ALIAS.get(name, name), default)

    def update(self, params: Dict[str, Any]) -> None:
        for k, v in params.items():
            canon = _ALIAS.get(str(k).strip())
            if canon is None:
                self.extra[str(k)] = v
                continue
            typ = _P[canon][0]
            self.params[canon] = _coerce(typ, v)
        self._normalize()

    def _normalize(self) -> None:
        obj = str(self.params["objective"]).strip().lower()
        self.params["objective"] = OBJECTIVE_ALIASES.get(obj, obj)
        self.params["boosting"] = str(self.params["boosting"]).strip().lower()
        self.params["tree_learner"] = str(self.params["tree_learner"]).strip().lower()
        self.params["device_type"] = str(self.params["device_type"]).strip().lower()

    _MULTICLASS_OBJECTIVES = ("multiclass", "multiclassova", "softmax",
                              "multiclass_ova", "ova", "ovr")
    _MULTICLASS_METRICS = _MULTICLASS_OBJECTIVES + (
        "multi_logloss", "multi_error", "auc_mu")

    def _check_conflicts(self) -> None:
        # mirrors reference Config::CheckParamConflict (src/io/config.cpp:248)
        p = self.params
        learner = p["tree_learner"]
        if learner not in ("serial", "feature", "data", "voting",
                           "feature_parallel", "data_parallel",
                           "voting_parallel", "data_feature", "feature_data",
                           "data_feature_parallel"):
            raise ValueError(f"unknown tree_learner {learner!r}")

        # multiclass objective <-> num_class <-> metric consistency
        obj = str(p["objective"])
        num_class = int(p["num_class"])
        # custom objectives count as multiclass when num_class > 1
        # (reference config.cpp:251)
        obj_multi = obj in self._MULTICLASS_OBJECTIVES or (
            obj in ("custom", "none", "null", "na") and num_class > 1)
        if obj_multi and num_class <= 1:
            raise ValueError("num_class must be > 1 for multiclass training")
        if not obj_multi and obj and num_class != 1 \
                and str(p["task"]).lower() in ("train", "training"):
            raise ValueError("num_class must be 1 for non-multiclass "
                             "training")
        for mt in p["metric"]:
            norm = str(mt).strip().lower()
            if norm in ("", "none", "null", "na", "custom"):
                continue  # disabled/custom metrics match anything
            mt_multi = norm in self._MULTICLASS_METRICS
            if obj and (obj_multi != mt_multi):
                raise ValueError(
                    f"multiclass objective and metric {mt!r} don't match")

        # max_depth caps num_leaves (config.cpp:303-315)
        max_depth = int(p["max_depth"])
        if max_depth > 0:
            full = 2 ** min(max_depth, 30)
            if full < int(p["num_leaves"]):
                p["num_leaves"] = int(full)

        # GOSS re-weights instead of bagging (reference goss.hpp ResetGoss
        # raises Log::Fatal on bagging with goss)
        if str(p["boosting"]) == "goss" and (
                float(p["bagging_fraction"]) < 1.0
                or int(p["bagging_freq"]) > 0):
            raise ValueError("cannot use bagging in GOSS")

    # -- string parsing ----------------------------------------------------
    @staticmethod
    def str_to_map(text: str) -> Dict[str, str]:
        """Parse 'k1=v1 k2=v2' (whitespace/newline separated) into a dict.

        Mirrors reference Config::Str2Map (src/io/config.cpp:41); '#' starts
        a comment, as in reference .conf files.
        """
        out: Dict[str, str] = {}
        for raw_line in text.replace("\r", "\n").split("\n"):
            line = raw_line.split("#", 1)[0].strip()
            if not line:
                continue
            for tok in line.split():
                if "=" in tok:
                    k, v = tok.split("=", 1)
                    out[k.strip()] = v.strip()
        return out

    @staticmethod
    def load_conf_file(path: str) -> Dict[str, str]:
        """Parse a reference-style .conf file (one `key = value` per line)."""
        out: Dict[str, str] = {}
        with open(path) as f:
            for raw_line in f:
                line = raw_line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                out[k.strip()] = v.strip()
        return out

    @classmethod
    def from_string(cls, text: str) -> "Config":
        return cls(cls.str_to_map(text))

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self.params)
        d.update(self.extra)
        return d


def canonical_name(name: str) -> Optional[str]:
    return _ALIAS.get(name)
