from .histogram import build_histogram, pack_stats
from .predict import (PackedForest, forest_class_scores, forest_leaf_values,
                      pack_trees)
from .split import find_best_split_all_features
