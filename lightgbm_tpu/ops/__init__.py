from .histogram import build_histogram, pack_stats
from .split import find_best_split_all_features
