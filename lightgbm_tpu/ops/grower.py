"""Leaf-wise tree growth as a single compiled device program.

The reference grows best-first one split at a time with pointer-chasing state
(reference src/treelearner/serial_tree_learner.cpp:173-237): an LRU histogram
pool, permuted row-index partitions, and per-leaf OrderedBin re-sorts.  None
of that maps to XLA.  Here the whole tree is ONE `lax.scan` of num_leaves-1
steps over fixed-shape tensors:

* leaf assignment is an [n] int32 vector (splits become `where` updates, the
  analog of DataPartition::Split, data_partition.hpp:111-163);
* the smaller/larger-leaf trick + histogram subtraction carries over verbatim
  as tensor subtraction (serial_tree_learner.cpp:428-437,566-572): each step
  histograms only the smaller child and derives the larger by subtracting
  from the parent's pooled histogram;
* the histogram pool is a dense [num_leaves, F, B, 3] tensor (the analog of
  HistogramPool, feature_histogram.hpp:654-831, without the LRU since HBM
  holds it whole);
* best-split search is the vectorized cumsum+argmax in ops/split.py;
* step records are emitted as scan outputs; the host assembles the Tree
  model from them afterwards.

Cost model: each step is O(n) masked one-hot matmul work regardless of leaf
size (vs the reference's O(n_leaf)); the subtraction trick halves it.  The
perf milestone adds leaf-gather compaction; the win is that 500 trees x 254
splits run with 500 dispatches instead of 127k.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import build_histogram_inline, pack_stats
from .split import (K_MIN_SCORE, SplitResult, find_best_split_all_features,
                    leaf_output, MISSING_NAN, MISSING_ZERO)


class GrowerParams(NamedTuple):
    """Static (compile-time) grower configuration."""
    num_leaves: int
    num_bins: int          # padded bin-axis size B
    block_rows: int
    precision: str
    l1: float
    l2: float
    max_delta_step: float
    min_data_in_leaf: float
    min_sum_hessian: float
    min_gain_to_split: float
    max_depth: int


def make_grower(params: GrowerParams, num_features: int,
                data_axis: Optional[str] = None, jit: bool = True):
    """Build the jitted whole-tree grower for fixed shapes/params.

    With `data_axis` set, the grower runs INSIDE shard_map over a mesh axis
    holding row shards: histograms and scalar stats are psum-reduced across
    the axis (the TPU-native replacement for the reference's
    Network::ReduceScatter of histogram buffers + HistogramBinEntry::
    SumReducer, data_parallel_tree_learner.cpp:149-163).  Every shard then
    sees GLOBAL histograms, makes identical split decisions, and partitions
    only its local rows — mirroring the reference data-parallel learner's
    use of global counts with local partitions.
    """
    L = params.num_leaves
    B = params.num_bins
    F = num_features
    precision = params.precision

    def preduce(x):
        return jax.lax.psum(x, data_axis) if data_axis else x

    split_kw = dict(l1=params.l1, l2=params.l2,
                    max_delta_step=params.max_delta_step,
                    min_data_in_leaf=params.min_data_in_leaf,
                    min_sum_hessian=params.min_sum_hessian,
                    min_gain_to_split=params.min_gain_to_split)

    def best_split(hist, sg, sh, cnt, meta, feature_mask,
                   min_c=-1e30, max_c=1e30):
        return find_best_split_all_features(
            hist, sg, sh, cnt,
            meta["num_bin"], meta["missing_type"], meta["default_bin"],
            meta["monotone"], meta["penalty"], feature_mask,
            min_constraint=min_c, max_constraint=max_c, **split_kw)

    def histogram(bins_pad, stats_pad):
        nb = bins_pad.shape[0] // params.block_rows if bins_pad.shape[0] >= params.block_rows else 1
        block = bins_pad.shape[0] // nb
        return build_histogram_inline(
            bins_pad.reshape(nb, block, F),
            stats_pad.reshape(stats_pad.shape[0], nb, block),
            B, precision)

    def masked_stats(grad, hess, mask):
        return pack_stats(grad * mask, hess * mask, mask, precision)

    def grow(bins_pad: jnp.ndarray,     # [n_pad, F] int32 (rows >= n zero-filled)
             grad: jnp.ndarray,         # [n_pad] f32 (padding rows zero)
             hess: jnp.ndarray,         # [n_pad] f32
             row_mask: jnp.ndarray,     # [n_pad] f32 (bagging x padding)
             feature_mask: jnp.ndarray,  # [F] f32
             meta: Dict[str, jnp.ndarray]):
        n_pad = bins_pad.shape[0]

        # ---- root ----------------------------------------------------
        g = grad * row_mask
        h = hess * row_mask
        sum_g = preduce(jnp.sum(g))
        sum_h = preduce(jnp.sum(h))
        cnt = preduce(jnp.sum(row_mask))
        root_hist = preduce(
            histogram(bins_pad, masked_stats(grad, hess, row_mask)))
        root_split = best_split(root_hist, sum_g, sum_h, cnt, meta, feature_mask)

        def stash(arr, i, val, pred=True):
            return arr.at[i].set(jnp.where(pred, val, arr[i]))

        state = {
            "leaf_ids": jnp.zeros(n_pad, jnp.int32),
            "pool": jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(root_hist),
            "leaf_sum_g": jnp.zeros(L, jnp.float32).at[0].set(sum_g),
            "leaf_sum_h": jnp.zeros(L, jnp.float32).at[0].set(sum_h),
            "leaf_cnt": jnp.zeros(L, jnp.float32).at[0].set(cnt),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_output": jnp.zeros(L, jnp.float32).at[0].set(
                leaf_output(sum_g, sum_h, params.l1, params.l2,
                            params.max_delta_step)),
            # stored best split per leaf
            "bs_gain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(root_split.gain),
            "bs_feat": jnp.zeros(L, jnp.int32).at[0].set(root_split.feature),
            "bs_thr": jnp.zeros(L, jnp.int32).at[0].set(root_split.threshold),
            "bs_dleft": jnp.zeros(L, jnp.bool_).at[0].set(root_split.default_left),
            "bs_lg": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_g),
            "bs_lh": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_h),
            "bs_lc": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_count),
            "bs_lo": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_output),
            "bs_ro": jnp.zeros(L, jnp.float32).at[0].set(root_split.right_output),
            # monotone value constraints per leaf (propagated on split)
            "leaf_min": jnp.full(L, -1e30, jnp.float32),
            "leaf_max": jnp.full(L, 1e30, jnp.float32),
            "active": jnp.array(True),
        }

        def step(state, s):
            # pick the leaf with max stored gain (only first s+1 slots filled;
            # unfilled slots hold K_MIN_SCORE)
            depth_ok = jnp.logical_or(
                params.max_depth <= 0,
                state["leaf_depth"] < params.max_depth)
            cand_gain = jnp.where(depth_ok, state["bs_gain"], K_MIN_SCORE)
            best_leaf = jnp.argmax(cand_gain).astype(jnp.int32)
            gain = cand_gain[best_leaf]
            do = state["active"] & (gain > 0.0)

            f = state["bs_feat"][best_leaf]
            thr = state["bs_thr"][best_leaf]
            dleft = state["bs_dleft"][best_leaf]
            lg = state["bs_lg"][best_leaf]
            lh = state["bs_lh"][best_leaf]
            lc = state["bs_lc"][best_leaf]
            lo = state["bs_lo"][best_leaf]
            ro = state["bs_ro"][best_leaf]

            pg = state["leaf_sum_g"][best_leaf]
            ph = state["leaf_sum_h"][best_leaf]
            pc = state["leaf_cnt"][best_leaf]
            rg, rh, rc = pg - lg, ph - lh, pc - lc

            # ---- partition (reference dense_bin.hpp Split semantics) ----
            col = jnp.take(bins_pad, f, axis=1)
            m_type = meta["missing_type"][f]
            nb_f = meta["num_bin"][f]
            db_f = meta["default_bin"][f]
            is_missing = jnp.where(
                m_type == MISSING_NAN, col == nb_f - 1,
                jnp.where(m_type == MISSING_ZERO, col == db_f, False))
            go_left = jnp.where(is_missing, dleft, col <= thr)
            in_leaf = state["leaf_ids"] == best_leaf
            new_leaf = (s + 1).astype(jnp.int32)
            leaf_ids = jnp.where(do & in_leaf & (~go_left), new_leaf,
                                 state["leaf_ids"])

            # ---- histograms: smaller child direct, larger by subtraction
            smaller_is_left = lc <= rc
            smaller_id = jnp.where(smaller_is_left, best_leaf, new_leaf)
            m = ((leaf_ids == smaller_id) & in_leaf).astype(jnp.float32) * row_mask
            hist_small = preduce(
                histogram(bins_pad, masked_stats(grad, hess, m)))
            parent_hist = state["pool"][best_leaf]
            hist_large = parent_hist - hist_small
            hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
            hist_right = jnp.where(smaller_is_left, hist_large, hist_small)

            pool = state["pool"]
            pool = pool.at[best_leaf].set(jnp.where(do, hist_left, parent_hist))
            pool = pool.at[new_leaf].set(jnp.where(do, hist_right,
                                                   pool[new_leaf]))

            # ---- monotone constraint propagation -----------------------
            # (reference serial_tree_learner.cpp:840-851)
            p_min = state["leaf_min"][best_leaf]
            p_max = state["leaf_max"][best_leaf]
            mono_f = meta["monotone"][f]
            mid = (lo + ro) / 2.0
            l_min = jnp.where(mono_f < 0, mid, p_min)
            l_max = jnp.where(mono_f > 0, mid, p_max)
            r_min = jnp.where(mono_f > 0, mid, p_min)
            r_max = jnp.where(mono_f < 0, mid, p_max)

            # ---- find best splits for the two children -----------------
            split_l = best_split(hist_left, lg, lh, lc, meta, feature_mask,
                                 l_min, l_max)
            split_r = best_split(hist_right, rg, rh, rc, meta, feature_mask,
                                 r_min, r_max)

            def upd(key, i, val):
                state[key] = stash(state[key], i, val, do)

            new_state = dict(state)
            new_state["leaf_ids"] = leaf_ids
            new_state["pool"] = pool
            for key, li, ri in (("leaf_sum_g", lg, rg), ("leaf_sum_h", lh, rh),
                                ("leaf_cnt", lc, rc), ("leaf_output", lo, ro),
                                ("leaf_min", l_min, r_min),
                                ("leaf_max", l_max, r_max)):
                arr = new_state[key]
                arr = stash(arr, best_leaf, li, do)
                arr = stash(arr, new_leaf, ri, do)
                new_state[key] = arr
            d = new_state["leaf_depth"]
            d = stash(d, new_leaf, d[best_leaf] + 1, do)
            d = stash(d, best_leaf, d[best_leaf] + 1, do)
            new_state["leaf_depth"] = d
            for key, lv, rv in (
                    ("bs_gain", split_l.gain, split_r.gain),
                    ("bs_feat", split_l.feature, split_r.feature),
                    ("bs_thr", split_l.threshold, split_r.threshold),
                    ("bs_dleft", split_l.default_left, split_r.default_left),
                    ("bs_lg", split_l.left_sum_g, split_r.left_sum_g),
                    ("bs_lh", split_l.left_sum_h, split_r.left_sum_h),
                    ("bs_lc", split_l.left_count, split_r.left_count),
                    ("bs_lo", split_l.left_output, split_r.left_output),
                    ("bs_ro", split_l.right_output, split_r.right_output)):
                arr = new_state[key]
                arr = stash(arr, best_leaf, lv, do)
                arr = stash(arr, new_leaf, rv, do)
                new_state[key] = arr
            new_state["active"] = do

            # pack the step record into one f32 row: a single [L-1, 15] array
            # means ONE device->host transfer per tree (transfer latency, not
            # bandwidth, dominates on tunneled/remote TPU attachments)
            rec = jnp.stack([
                best_leaf.astype(jnp.float32), f.astype(jnp.float32),
                thr.astype(jnp.float32), dleft.astype(jnp.float32),
                gain, lo, ro, lc, rc, lh, rh,
                state["leaf_output"][best_leaf], ph, pc,
                do.astype(jnp.float32)])
            return new_state, rec

        state, records = jax.lax.scan(step, state, jnp.arange(L - 1))
        return {
            "records": records,      # [L-1, 15] f32, fields per REC_* indices
            "leaf_ids": state["leaf_ids"],
            "leaf_output": state["leaf_output"],
            "leaf_cnt": state["leaf_cnt"],
            "leaf_sum_h": state["leaf_sum_h"],
        }

    return jax.jit(grow) if jit else grow


# record-row field indices (see `rec` stack in make_grower.step)
REC_LEAF, REC_FEATURE, REC_THRESHOLD, REC_DEFAULT_LEFT, REC_GAIN, \
    REC_LEFT_OUTPUT, REC_RIGHT_OUTPUT, REC_LEFT_COUNT, REC_RIGHT_COUNT, \
    REC_LEFT_WEIGHT, REC_RIGHT_WEIGHT, REC_INTERNAL_VALUE, \
    REC_INTERNAL_WEIGHT, REC_INTERNAL_COUNT, REC_DID_SPLIT = range(15)


def pad_rows(n: int, block_rows: int) -> int:
    """Rows padded up to a whole number of histogram blocks."""
    block = min(block_rows, max(n, 1))
    return ((n + block - 1) // block) * block
