"""Leaf-wise tree growth as a single compiled device program.

The reference grows best-first one split at a time with pointer-chasing state
(reference src/treelearner/serial_tree_learner.cpp:173-237): an LRU histogram
pool, permuted row-index partitions, and per-leaf OrderedBin re-sorts.  None
of that maps to XLA.  Here the whole tree is ONE `lax.scan` of num_leaves-1
steps over fixed-shape tensors:

* leaf assignment is an [n] int32 vector (splits become `where` updates, the
  analog of DataPartition::Split, data_partition.hpp:111-163);
* the smaller/larger-leaf trick + histogram subtraction carries over verbatim
  as tensor subtraction (serial_tree_learner.cpp:428-437,566-572): each step
  histograms only the smaller child and derives the larger by subtracting
  from the parent's pooled histogram;
* the histogram pool is a dense [num_leaves, F, B, 3] tensor (the analog of
  HistogramPool, feature_histogram.hpp:654-831, without the LRU since HBM
  holds it whole);
* best-split search is the vectorized cumsum+argmax in ops/split.py;
* step records are emitted as scan outputs; the host assembles the Tree
  model from them afterwards.

Distribution — the same grower body runs under shard_map in three sharded
modes, mirroring the reference's parallel tree learners (SURVEY.md §2.3):

* `data_axis` (DataParallelTreeLearner, data_parallel_tree_learner.cpp:
  149-163): rows sharded; the [F, B, 3] histogram is psum-reduced so every
  shard sees GLOBAL histograms and makes identical split decisions, while
  partitioning only its local rows.  XLA lowers the psum to reduce-scatter
  + all-gather over ICI — the hand-rolled Network::ReduceScatter +
  HistogramBinEntry::SumReducer disappear into the compiler.
* `feature_axis` (FeatureParallelTreeLearner, feature_parallel_tree_
  learner.cpp:23-75): rows replicated, features sharded; each shard
  histograms + searches only its own features, then the global best split
  is an all_gather of per-shard best gains + argmax (replacing
  SyncUpGlobalBestSplit's allreduce-by-max, parallel_tree_learner.h:
  190-213).  The winning feature's bin column is broadcast with a one-shard
  psum so every shard partitions identically.
* `data_axis` + `voting_k` (VotingParallelTreeLearner, voting_parallel_
  tree_learner.cpp:170-471 / PV-Tree): rows sharded, but only the top-k
  VOTED features' histograms are aggregated.  Each shard proposes its local
  top-2k features by gain (computed against LOCAL leaf sums with 1/p-scaled
  minimum-data thresholds, :58-59); gains are psum-summed per feature (the
  weighted-gain vote of GlobalVoting, :170-200); the global top-k features'
  histograms are psum'ed ([k, B, 3] instead of [F, B, 3] — top-k gradient
  compression on the data axis) and the final search runs on those.

Cost model: each step is O(n) masked one-hot matmul work regardless of leaf
size (vs the reference's O(n_leaf)); the subtraction trick halves it.  The
perf milestone adds leaf-gather compaction; the win is that 500 trees x 254
splits run with 500 dispatches instead of 127k.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import build_histogram_inline, pack_stats
from .split import (K_MIN_SCORE, SplitResult, finalize_split, leaf_output,
                    per_feature_best_split, per_feature_best_split_categorical,
                    MISSING_NAN, MISSING_ZERO)


class GrowerParams(NamedTuple):
    """Static (compile-time) grower configuration."""
    num_leaves: int
    num_bins: int          # padded bin-axis size B
    block_rows: int
    precision: str
    l1: float
    l2: float
    max_delta_step: float
    min_data_in_leaf: float
    min_sum_hessian: float
    min_gain_to_split: float
    max_depth: int
    # categorical split search (feature_histogram.hpp:118-279); has_cat
    # statically disables the whole categorical path for numerical data
    has_cat: bool = False
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0


def make_grower(params: GrowerParams, num_features: int,
                data_axis: Optional[str] = None,
                feature_axis: Optional[str] = None,
                voting_k: int = 0, num_shards: int = 1, jit: bool = True):
    """Build the whole-tree grower for fixed shapes/params.

    num_features is the LOCAL feature count: with `feature_axis` set it is
    the per-shard shard width and the passed meta/feature_mask arrays are
    the GLOBAL [F_local * num_shards] versions (sliced per shard inside).
    """
    if voting_k and not data_axis:
        raise ValueError("voting requires a data axis")
    if data_axis and feature_axis:
        raise ValueError("2-D (data x feature) growers not supported yet")
    L = params.num_leaves
    B = params.num_bins
    F = num_features
    precision = params.precision

    def preduce_scalar(x):
        return jax.lax.psum(x, data_axis) if data_axis else x

    def preduce_hist(x):
        # plain data-parallel aggregates full histograms; voting keeps the
        # pool LOCAL and aggregates only voted features inside select()
        if data_axis and not voting_k:
            return jax.lax.psum(x, data_axis)
        return x

    split_kw = dict(l1=params.l1, l2=params.l2,
                    max_delta_step=params.max_delta_step,
                    min_data_in_leaf=params.min_data_in_leaf,
                    min_sum_hessian=params.min_sum_hessian,
                    min_gain_to_split=params.min_gain_to_split)
    # local-vote thresholds scaled by 1/p (voting_parallel_tree_learner.
    # cpp:58-59: local min_data/min_hessian are divided by num_machines)
    local_kw = dict(split_kw)
    if voting_k:
        local_kw["min_data_in_leaf"] = params.min_data_in_leaf / num_shards
        local_kw["min_sum_hessian"] = params.min_sum_hessian / num_shards

    # width of the carried categorical bin mask; 1 when the categorical
    # path is statically disabled (numerical-only data)
    CB = B if params.has_cat else 1

    def pf_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c):
        return per_feature_best_split(
            hist, sg, sh, cnt,
            meta["num_bin"], meta["missing_type"], meta["default_bin"],
            meta["monotone"], meta["penalty"], fmask,
            min_constraint=min_c, max_constraint=max_c, **kw)

    def combined_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c):
        """Per-feature bests merging numerical and categorical searches.

        Returns (gain_vec [F'], finalize(best_idx) -> SplitResult) so the
        callers (serial argmax, voting top-k, feature-parallel all-gather)
        can each apply their own winner selection.
        """
        if not params.has_cat:
            pf = pf_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c)

            def fin_plain(bi):
                res = finalize_split(pf, bi, sg, sh,
                                     l1=params.l1, l2=params.l2,
                                     max_delta_step=params.max_delta_step,
                                     min_constraint=min_c,
                                     max_constraint=max_c)
                return res._replace(is_cat=jnp.asarray(False),
                                    cat_mask=jnp.zeros(CB, jnp.float32))
            return pf.gain, fin_plain

        is_cat = meta["is_categorical"] > 0
        catf = is_cat.astype(jnp.float32)
        pf = pf_search(hist, sg, sh, cnt, meta, fmask * (1.0 - catf),
                       kw, min_c, max_c)
        pfc = per_feature_best_split_categorical(
            hist, sg, sh, cnt, meta["num_bin"], meta["missing_type"],
            meta["penalty"], fmask * catf,
            cat_l2=params.cat_l2, cat_smooth=params.cat_smooth,
            max_cat_threshold=params.max_cat_threshold,
            max_cat_to_onehot=params.max_cat_to_onehot,
            min_data_per_group=params.min_data_per_group,
            min_constraint=min_c, max_constraint=max_c, **kw)
        gain = jnp.where(is_cat, pfc.gain, pf.gain)

        def fin(bi):
            resn = finalize_split(pf, bi, sg, sh,
                                  l1=params.l1, l2=params.l2,
                                  max_delta_step=params.max_delta_step,
                                  min_constraint=min_c, max_constraint=max_c)
            c = is_cat[bi]
            return SplitResult(
                gain=gain[bi], feature=bi.astype(jnp.int32),
                threshold=jnp.where(c, 0, resn.threshold).astype(jnp.int32),
                default_left=jnp.where(c, False, resn.default_left),
                left_sum_g=jnp.where(c, pfc.left_sum_g[bi], resn.left_sum_g),
                left_sum_h=jnp.where(c, pfc.left_sum_h[bi], resn.left_sum_h),
                left_count=jnp.where(c, pfc.left_count[bi], resn.left_count),
                left_output=jnp.where(c, pfc.left_output[bi],
                                      resn.left_output),
                right_output=jnp.where(c, pfc.right_output[bi],
                                       resn.right_output),
                is_cat=c,
                cat_mask=pfc.cat_mask[bi] * c.astype(jnp.float32))
        return gain, fin

    def histogram(bins_pad, stats_pad):
        nb = bins_pad.shape[0] // params.block_rows if bins_pad.shape[0] >= params.block_rows else 1
        block = bins_pad.shape[0] // nb
        return build_histogram_inline(
            bins_pad.reshape(nb, block, F),
            stats_pad.reshape(stats_pad.shape[0], nb, block),
            B, precision)

    def masked_stats(grad, hess, mask):
        return pack_stats(grad * mask, hess * mask, mask, precision)

    def grow(bins_pad: jnp.ndarray,     # [n_pad, F] int32 (rows >= n zero-filled)
             grad: jnp.ndarray,         # [n_pad] f32 (padding rows zero)
             hess: jnp.ndarray,         # [n_pad] f32
             row_mask: jnp.ndarray,     # [n_pad] f32 (bagging x padding)
             feature_mask: jnp.ndarray,  # [F] f32 ([F_global] w/ feature_axis)
             meta: Dict[str, jnp.ndarray]):
        n_pad = bins_pad.shape[0]

        if feature_axis:
            ax = jax.lax.axis_index(feature_axis)

            def fslice(a):
                return jax.lax.dynamic_slice_in_dim(a, ax * F, F)

            meta_local = {k: fslice(v) for k, v in meta.items()}
            fmask_local = fslice(feature_mask)
        else:
            ax = None
            meta_local = meta
            fmask_local = feature_mask

        def select(hist, sg, sh, cnt, min_c=-1e30, max_c=1e30) -> SplitResult:
            """Best split across all (global) features for one leaf; the
            returned feature index is GLOBAL in every mode."""
            if voting_k:
                # local leaf totals from any one feature's bins (every row
                # lands in exactly one bin per feature)
                loc = jnp.sum(hist[0], axis=0)
                gain_loc, _ = combined_search(hist, loc[0], loc[1], loc[2],
                                              meta_local, fmask_local,
                                              local_kw, min_c, max_c)
                k2 = min(2 * voting_k, F)
                vals, idx = jax.lax.top_k(gain_loc, k2)
                # weighted-gain vote across shards (GlobalVoting :170-200)
                contrib = jnp.zeros(F, jnp.float32).at[idx].add(
                    jnp.where(vals > K_MIN_SCORE / 2, vals, 0.0))
                score = jax.lax.psum(contrib, data_axis)
                kk = min(voting_k, F)
                _, sel = jax.lax.top_k(score, kk)
                sel = sel.astype(jnp.int32)
                # aggregate ONLY the voted features' histograms
                sel_hist = jax.lax.psum(hist[sel], data_axis)
                sel_meta = {k: v[sel] for k, v in meta_local.items()}
                gain_sel, fin = combined_search(sel_hist, sg, sh, cnt,
                                                sel_meta, fmask_local[sel],
                                                split_kw, min_c, max_c)
                bi = jnp.argmax(gain_sel).astype(jnp.int32)
                res = fin(bi)
                return res._replace(feature=sel[bi])

            gain_vec, fin = combined_search(hist, sg, sh, cnt, meta_local,
                                            fmask_local, split_kw,
                                            min_c, max_c)
            bf = jnp.argmax(gain_vec).astype(jnp.int32)
            res = fin(bf)
            if feature_axis:
                # global best = argmax over per-shard bests (replaces
                # SyncUpGlobalBestSplit, parallel_tree_learner.h:190-213);
                # first-max-wins over shards + contiguous feature sharding
                # reproduces the serial lowest-feature tie-break
                gains = jax.lax.all_gather(res.gain, feature_axis)  # [P]
                winner = jnp.argmax(gains).astype(jnp.int32)
                own = (ax == winner)

                def pick(x):
                    return jax.lax.psum(
                        jnp.where(own, x, jnp.zeros_like(x)), feature_axis)

                res = SplitResult(
                    gain=gains[winner],
                    feature=(winner * F + pick(res.feature)).astype(jnp.int32),
                    threshold=pick(res.threshold).astype(jnp.int32),
                    default_left=pick(res.default_left.astype(jnp.int32)) > 0,
                    left_sum_g=pick(res.left_sum_g),
                    left_sum_h=pick(res.left_sum_h),
                    left_count=pick(res.left_count),
                    left_output=pick(res.left_output),
                    right_output=pick(res.right_output),
                    is_cat=pick(res.is_cat.astype(jnp.int32)) > 0,
                    cat_mask=pick(res.cat_mask))
            return res

        def feature_column(f):
            """Bin column of (global) feature f, on every shard."""
            if feature_axis:
                shard = f // F
                lf = jnp.mod(f, F)
                own = (ax == shard)
                col_l = jnp.take(bins_pad, lf, axis=1)
                return jax.lax.psum(
                    jnp.where(own, col_l, jnp.zeros_like(col_l)), feature_axis)
            return jnp.take(bins_pad, f, axis=1)

        # ---- root ----------------------------------------------------
        g = grad * row_mask
        h = hess * row_mask
        sum_g = preduce_scalar(jnp.sum(g))
        sum_h = preduce_scalar(jnp.sum(h))
        cnt = preduce_scalar(jnp.sum(row_mask))
        root_hist = preduce_hist(
            histogram(bins_pad, masked_stats(grad, hess, row_mask)))
        root_split = select(root_hist, sum_g, sum_h, cnt)

        def stash(arr, i, val, pred=True):
            return arr.at[i].set(jnp.where(pred, val, arr[i]))

        state = {
            "leaf_ids": jnp.zeros(n_pad, jnp.int32),
            "pool": jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(root_hist),
            "leaf_sum_g": jnp.zeros(L, jnp.float32).at[0].set(sum_g),
            "leaf_sum_h": jnp.zeros(L, jnp.float32).at[0].set(sum_h),
            "leaf_cnt": jnp.zeros(L, jnp.float32).at[0].set(cnt),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_output": jnp.zeros(L, jnp.float32).at[0].set(
                leaf_output(sum_g, sum_h, params.l1, params.l2,
                            params.max_delta_step)),
            # stored best split per leaf
            "bs_gain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(root_split.gain),
            "bs_feat": jnp.zeros(L, jnp.int32).at[0].set(root_split.feature),
            "bs_thr": jnp.zeros(L, jnp.int32).at[0].set(root_split.threshold),
            "bs_dleft": jnp.zeros(L, jnp.bool_).at[0].set(root_split.default_left),
            "bs_lg": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_g),
            "bs_lh": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_h),
            "bs_lc": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_count),
            "bs_lo": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_output),
            "bs_ro": jnp.zeros(L, jnp.float32).at[0].set(root_split.right_output),
            # categorical best-split carry: flag + bins-going-left mask
            "bs_iscat": jnp.zeros(L, jnp.bool_).at[0].set(root_split.is_cat),
            "bs_catmask": jnp.zeros((L, CB), jnp.float32).at[0].set(
                root_split.cat_mask),
            # monotone value constraints per leaf (propagated on split)
            "leaf_min": jnp.full(L, -1e30, jnp.float32),
            "leaf_max": jnp.full(L, 1e30, jnp.float32),
            "active": jnp.array(True),
        }

        def step(state, s):
            # pick the leaf with max stored gain (only first s+1 slots filled;
            # unfilled slots hold K_MIN_SCORE)
            depth_ok = jnp.logical_or(
                params.max_depth <= 0,
                state["leaf_depth"] < params.max_depth)
            cand_gain = jnp.where(depth_ok, state["bs_gain"], K_MIN_SCORE)
            best_leaf = jnp.argmax(cand_gain).astype(jnp.int32)
            gain = cand_gain[best_leaf]
            do = state["active"] & (gain > 0.0)

            f = state["bs_feat"][best_leaf]
            thr = state["bs_thr"][best_leaf]
            dleft = state["bs_dleft"][best_leaf]
            lg = state["bs_lg"][best_leaf]
            lh = state["bs_lh"][best_leaf]
            lc = state["bs_lc"][best_leaf]
            lo = state["bs_lo"][best_leaf]
            ro = state["bs_ro"][best_leaf]

            pg = state["leaf_sum_g"][best_leaf]
            ph = state["leaf_sum_h"][best_leaf]
            pc = state["leaf_cnt"][best_leaf]
            rg, rh, rc = pg - lg, ph - lh, pc - lc

            # ---- partition (reference dense_bin.hpp Split /
            # SplitCategorical semantics) ----
            col = feature_column(f)
            m_type = meta["missing_type"][f]
            nb_f = meta["num_bin"][f]
            db_f = meta["default_bin"][f]
            is_missing = jnp.where(
                m_type == MISSING_NAN, col == nb_f - 1,
                jnp.where(m_type == MISSING_ZERO, col == db_f, False))
            go_left = jnp.where(is_missing, dleft, col <= thr)
            iscat_s = state["bs_iscat"][best_leaf]
            if params.has_cat:
                # bitset membership: bins in the stored mask go left,
                # everything else (incl. the NaN bin) goes right
                # (reference CategoricalDecisionInner, tree.h:307-318)
                cmask = state["bs_catmask"][best_leaf]
                go_left = jnp.where(iscat_s, cmask[col] > 0.5, go_left)
            in_leaf = state["leaf_ids"] == best_leaf
            new_leaf = (s + 1).astype(jnp.int32)
            leaf_ids = jnp.where(do & in_leaf & (~go_left), new_leaf,
                                 state["leaf_ids"])

            # ---- histograms: smaller child direct, larger by subtraction
            smaller_is_left = lc <= rc
            smaller_id = jnp.where(smaller_is_left, best_leaf, new_leaf)
            m = ((leaf_ids == smaller_id) & in_leaf).astype(jnp.float32) * row_mask
            hist_small = preduce_hist(
                histogram(bins_pad, masked_stats(grad, hess, m)))
            parent_hist = state["pool"][best_leaf]
            hist_large = parent_hist - hist_small
            hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
            hist_right = jnp.where(smaller_is_left, hist_large, hist_small)

            pool = state["pool"]
            pool = pool.at[best_leaf].set(jnp.where(do, hist_left, parent_hist))
            pool = pool.at[new_leaf].set(jnp.where(do, hist_right,
                                                   pool[new_leaf]))

            # ---- monotone constraint propagation -----------------------
            # (reference serial_tree_learner.cpp:840-851)
            p_min = state["leaf_min"][best_leaf]
            p_max = state["leaf_max"][best_leaf]
            mono_f = meta["monotone"][f]
            mid = (lo + ro) / 2.0
            l_min = jnp.where(mono_f < 0, mid, p_min)
            l_max = jnp.where(mono_f > 0, mid, p_max)
            r_min = jnp.where(mono_f > 0, mid, p_min)
            r_max = jnp.where(mono_f < 0, mid, p_max)

            # ---- find best splits for the two children -----------------
            split_l = select(hist_left, lg, lh, lc, l_min, l_max)
            split_r = select(hist_right, rg, rh, rc, r_min, r_max)

            new_state = dict(state)
            new_state["leaf_ids"] = leaf_ids
            new_state["pool"] = pool
            for key, li, ri in (("leaf_sum_g", lg, rg), ("leaf_sum_h", lh, rh),
                                ("leaf_cnt", lc, rc), ("leaf_output", lo, ro),
                                ("leaf_min", l_min, r_min),
                                ("leaf_max", l_max, r_max)):
                arr = new_state[key]
                arr = stash(arr, best_leaf, li, do)
                arr = stash(arr, new_leaf, ri, do)
                new_state[key] = arr
            d = new_state["leaf_depth"]
            d = stash(d, new_leaf, d[best_leaf] + 1, do)
            d = stash(d, best_leaf, d[best_leaf] + 1, do)
            new_state["leaf_depth"] = d
            for key, lv, rv in (
                    ("bs_gain", split_l.gain, split_r.gain),
                    ("bs_feat", split_l.feature, split_r.feature),
                    ("bs_thr", split_l.threshold, split_r.threshold),
                    ("bs_dleft", split_l.default_left, split_r.default_left),
                    ("bs_lg", split_l.left_sum_g, split_r.left_sum_g),
                    ("bs_lh", split_l.left_sum_h, split_r.left_sum_h),
                    ("bs_lc", split_l.left_count, split_r.left_count),
                    ("bs_lo", split_l.left_output, split_r.left_output),
                    ("bs_ro", split_l.right_output, split_r.right_output),
                    ("bs_iscat", split_l.is_cat, split_r.is_cat),
                    ("bs_catmask", split_l.cat_mask, split_r.cat_mask)):
                arr = new_state[key]
                arr = stash(arr, best_leaf, lv, do)
                arr = stash(arr, new_leaf, rv, do)
                new_state[key] = arr
            new_state["active"] = do

            # pack the step record into one f32 row: a single [L-1, 16(+B)]
            # array means ONE device->host transfer per tree (transfer
            # latency, not bandwidth, dominates on tunneled/remote TPU
            # attachments); cat splits append their bin mask after col 16
            rec = jnp.stack([
                best_leaf.astype(jnp.float32), f.astype(jnp.float32),
                thr.astype(jnp.float32), dleft.astype(jnp.float32),
                gain, lo, ro, lc, rc, lh, rh,
                state["leaf_output"][best_leaf], ph, pc,
                do.astype(jnp.float32), iscat_s.astype(jnp.float32)])
            if params.has_cat:
                rec = jnp.concatenate(
                    [rec, state["bs_catmask"][best_leaf]])
            return new_state, rec

        state, records = jax.lax.scan(step, state, jnp.arange(L - 1))
        return {
            "records": records,      # [L-1, 15] f32, fields per REC_* indices
            "leaf_ids": state["leaf_ids"],
            "leaf_output": state["leaf_output"],
            "leaf_cnt": state["leaf_cnt"],
            "leaf_sum_h": state["leaf_sum_h"],
        }

    return jax.jit(grow) if jit else grow


# record-row field indices (see `rec` stack in make_grower.step); rows are
# 16 wide, plus a trailing [B] categorical bin mask when has_cat
REC_LEAF, REC_FEATURE, REC_THRESHOLD, REC_DEFAULT_LEFT, REC_GAIN, \
    REC_LEFT_OUTPUT, REC_RIGHT_OUTPUT, REC_LEFT_COUNT, REC_RIGHT_COUNT, \
    REC_LEFT_WEIGHT, REC_RIGHT_WEIGHT, REC_INTERNAL_VALUE, \
    REC_INTERNAL_WEIGHT, REC_INTERNAL_COUNT, REC_DID_SPLIT, \
    REC_IS_CAT = range(16)
REC_WIDTH = 16  # categorical mask starts at REC_WIDTH


def pad_rows(n: int, block_rows: int) -> int:
    """Rows padded up to a whole number of histogram blocks."""
    block = min(block_rows, max(n, 1))
    return ((n + block - 1) // block) * block
