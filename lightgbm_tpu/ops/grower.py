"""Leaf-wise tree growth as a single compiled device program.

The reference grows best-first one split at a time with pointer-chasing state
(reference src/treelearner/serial_tree_learner.cpp:173-237): an LRU histogram
pool, permuted row-index partitions, and per-leaf OrderedBin re-sorts.  None
of that maps to XLA.  Here the whole tree is ONE `lax.while_loop` over
BATCHED ROUNDS, each splitting up to `split_batch` leaves at once:

* leaf assignment is an [n] int32 vector (splits become `where` updates, the
  analog of DataPartition::Split, data_partition.hpp:111-163);
* each round picks the top-K leaves by stored best gain (`lax.top_k` over
  the per-leaf candidate table — K-wide best-first, degenerating to the
  reference's strict best-first order at split_batch=1), partitions all K
  leaves' rows in one vectorized pass, and histograms all K smaller
  children in ONE [F*B, n] x [n, K*S] MXU contraction
  (ops/histogram.py build_histogram_batched_inline).  Batching exists for
  the MXU: a single-leaf histogram is an M=8 matmul (~3% MFU measured);
  K leaves widen the small axis to K*S >= 128 lanes, the whole systolic
  array lights up, and a tree takes ~254/K passes instead of 254;
* the smaller/larger-leaf trick + histogram subtraction carries over
  verbatim as tensor subtraction (serial_tree_learner.cpp:428-437,566-572):
  each round histograms only the smaller child of every split and derives
  the sibling from the parent's pooled histogram;
* the histogram pool is a dense [num_leaves, F, B, 3] tensor (the analog of
  HistogramPool, feature_histogram.hpp:654-831, without the LRU since HBM
  holds it whole);
* best-split search for all 2K children is the vectorized cumsum+argmax of
  ops/split.py, vmapped over children;
* step records are written into a fixed [L-1, W] buffer at a dynamic
  offset; the host assembles the Tree model from ONE fetch afterwards.

The `while_loop` trip count is data-dependent (ceil(254/K) rounds when
gains stay positive, up to 254 for pathological chain trees), which XLA
supports natively — no wasted full-data passes on no-op steps.

Distribution — the same round body runs under shard_map in three sharded
modes, mirroring the reference's parallel tree learners (SURVEY.md §2.3):

* `data_axis` (DataParallelTreeLearner, data_parallel_tree_learner.cpp:
  149-163): rows sharded; the [K, F, B, 3] smaller-child histograms
  aggregate over ICI in one of two modes (GrowerParams.hist_agg):
  - "psum": every shard receives the full GLOBAL histograms and makes
    identical split decisions while partitioning only its local rows.
    XLA lowers the psum to reduce-scatter + all-gather — but the
    all-gather half replicates the whole [K, F, B, 3] aggregate to
    every shard, the pool stores all F features P times across the
    mesh, and the split search repeats P times.
  - "scatter": stop after the reduce-scatter (`lax.psum_scatter`) —
    each shard keeps only its CONTIGUOUS F/P feature slice of the
    aggregated histograms, exactly the reference's
    Network::ReduceScatter leaving worker i its own feature block
    (data_parallel_tree_learner.cpp:149-163).  The pool, sibling
    subtraction, EFB expansion, sparse zero-bin fixes, and CEGB
    charges all operate on the slice; the split search runs only over
    it; and the global winner is ONE tiny best-split record: an
    all_gather of per-shard bests + the shared deterministic tie-break
    (the SyncUpGlobalBestSplit analog, parallel_tree_learner.h:
    190-213).  Per-shard pool HBM and psum receive volume both drop
    ~P×.  Integer (int8/int16) psum_scatter sums stay associative, so
    scatter decisions are BIT-IDENTICAL to psum at any shard count.
* `feature_axis` (FeatureParallelTreeLearner, feature_parallel_tree_
  learner.cpp:23-75): BINS REPLICATED (like the reference's all-data-on-
  all-machines feature mode), search sharded; each shard histograms +
  searches only its own feature slice, then the global best split is an
  all_gather of per-shard best gains + argmax (replacing
  SyncUpGlobalBestSplit's allreduce-by-max, parallel_tree_learner.h:
  190-213).  Every shard partitions identically from its full local
  matrix — no per-split column movement at all.
* `data_axis` + `voting_k` (VotingParallelTreeLearner, voting_parallel_
  tree_learner.cpp:170-471 / PV-Tree): rows sharded, but only the top-k
  VOTED features' histograms are aggregated per leaf.  Each shard proposes
  its local top-2k features by gain (computed against LOCAL leaf sums with
  1/p-scaled minimum-data thresholds, :58-59); gains are psum-summed per
  feature (the weighted-gain vote of GlobalVoting, :170-200); the global
  top-k features' histograms are psum'ed ([k, B, 3] instead of [F, B, 3] —
  top-k gradient compression on the data axis) and the final search runs
  on those.

Cost model: each round is one O(n) batched contraction covering up to K
splits, so a 255-leaf tree costs ~ (log2(K) + 254/K) full-data passes at
MXU-shaped operand sizes — versus 254 passes at M=8 shapes before.

Quantized precisions ("int8"/"int16", GrowerParams.precision): grad/hess
discretize per tree onto an integer grid (stochastic rounding hashed on
GLOBAL row indices — sharding-invariant, deterministic given the seed),
the histogram pool/psum/subtraction stay in exact int32, and the scales
rescale (g, h) to f32 once per leaf inside select().  Because integer
sums are associative, the `data_axis` mode's split decisions are
bit-identical for ANY shard count — the fast deterministic mode.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compile_ledger import ledger_jit
from .fused import fused_hist_scan, partition_rows
from .histogram import (build_histogram_batched_t, build_histogram_sparse,
                        build_histogram_t, key_words, pack_stats,
                        quant_limit, quantize_values, unpack2d)
from .split import (K_MIN_SCORE, SplitResult, argbest, finalize_split,
                    leaf_output, leaf_split_gain, numeric_go_left,
                    per_feature_best_split,
                    per_feature_best_split_categorical, unpack_pf_records,
                    MISSING_NAN, MISSING_ZERO)


class GrowerParams(NamedTuple):
    """Static (compile-time) grower configuration.

    Shape-stability discipline (ROADMAP item 3): every field here keys a
    DISTINCT compiled program, so only genuinely structural axes belong —
    operand shapes/dtypes (num_bins, precision, split_batch, sparse/EFB
    storage), kernel choice (hist_impl, partition_impl), and collective
    topology (hist_agg).  Branchless-free boolean switches ride the
    traced `meta["mode_flags"]` vector instead (quantized rounding mode,
    leaf refit, CEGB penalty scalars): one `grow` program serves every
    value of those, bit-identically to the old per-mode closures."""
    num_leaves: int
    num_bins: int          # padded bin-axis size B
    block_rows: int
    precision: str
    l1: float
    l2: float
    max_delta_step: float
    min_data_in_leaf: float
    min_sum_hessian: float
    min_gain_to_split: float
    max_depth: int
    # categorical split search (feature_histogram.hpp:118-279); has_cat
    # statically disables the whole categorical path for numerical data
    has_cat: bool = False
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    # leaves split per round; 1 = strict reference best-first order
    split_batch: int = 16
    # batch only leaves whose gain >= split_batch_alpha * round-max gain:
    # batching near-ties keeps the split order close to strict best-first
    # (a child's gain rarely exceeds a near-tie of its parent's round)
    split_batch_alpha: float = 0.0
    # per-NODE feature sampling (reference GetUsedFeatures with
    # is_tree_level=false, serial_tree_learner.cpp:271-319); Bernoulli
    # form of the reference's exact-count sample, like the GOSS sampler
    feature_fraction_bynode: float = 1.0
    # bins stored packed two-rows-per-byte (reference dense_nbits_bin.hpp,
    # max_bin<=16): halves the histogram row sweep's DMA traffic
    packed_bins: bool = False
    # very-sparse features stored as padded COO (row-id, bin) pairs in
    # meta["sparse_idx"/"sparse_bin"] instead of dense bins_t columns
    # (reference OrderedSparseBin, src/io/ordered_sparse_bin.hpp):
    # histograms come from an O(nnz) gather contraction, the zero bin is
    # reconstructed from leaf totals (FixHistogram, dataset.cpp:1044),
    # and partitions materialize the chosen column on the fly.
    # meta["hist_perm"] maps feature f to its slot in
    # concat(dense columns, sparse groups).
    has_sparse: bool = False
    has_cegb: bool = False
    # lazy per-row acquisition costs: meta carries a [FG, n_pad] paid
    # matrix threaded across trees (feature_used_in_data_ bitset,
    # cost_effective_gradient_boosting.hpp:46-48,88-107)
    has_cegb_lazy: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    # forced splits (reference ForceSplits, serial_tree_learner.cpp:
    # 607-769): static BFS-ordered tuple of (parent_leaf, feature, thr_bin)
    # applied as unrolled rounds before best-gain growth
    forced: tuple = ()
    # batched-histogram backend: "xla" (scan + dot_general), "pallas"
    # or "pallas2" (fused VMEM kernels — ops/histogram.py _hist_pallas
    # with variant="flat" / "perfeature")
    hist_impl: str = "xla"
    # row-partition lowering: "select" unrolls K scalar-broadcast passes
    # (one dynamic row slice + elementwise compare per split — no per-row
    # table gathers, which XLA serializes on TPU); "vselect" fuses those
    # K passes into one [K, n] block (fewer program points; NOTE its
    # categorical path per-row-gathers from the [K, CB] mask table);
    # "gather" resolves each row's slot through [L]/[K] table lookups
    # (one pass, but gather-bound)
    partition_impl: str = "select"
    # EFB (reference FindGroups/FastFeatureBundling, dataset.cpp:91-263):
    # bins_t holds G <= F bundle columns; meta carries bundle_idx /
    # bin_offset / needs_fix per feature and the search expands bundle
    # histograms back to feature space, reconstructing each bundled
    # feature's bin 0 from leaf totals (FixHistogram, dataset.cpp:1044)
    has_bundles: bool = False
    # frontier ramp: statically-unrolled pre-rounds at K' = 1, 2, 4, ...
    # before the full-K while_loop.  After r rounds the frontier holds at
    # most 2^r leaves, so each pre-round's K' covers every possible
    # positive-gain leaf and the grown tree is BIT-IDENTICAL to the plain
    # loop — the ramp only removes the dead-slot contraction work of the
    # first log2(K) rounds (at K=84 that waste is ~half the tree's MXU
    # time).  Disabled automatically when forced splits pre-grow the
    # frontier beyond the 2^r bound.
    ramp: bool = False
    # quantized precisions (int16/int8) only: grad/hess rounding onto the
    # integer grid — "stochastic" (unbiased, hashed global-row-index
    # randomness, shard-count invariant) or "nearest"
    quant_round: str = "stochastic"
    # recompute final leaf outputs from the TRUE f32 grad/hess sums over
    # each leaf's rows (LightGBM quantized training's renew-leaf): split
    # DECISIONS stay integer-exact, leaf values regain float precision
    quant_refit: bool = False
    # frontier-ramp growth factor for the K' pre-round widths (1, s,
    # s^2, ...): any s >= 2 keeps s^(i-1) >= 2^(i-1) (the frontier bound
    # after i-1 rounds), so the tree stays BIT-IDENTICAL to the plain
    # loop at any step.  s=4 halves the unrolled pre-round count — the
    # "wide" bucket policy's compile-time lever for the grow program
    ramp_step: int = 2
    # data-axis histogram aggregation (see the module docstring):
    # "psum" replicates the full aggregate on every shard; "scatter"
    # reduce-scatters (lax.psum_scatter) so each shard keeps only its
    # F/P feature slice of the pool and search, syncing the winner as
    # one best-split record.  In voting mode "scatter" applies to the
    # voted [k, B, 3] aggregation instead (the pool is local anyway).
    hist_agg: str = "psum"


def resolve_split_batch(split_batch: int, num_leaves: int) -> int:
    """Auto-pick the per-round split batch K.

    K trades MXU utilization (bigger contraction N axis) against split-order
    fidelity: each round splits the top-K frontier leaves at once, so
    keeping K a small fraction of num_leaves means only the very top of the
    frontier is batched and the order stays close to strict best-first.
    Measured anchors: K=3 at 31 leaves already costs ~0.05 multiclass
    logloss (small trees cannot absorb batching), while at 255 leaves K=15
    and K=25 train to identical Higgs AUC (0.8268/0.8269,
    docs/PERF_NOTES.md) and K=25 is 1.3x faster — so small trees stay
    strictly sequential and only wide trees ride the full 128-lane MXU
    tile (25 slots x 5 hilo stat rows = 125).
    """
    if split_batch > 0:
        return split_batch
    return max(1, num_leaves // 16) if num_leaves < 192 else 25


# ---- traced mode switches (meta["mode_flags"]) ---------------------------
# Layout of the f32 [MF_WIDTH] vector: boolean mode switches and penalty
# scalars whose branches are branchless-cheap ride the TRACED program
# instead of keying distinct compiled closures.  Callers that omit the
# vector (direct grower tests) fall back to the static GrowerParams fields
# as trace-time constants — the selected values are bit-identical either
# way, so one `grow` program serves every combination.
MF_STOCHASTIC, MF_QUANT_REFIT, MF_CEGB_TRADEOFF, MF_CEGB_SPLIT = range(4)
MF_WIDTH = 4

# the folded fields and their canonical (cache-key) values
_FOLDED_FIELDS = dict(quant_round="stochastic", quant_refit=False,
                      cegb_tradeoff=1.0, cegb_penalty_split=0.0)


def canonical_params(params: GrowerParams) -> GrowerParams:
    """Normalize the mode-flag-folded fields so every structurally
    identical configuration maps onto ONE cached grower program.  Only
    for callers that supply meta["mode_flags"] (the learner does): the
    grower never reads the folded fields then."""
    return params._replace(**_FOLDED_FIELDS)


def mode_flags_np(quant_round: str = "stochastic",
                  quant_refit: bool = False,
                  cegb_tradeoff: float = 1.0,
                  cegb_penalty_split: float = 0.0) -> np.ndarray:
    """Build the meta["mode_flags"] vector for the given mode values."""
    return np.asarray(
        [1.0 if str(quant_round) == "stochastic" else 0.0,
         1.0 if quant_refit else 0.0,
         float(cegb_tradeoff), float(cegb_penalty_split)], np.float32)


def pool_dtype(precision: str):
    """Histogram pool / accumulation dtype for `precision` — the single
    definition shared with the learner's donated-pool allocation."""
    return (jnp.float64 if precision == "f64"
            else jnp.int32 if precision in ("int8", "int16")
            else jnp.float32)


# meta entries that are NOT per-feature [F'] vectors and must be skipped
# by feature-axis slicing and by search-slice meta gathers
NONFEAT_META = ("sparse_idx", "sparse_bin", "hist_perm",
                "scatter_feat", "cegb_paid", "mode_flags")


def make_grower(params: GrowerParams, num_features: int,
                data_axis: Optional[str] = None,
                feature_axis: Optional[str] = None,
                voting_k: int = 0, num_shards: int = 1, jit: bool = True,
                num_columns: Optional[int] = None,
                debug_hist: bool = False, external_pool: bool = False):
    """Build the whole-tree grower for fixed shapes/params.

    num_features is the LOCAL feature count: with `feature_axis` set it is
    the per-shard shard width and the passed meta/feature_mask arrays are
    the GLOBAL [F_local * num_shards] versions (sliced per shard inside).
    num_columns is the bin-matrix column count: G < F when EFB bundling is
    active (has_bundles), otherwise F.

    `data_axis` and `feature_axis` COMPOSE (the reference's parallel
    learners are templates over the device learner so device x
    {feature,data} compose, parallel_tree_learner.h:25-187): rows shard
    over `data`, the histogram/search feature slice over `feature`;
    histograms psum over `data`, per-shard bests all_gather+argmax over
    `feature`, and the scalar leaf sums reduce over `data` only (rows are
    replicated across feature shards).

    Growers are MEMOIZED on every argument: two calls with identical
    configuration return the SAME (jitted) callable, so a second learner
    of the same shape reuses the first one's compiled executables instead
    of re-tracing a fresh closure — the retrace-elimination half of
    ROADMAP item 3 (the zoo was never the one big program, but every
    Booster construction silently re-compiling it).

    external_pool=True adds an 8th `pool` argument (the [L, G/P, B, 3]
    histogram pool in `pool_dtype(precision)`, donated when jit=True):
    the grower zeroes and refills it IN PLACE and returns it as
    out["pool"], so XLA aliases one pool allocation across iterations
    instead of allocating a fresh pool per tree."""
    return _build_grower(params, num_features, data_axis, feature_axis,
                         voting_k, num_shards, jit, num_columns,
                         debug_hist, external_pool)


# bounded: the key includes dataset-shape-derived fields (block_rows,
# num_features), so an unbounded cache would pin one compiled grower per
# distinct shape for the process lifetime in long-lived sweep/serving
# processes.  64 spans any realistic concurrent working set; eviction
# only costs a re-trace on the next same-shaped construction.
@functools.lru_cache(maxsize=64)
def _build_grower(params, num_features, data_axis, feature_axis,
                  voting_k, num_shards, jit, num_columns, debug_hist,
                  external_pool):
    # the axis-addressed collective vocabulary (the ONLY sanctioned
    # spelling of cross-shard ops — graftlint T5xx).  Imported at build
    # time: parallel/strategies.py imports this module, so a module-level
    # import back into parallel/ would cycle.
    from ..parallel.topology import (axis_all_gather, axis_best_split_sync,
                                     axis_index, axis_pmax, axis_psum,
                                     axis_psum_scatter)

    if voting_k and not data_axis:
        raise ValueError("voting requires a data axis")
    if voting_k and feature_axis:
        # the reference's voting learner is a data-parallel variant
        # (voting_parallel_tree_learner.cpp); it does not compose with
        # feature sharding there either
        raise ValueError("voting does not compose with a feature axis")
    L = params.num_leaves
    B = params.num_bins
    F = num_features
    G = num_columns if num_columns is not None else F
    if params.has_bundles and (feature_axis or voting_k):
        raise ValueError("EFB bundling composes with serial/data learners "
                         "only")
    if params.has_bundles and params.forced:
        raise ValueError("EFB bundling does not compose with forced splits; "
                         "set enable_bundle=false")
    if params.packed_bins and (
            params.has_bundles
            or params.partition_impl not in ("select", "vselect")
            or not params.hist_impl.startswith("pallas")):
        raise ValueError(
            "packed 4-bit bins require the pallas histogram impl, a "
            "select-family partition lowering, and no EFB bundling")
    if params.has_sparse and (
            feature_axis or params.has_bundles
            or params.packed_bins
            or params.partition_impl not in ("select", "vselect")):
        # EFB/packing already reshape the dense matrix the sparse split
        # composes with; feature sharding replicates rows — serial,
        # data-parallel, and voting only
        raise ValueError(
            "sparse train-time storage (tpu_sparse_threshold) requires "
            "tree_learner=serial/data/voting, a select-family partition "
            "lowering, and no EFB bundling / 4-bit packing")
    if params.partition_impl == "kernel" and (
            params.has_cat or params.has_bundles or params.has_sparse
            or params.packed_bins):
        raise ValueError(
            "tpu_partition_impl=kernel (the pallas row-partition) covers "
            "plain dense numerical columns only — categorical splits, EFB "
            "bundles, sparse storage, and 4-bit packing keep the "
            "select-family lowerings")
    precision = params.precision
    # quantized-gradient mode (tpu_hist_precision=int16|int8): stats ride
    # the MXU as narrow ints, histograms/pool/psum/subtraction stay in
    # exact int32, and the per-iteration scales rescale (g, h) back to
    # floats once per leaf at the split-search boundary (select)
    quantized = precision in ("int8", "int16")
    if quantized:
        if params.forced:
            raise ValueError("quantized histogram precisions do not "
                             "compose with forced splits")
        if params.has_sparse:
            raise ValueError(
                "quantized histogram precisions do not compose with "
                "sparse train-time storage (tpu_sparse_threshold)")
        if params.quant_round not in ("stochastic", "nearest"):
            raise ValueError(
                f"tpu_quant_round={params.quant_round!r}; expected "
                "stochastic or nearest")
    K = max(1, min(int(params.split_batch), L - 1))

    if params.hist_agg not in ("psum", "scatter"):
        raise ValueError(f"hist_agg={params.hist_agg!r}; expected psum or "
                         "scatter (the learner resolves 'auto' upstream)")
    if external_pool and voting_k:
        raise ValueError("external (donated) histogram pools do not "
                         "compose with voting (its pool is shard-LOCAL "
                         "by design and cannot be a global array)")
    if params.ramp_step < 2:
        raise ValueError(f"ramp_step={params.ramp_step}; the frontier "
                         "bound needs a growth factor >= 2")
    # scatter aggregation: active only with a real (>1) data axis.  In
    # plain data / data_feature modes the POOL is scattered (each shard
    # holds its G/P column slice); voting keeps the pool local and
    # scatters only the voted [k, B, 3] aggregation inside select()
    scatter_on = (params.hist_agg == "scatter" and data_axis is not None
                  and num_shards > 1)
    pool_scatter = scatter_on and not voting_k
    vote_scatter = scatter_on and bool(voting_k)
    if pool_scatter and G % num_shards != 0:
        raise ValueError(
            f"hist_agg=scatter needs the histogram column count {G} padded "
            f"to a multiple of the data-shard count {num_shards}")
    # per-shard column slice and (non-bundle) feature slice widths; with
    # EFB the features of a column slice are resolved through the static
    # meta["scatter_feat"] table instead (columns != features there)
    SG = G // num_shards if pool_scatter else G
    SF = F // num_shards if (pool_scatter and not params.has_bundles) else F
    # the one sparse reconstruction input the scattered slice cannot
    # derive locally: dense_ref's histogram (the leaf-total source) may
    # live on another shard, so exact per-leaf totals are carried in
    # state and threaded into select explicitly
    sparse_tot = pool_scatter and params.has_sparse

    def preduce_scalar(x):
        return axis_psum(x, data_axis) if data_axis else x

    def agg_hist(x):
        """Aggregate LOCAL (per-shard) histograms over the row axes.
        x's feature/column axis is axis -3 ([..., G, B, 3]).  psum
        replicates the full aggregate; scatter (reduce-scatter) leaves
        this shard only its contiguous G/P column slice — shard d holds
        columns [d*SG, (d+1)*SG).  Voting keeps the pool LOCAL and
        aggregates only voted features inside select()."""
        if not data_axis or voting_k:
            return x
        if pool_scatter:
            return axis_psum_scatter(x, data_axis,
                                     scatter_dimension=x.ndim - 3,
                                     tiled=True)
        return axis_psum(x, data_axis)

    split_kw = dict(l1=params.l1, l2=params.l2,
                    max_delta_step=params.max_delta_step,
                    min_data_in_leaf=params.min_data_in_leaf,
                    min_sum_hessian=params.min_sum_hessian,
                    min_gain_to_split=params.min_gain_to_split)
    # local-vote thresholds scaled by 1/p (voting_parallel_tree_learner.
    # cpp:58-59: local min_data/min_hessian are divided by num_machines)
    local_kw = dict(split_kw)
    if voting_k:
        local_kw["min_data_in_leaf"] = params.min_data_in_leaf / num_shards
        local_kw["min_sum_hessian"] = params.min_sum_hessian / num_shards

    # width of the carried categorical bin mask; 1 when the categorical
    # path is statically disabled (numerical-only data)
    CB = B if params.has_cat else 1

    def pf_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c,
                  acc_scale=None):
        return per_feature_best_split(
            hist, sg, sh, cnt,
            meta["num_bin"], meta["missing_type"], meta["default_bin"],
            meta["monotone"], meta["penalty"], fmask,
            min_constraint=min_c, max_constraint=max_c,
            acc_scale=acc_scale, **kw)

    def combined_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c,
                        acc_scale=None):
        """Per-feature bests merging numerical and categorical searches.

        Returns (gain_vec [F'], finalize(best_idx) -> SplitResult) so the
        callers (serial argmax, voting top-k, feature-parallel all-gather)
        can each apply their own winner selection.
        """
        if not params.has_cat:
            pf = pf_search(hist, sg, sh, cnt, meta, fmask, kw, min_c, max_c,
                           acc_scale=acc_scale)

            def fin_plain(bi):
                res = finalize_split(pf, bi, sg, sh,
                                     l1=params.l1, l2=params.l2,
                                     max_delta_step=params.max_delta_step,
                                     min_constraint=min_c,
                                     max_constraint=max_c)
                return res._replace(is_cat=jnp.asarray(False),
                                    cat_mask=jnp.zeros(CB, jnp.float32))
            return pf.gain, fin_plain

        is_cat = meta["is_categorical"] > 0
        catf = is_cat.astype(jnp.float32)
        pf = pf_search(hist, sg, sh, cnt, meta, fmask * (1.0 - catf),
                       kw, min_c, max_c)
        pfc = per_feature_best_split_categorical(
            hist, sg, sh, cnt, meta["num_bin"], meta["missing_type"],
            meta["penalty"], fmask * catf,
            cat_l2=params.cat_l2, cat_smooth=params.cat_smooth,
            max_cat_threshold=params.max_cat_threshold,
            max_cat_to_onehot=params.max_cat_to_onehot,
            min_data_per_group=params.min_data_per_group,
            min_constraint=min_c, max_constraint=max_c, **kw)
        gain = jnp.where(is_cat, pfc.gain, pf.gain)

        def fin(bi):
            resn = finalize_split(pf, bi, sg, sh,
                                  l1=params.l1, l2=params.l2,
                                  max_delta_step=params.max_delta_step,
                                  min_constraint=min_c, max_constraint=max_c)
            c = is_cat[bi]
            return SplitResult(
                gain=gain[bi], feature=bi.astype(jnp.int32),
                threshold=jnp.where(c, 0, resn.threshold).astype(jnp.int32),
                default_left=jnp.where(c, False, resn.default_left),
                left_sum_g=jnp.where(c, pfc.left_sum_g[bi], resn.left_sum_g),
                left_sum_h=jnp.where(c, pfc.left_sum_h[bi], resn.left_sum_h),
                left_count=jnp.where(c, pfc.left_count[bi], resn.left_count),
                left_output=jnp.where(c, pfc.left_output[bi],
                                      resn.left_output),
                right_output=jnp.where(c, pfc.right_output[bi],
                                       resn.right_output),
                is_cat=c,
                cat_mask=pfc.cat_mask[bi] * c.astype(jnp.float32))
        return gain, fin

    bynode = params.feature_fraction_bynode < 1.0

    # in-kernel split scan (hist_impl="fused"): the frontier megakernel
    # runs sibling subtraction + the gain scan in VMEM and the round body
    # consumes its per-feature best records instead of calling select().
    # It engages only where its records provably reproduce select() bit
    # for bit: the serial learner on plain dense quantized columns (the
    # int32 cumsums are exact; every excluded feature — sharding, voting,
    # per-node masks, categorical/EFB/sparse/CEGB/forced, packed bins —
    # reshapes the search itself).  Everywhere else "fused" still rides
    # the perfeature VMEM histogram accumulator and the device-resident
    # select(), so the mode degrades, never errors.
    fused_scan = (params.hist_impl == "fused" and quantized
                  and data_axis is None and feature_axis is None
                  and not voting_k and not bynode
                  and not params.has_cat and not params.has_bundles
                  and not params.has_sparse and not params.has_cegb
                  and not params.forced and not params.packed_bins)

    def grow(bins_t: jnp.ndarray,       # [G, n_pad] uint8/int32 (rows on
             #                            lanes; cols >= n zero-filled)
             grad: jnp.ndarray,         # [n_pad] f32 (padding rows zero)
             hess: jnp.ndarray,         # [n_pad] f32
             row_mask: jnp.ndarray,     # [n_pad] f32 (bagging x padding)
             feature_mask: jnp.ndarray,  # [F] f32 ([F_global] w/ feature_axis)
             meta: Dict[str, jnp.ndarray],
             key: jnp.ndarray,          # PRNG key (per-node sampling)
             pool_buf: Optional[jnp.ndarray] = None):  # donated pool
        #                                 (external_pool only; see above)
        # traced mode switches: present whenever the learner built the
        # meta (one program serves every value); direct callers without
        # the vector fall back to the static params fields as trace-time
        # constants — bit-identical selected values either way
        mf = meta.get("mode_flags")

        def mode_flag(idx: int, static_val: float) -> jnp.ndarray:
            if mf is not None:
                return mf[idx]
            return jnp.float32(static_val)

        # rows come from grad, NOT bins_t: with packed (4-bit) storage the
        # bin matrix holds two rows per byte
        n_pad = grad.shape[0]
        block = min(params.block_rows, n_pad)
        nb = max(n_pad // block, 1)
        block = n_pad // nb
        bcols = block // 2 if params.packed_bins else block

        if feature_axis:
            ax = axis_index(feature_axis)

            def fslice(a):
                return jax.lax.dynamic_slice_in_dim(a, ax * F, F)

            meta_local = {k: (v if k in NONFEAT_META else fslice(v))
                          for k, v in meta.items()}
            # bins arrive REPLICATED [F_global, n] (the reference's
            # all-data-on-all-machines feature mode): histogram only this
            # shard's feature slice; the partition reads the full matrix
            bins_hist_t = fslice(bins_t)
        else:
            ax = None
            meta_local = meta
            bins_hist_t = bins_t
        # this shard's LINEARIZED position on the row axes: under scatter
        # it owns histogram columns [dax*SG, (dax+1)*SG) after the
        # reduce-scatter
        dax = axis_index(data_axis) if scatter_on else None

        FG = feature_mask.shape[0]  # global feature width

        def bynode_masks(k, shape_prefix):
            """Per-node feature masks: Bernoulli(frac) over the tree-level
            mask, falling back to the full mask for empty draws."""
            r = jax.random.uniform(k, shape_prefix + (FG,))
            samp = ((r < params.feature_fraction_bynode)
                    & (feature_mask > 0)).astype(jnp.float32)
            nonempty = jnp.sum(samp, axis=-1, keepdims=True) > 0
            return jnp.where(nonempty, samp, feature_mask)

        def expand_bundles(hist_g, sg, sh, cnt, fmeta=None, col_base=0):
            """[G', B, 3] bundle histograms -> [F', B, 3] feature
            histograms for the features described by `fmeta` (the full
            meta_local by default; a scatter_feat-gathered slice under
            scatter aggregation, where hist_g holds only this shard's
            column slice and col_base is its first global column).

            Each bundled feature's bins live at bin_offset+1..+num_bin-1 of
            its bundle column; its bin 0 (the shared all-default bin) is
            reconstructed from the leaf totals minus the other bins — the
            FixHistogram trick (reference src/io/dataset.cpp:1044-1063)."""
            if not params.has_bundles:
                return hist_g
            if fmeta is None:
                fmeta = meta_local
            bi = jnp.clip(fmeta["bundle_idx"] - col_base, 0,
                          hist_g.shape[0] - 1)             # [F'] local col
            off = fmeta["bin_offset"]                      # [F']
            fix = fmeta["needs_fix"] > 0                   # [F']
            iota_b = jnp.arange(B, dtype=jnp.int32)
            src = jnp.clip(off[:, None] + iota_b[None, :], 0, B - 1)
            hist_f = hist_g[bi[:, None], src]              # [F', B, 3]
            # bundled features: mask bins outside their range, then
            # reconstruct bin 0 from totals
            nbv = fmeta["num_bin"][:, None]
            in_range = (iota_b[None, :] >= 1) & (iota_b[None, :] < nbv)
            keep = jnp.where(fix[:, None], in_range,
                             jnp.ones_like(in_range))
            hist_f = jnp.where(keep[:, :, None], hist_f, 0.0)
            totals = jnp.stack([sg, sh, cnt])             # [3]
            rest = jnp.sum(hist_f, axis=1)                # [F, 3]
            bin0 = totals[None, :] - rest                 # [F, 3]
            hist_f = hist_f.at[:, 0, :].set(
                jnp.where(fix[:, None], bin0, hist_f[:, 0, :]))
            return hist_f

        def fix_sparse_bins(hist, isp, db, totals):
            """hist[f, default_bin] = totals - sum(other bins) where isp:
            the FixHistogram identity (reference dataset.cpp:1044-1063)
            over [F', B, 3] rows with caller-supplied leaf totals."""
            iota_b = jnp.arange(B, dtype=jnp.int32)
            at_db = isp[:, None] & (iota_b[None, :] == db[:, None])
            zeroed = jnp.where(at_db[:, :, None], 0.0, hist)
            bin0 = totals[None, :] - jnp.sum(zeroed, axis=1)
            return jnp.where(at_db[:, :, None], bin0[:, None, :], zeroed)

        def expand_sparse(hist):
            """Reconstruct each sparse feature's zero bin from the leaf
            totals: the stored COO entries cover only nonzero bins.
            [F, B, 3] in and out.

            The totals come from a known-DENSE feature's own histogram
            (every row lands in exactly one bin per feature), not from
            the f32 scalar leaf sums: the reconstruction then stays
            entirely in the histogram accumulation dtype, so
            deterministic f64 sparse storage bit-matches dense — and in
            voting mode, where hist is the shard-LOCAL pool, the derived
            totals are automatically the LOCAL ones the vote needs."""
            if not params.has_sparse:
                return hist
            totals = jnp.sum(hist[meta_local["dense_ref"][0]], axis=0)
            return fix_sparse_bins(hist, meta_local["is_sparse"] > 0,
                                   meta_local["default_bin"], totals)

        # CEGB penalty scalars ride the traced mode-flag vector: changing
        # cegb_tradeoff / cegb_penalty_split between runs no longer keys
        # a fresh compiled program (the per-feature penalties were always
        # traced via meta["cegb_coupled"/"cegb_lazy"])
        cegb_tradeoff = mode_flag(MF_CEGB_TRADEOFF, params.cegb_tradeoff)
        cegb_split_pen = mode_flag(MF_CEGB_SPLIT, params.cegb_penalty_split)

        def cegb_delta(used, cnt, unpaid=None):
            """[M, FG] per-leaf gain charge (DetlaGain,
            cost_effective_gradient_boosting.hpp:50-62): the split
            penalty scaled by the leaf's row count, the coupled
            acquisition penalty for features the model has not used yet,
            and (lazy mode) the per-row on-demand cost for rows that
            have not paid for the feature."""
            d = (cegb_split_pen * cnt[:, None]
                 + meta["cegb_coupled"][None, :] * (1.0 - used)[None, :])
            if unpaid is not None:
                d = d + meta["cegb_lazy"][None, :] * unpaid
            return cegb_tradeoff * d

        def apply_delta(gain_vec, delta):
            return jnp.where(gain_vec > K_MIN_SCORE / 2, gain_vec - delta,
                             gain_vec)

        def sync_best(res: SplitResult, gfeat, axis) -> SplitResult:
            """Global best split from per-shard bests: all_gather ONE tiny
            best-split record per shard over `axis` and pick the winner
            with the shared deterministic tie-break (split.argbest:
            highest gain, then lowest feature id, then lowest threshold
            bin) — the SyncUpGlobalBestSplit analog
            (parallel_tree_learner.h:190-213).  `gfeat` is this shard's
            winning feature id in the frame common to all shards on
            `axis`, and becomes the returned feature."""
            payload = dict(
                default_left=res.default_left.astype(jnp.int32),
                left_sum_g=res.left_sum_g,
                left_sum_h=res.left_sum_h,
                left_count=res.left_count,
                left_output=res.left_output,
                right_output=res.right_output,
                is_cat=res.is_cat.astype(jnp.int32),
                cat_mask=res.cat_mask)
            gain, feat, thr, w = axis_best_split_sync(
                axis, res.gain, gfeat, res.threshold, payload)
            return SplitResult(
                gain=gain,
                feature=feat,
                threshold=thr.astype(jnp.int32),
                default_left=w["default_left"] > 0,
                left_sum_g=w["left_sum_g"],
                left_sum_h=w["left_sum_h"],
                left_count=w["left_count"],
                left_output=w["left_output"],
                right_output=w["right_output"],
                is_cat=w["is_cat"] > 0,
                cat_mask=w["cat_mask"])

        def select(hist, sg, sh, cnt, min_c, max_c, fmask,
                   delta, sp_tot=None) -> SplitResult:
            """Best split across all (global) features for one leaf; the
            returned feature index is GLOBAL in every mode.  vmapped over
            children by the round body.  fmask/delta are global-width.
            sp_tot is the leaf's exact [3] histogram-dtype totals, threaded
            in only under scatter aggregation with sparse storage (the
            slice cannot derive them from dense_ref locally)."""
            fmask_local = fslice(fmask) if feature_axis else fmask
            delta_local = (fslice(delta) if feature_axis else delta) \
                if params.has_cegb else None
            if voting_k:
                # local leaf totals from any one DENSE feature's bins
                # (every row lands in exactly one bin per feature; a
                # sparse column is missing its zero-bin mass)
                dref = (meta_local["dense_ref"][0] if params.has_sparse
                        else 0)
                loc = dequant(jnp.sum(hist[dref], axis=0))
                # sparse features need their LOCAL zero bin before the
                # local gain vote — reconstructed from the SAME `loc`
                # totals that (psum'd) later fix the voted aggregation
                hist_loc = (fix_sparse_bins(hist,
                                            meta_local["is_sparse"] > 0,
                                            meta_local["default_bin"],
                                            loc)
                            if params.has_sparse else hist)
                gain_loc, _ = combined_search(
                    dequant(hist_loc), loc[0], loc[1], loc[2], meta_local,
                    fmask_local, local_kw, min_c, max_c)
                k2 = min(2 * voting_k, F)
                vals, idx = jax.lax.top_k(gain_loc, k2)
                # weighted-gain vote across shards (GlobalVoting :170-200)
                contrib = jnp.zeros(F, jnp.float32).at[idx].add(
                    jnp.where(vals > K_MIN_SCORE / 2, vals, 0.0))
                score = axis_psum(contrib, data_axis)
                kk = min(voting_k, F)
                _, sel = jax.lax.top_k(score, kk)
                sel = sel.astype(jnp.int32)
                if vote_scatter:
                    # reduce-scatter the voted aggregation: pad the voted
                    # set to a shard multiple (extras duplicate sel[0]
                    # with a zeroed mask, so the searched candidate set
                    # is unchanged), psum_scatter the [kp, B, 3] block so
                    # each shard receives only its kp/P slice, search it,
                    # and sync the winner as one best-split record
                    kp = -(-kk // num_shards) * num_shards
                    if kp > kk:
                        sel_p = jnp.concatenate(
                            [sel, jnp.broadcast_to(sel[:1], (kp - kk,))])
                        vmask = jnp.zeros(kp, jnp.float32).at[:kk].set(1.0)
                    else:
                        sel_p, vmask = sel, jnp.ones(kk, jnp.float32)
                    sel_hist = axis_psum_scatter(
                        hist[sel_p], data_axis, scatter_dimension=0,
                        tiled=True)                        # [kp/P, B, 3]
                    W = kp // num_shards
                    sel_loc = jax.lax.dynamic_slice_in_dim(sel_p,
                                                           dax * W, W)
                    fmask_sel = (fmask_local[sel_loc]
                                 * jax.lax.dynamic_slice_in_dim(
                                     vmask, dax * W, W))
                else:
                    sel_loc, sel_hist = sel, None
                    fmask_sel = fmask_local[sel]
                # aggregate ONLY the voted features' histograms — RAW
                # (zero bins reconstructed after the psum from GLOBAL
                # totals); the 2-D COO tables are not per-feature rows
                sel_meta = {k: v[sel_loc] for k, v in meta_local.items()
                            if k not in NONFEAT_META}
                if sel_hist is None:
                    sel_hist = axis_psum(hist[sel], data_axis)
                if params.has_sparse:
                    sel_hist = fix_sparse_bins(
                        sel_hist, sel_meta["is_sparse"] > 0,
                        sel_meta["default_bin"],
                        axis_psum(loc, data_axis))
                gain_sel, fin = combined_search(dequant(sel_hist), sg, sh,
                                                cnt, sel_meta,
                                                fmask_sel,
                                                split_kw, min_c, max_c)
                if params.has_cegb:
                    gain_sel = apply_delta(gain_sel, delta_local[sel_loc])
                # shared tie-break: lowest GLOBAL feature id among equal
                # gains (a plain argmax would inherit the vote ranking)
                bi = argbest(gain_sel, sel_loc)
                res = fin(bi)
                # f32 downcast at the state boundary, like finalize_split
                res = res._replace(feature=sel_loc[bi],
                                   gain=gain_sel[bi].astype(jnp.float32))
                if vote_scatter:
                    res = sync_best(res, sel_loc[bi], data_axis)
                return res

            # the leaf-cost boundary: integer histograms rescale to f32
            # stats HERE, once per leaf — everything upstream (psum or
            # psum_scatter, pool, sibling subtraction) was exact int32.
            # On the plain numerical path the int32 tensor travels one
            # stage further: per_feature_best_split runs its bin cumsums
            # in int32 (exact, reassociation-proof) and dequantizes at
            # the scan boundary — bundle/sparse/categorical expansion
            # needs f32 up front, so those paths rescale here as before
            int_scan = (quantized and not params.has_bundles
                        and not params.has_sparse and not params.has_cat)
            acc = qscale if int_scan else None
            if not int_scan:
                hist = dequant(hist)
            if pool_scatter:
                # scattered slice: this shard holds only the aggregated
                # histogram columns [dax*SG, (dax+1)*SG) — search the
                # features living there against the GLOBAL leaf totals,
                # then sync the winner as one tiny best-split record
                if params.has_bundles:
                    # the features of this shard's column slice, via the
                    # static assignment table (bundle columns != features;
                    # entries sorted ascending, -1 = padding)
                    sfeat = jax.lax.dynamic_index_in_dim(
                        meta["scatter_feat"], dax, 0, keepdims=False)
                    sidx = jnp.maximum(sfeat, 0)
                    fmask_s = (fmask_local[sidx]
                               * (sfeat >= 0).astype(jnp.float32))
                    meta_s = {k: v[sidx] for k, v in meta_local.items()
                              if k not in NONFEAT_META}
                    delta_s = (delta_local[sidx] if params.has_cegb
                               else None)
                    hist = expand_bundles(hist, sg, sh, cnt, meta_s,
                                          col_base=dax * SG)
                else:
                    def dslice(a):
                        return jax.lax.dynamic_slice_in_dim(
                            a, dax * SF, SF)

                    sfeat = dax * SF + jnp.arange(SF, dtype=jnp.int32)
                    fmask_s = dslice(fmask_local)
                    meta_s = {k: dslice(v) for k, v in meta_local.items()
                              if k not in NONFEAT_META}
                    delta_s = (dslice(delta_local) if params.has_cegb
                               else None)
                    if params.has_sparse:
                        # zero-bin reconstruction on the slice from the
                        # threaded exact leaf totals (dense_ref's column
                        # may live on another shard)
                        hist = fix_sparse_bins(hist,
                                               meta_s["is_sparse"] > 0,
                                               meta_s["default_bin"],
                                               sp_tot)
                gain_vec, fin = combined_search(hist, sg, sh, cnt, meta_s,
                                                fmask_s, split_kw,
                                                min_c, max_c, acc_scale=acc)
                if params.has_cegb:
                    gain_vec = apply_delta(gain_vec, delta_s)
                # per-shard best: slice entries ascend in feature id, so
                # first-max argmax = lowest feature id within the shard
                bf = jnp.argmax(gain_vec).astype(jnp.int32)
                res = fin(bf)
                # f32 downcast at the state boundary, like finalize_split
                res = res._replace(gain=gain_vec[bf].astype(jnp.float32))
                # cross-shard winner in the feature-frame-LOCAL id space
                # (global when no feature axis; the feature sync below
                # lifts it to global otherwise)
                res = sync_best(res, sfeat[bf], data_axis)
            else:
                hist = expand_bundles(hist, sg, sh, cnt)
                hist = expand_sparse(hist)
                gain_vec, fin = combined_search(hist, sg, sh, cnt,
                                                meta_local, fmask_local,
                                                split_kw, min_c, max_c,
                                                acc_scale=acc)
                if params.has_cegb:
                    gain_vec = apply_delta(gain_vec, delta_local)
                bf = jnp.argmax(gain_vec).astype(jnp.int32)
                res = fin(bf)
                if params.has_cegb:
                    res = res._replace(gain=gain_vec[bf])
            if feature_axis:
                # global best over feature shards (replaces
                # SyncUpGlobalBestSplit, parallel_tree_learner.h:190-213)
                # with the same shared tie-break; contiguous feature
                # sharding keeps ax*F + local ids ascending, so the
                # winner matches the serial lowest-feature rule exactly
                res = sync_best(res, ax * F + res.feature, feature_axis)
            return res

        vselect = jax.vmap(select,
                           in_axes=(0, 0, 0, 0, 0, 0,
                                    0 if bynode else None,
                                    0 if params.has_cegb else None,
                                    0 if sparse_tot else None))

        # ---- root ----------------------------------------------------
        g = grad * row_mask
        h = hess * row_mask
        if quantized:
            # per-iteration gradient discretization: symmetric max-abs
            # scales per class (max is associative, so pmax makes them
            # bit-identical on every shard), stochastic rounding keyed on
            # GLOBAL row indices (invariant to row sharding), and a grid
            # capped by quant_limit so a worst-case int32 bin can never
            # overflow across the GLOBAL row count
            total_rows = n_pad * (num_shards if data_axis else 1)
            qmax = quant_limit(precision, total_rows)
            amax_g = jnp.max(jnp.abs(g))
            amax_h = jnp.max(jnp.abs(h))
            if data_axis:
                amax_g = axis_pmax(amax_g, data_axis)
                amax_h = axis_pmax(amax_h, data_axis)
            g_scale = jnp.maximum(amax_g, jnp.float32(1e-30)) / qmax
            h_scale = jnp.maximum(amax_h, jnp.float32(1e-30)) / qmax
            # fold_in leaves the caller's split stream untouched, so the
            # bynode draws below stay on their usual sequence
            seed_a, seed_b = key_words(jax.random.fold_in(key, 0x5154))
            row0 = (axis_index(data_axis) * n_pad if data_axis
                    else 0)
            # rounding mode as a traced flag: stochastic and nearest are
            # both elementwise-cheap, so ONE program serves either (the
            # old static `mode` keyed a distinct compile per value)
            sto = mode_flag(MF_STOCHASTIC,
                            1.0 if params.quant_round == "stochastic"
                            else 0.0)
            g_q = quantize_values(g, g_scale, qmax, "stochastic",
                                  seed_a, seed_b, row0, salt=0x9E3779B9,
                                  stochastic=sto)
            h_q = quantize_values(h, h_scale, qmax, "stochastic",
                                  seed_a, seed_b, row0, salt=0x85EBCA6B,
                                  stochastic=sto)
            qscale = jnp.stack([g_scale, h_scale, jnp.float32(1.0)])

            def dequant(hh):
                return hh.astype(jnp.float32) * qscale

            # scalar leaf totals from the SAME quantized values the
            # histograms accumulate (int32 sums, psum-exact), rescaled
            sum_g = (preduce_scalar(jnp.sum(g_q, dtype=jnp.int32))
                     .astype(jnp.float32) * g_scale)
            sum_h = (preduce_scalar(jnp.sum(h_q, dtype=jnp.int32))
                     .astype(jnp.float32) * h_scale)
            cnt = (preduce_scalar(
                jnp.sum(row_mask.astype(jnp.int32), dtype=jnp.int32))
                .astype(jnp.float32))
            stats = pack_stats(g_q, h_q, row_mask, precision)  # [3, n_pad]
        else:
            def dequant(hh):  # identity: floats never rescale
                return hh

            # deterministic (f64) mode: the scalar leaf sums must be
            # reduced in f64 too, or psum reassociation of f32 partials
            # re-enters by the back door
            sum_t = jnp.float64 if precision == "f64" else jnp.float32
            sum_g = preduce_scalar(
                jnp.sum(g, dtype=sum_t)).astype(jnp.float32)
            sum_h = preduce_scalar(
                jnp.sum(h, dtype=sum_t)).astype(jnp.float32)
            cnt = preduce_scalar(
                jnp.sum(row_mask, dtype=sum_t)).astype(jnp.float32)
            # per-tree packed stats, reused by every round's contraction
            stats = pack_stats(g, h, row_mask, precision)     # [S, n_pad]
        S = stats.shape[0]
        # dense column count from the matrix itself: with sparse storage
        # bins_t holds only the dense groups (Gd < G = feature width)
        Gd = bins_hist_t.shape[0]
        bins_blocks = jnp.moveaxis(bins_hist_t.reshape(Gd, nb, bcols), 1, 0)
        stats_blocks = stats.reshape(S, nb, block)

        if fused_scan:
            # static per-feature tables for the megakernel's in-VMEM scan
            # (ops/fused.py layout); feature_mask is baked in because the
            # fused predicate excludes per-node masks
            zi = jnp.zeros(F, jnp.int32)
            fmeta_i = jnp.stack(
                [meta["num_bin"].astype(jnp.int32),
                 meta["missing_type"].astype(jnp.int32),
                 meta["default_bin"].astype(jnp.int32),
                 meta["monotone"].astype(jnp.int32),
                 zi, zi, zi, zi], axis=1)
            zf = jnp.zeros(F, jnp.float32)
            fmeta_f = jnp.stack(
                [meta["penalty"].astype(jnp.float32),
                 feature_mask.astype(jnp.float32),
                 zf, zf, zf, zf, zf, zf], axis=1)

        if params.has_sparse:
            sp_idx_t = meta["sparse_idx"]
            sp_bin_t = meta["sparse_bin"]
            if data_axis:
                # the [d_shards, Gs, M] per-shard tables (rows
                # re-indexed shard-local by the learner) shard their
                # leading axis over 'data': this shard sees its own
                # [1, Gs, M] block
                sp_idx_t = sp_idx_t[0]
                sp_bin_t = sp_bin_t[0]
        else:
            sp_idx_t = sp_bin_t = None

        def merge_sparse_hist(dense_h, leaf_vec, slot_ids):
            """[.., Gd, B, 3] LOCAL dense hist -> [.., G, B, 3] LOCAL
            feature hist: append the sparse groups' O(nnz) gather
            contraction and reorder by the static feature->slot
            permutation.  The caller aggregates the MERGED tensor over
            the data axis (psum is elementwise, so aggregating after the
            merge is value-identical to the old per-part psums — and
            scatter needs the full feature-ordered axis to slice);
            zero-bin reconstruction happens AFTER the aggregation, in
            select, from global totals."""
            if not params.has_sparse:
                return dense_h
            sp = build_histogram_sparse(
                sp_idx_t, sp_bin_t, stats, leaf_vec,
                slot_ids, B, precision)           # [k, Gs, B, 3]
            merged = jnp.concatenate([dense_h, sp], axis=-3)
            return jnp.take(merged, meta["hist_perm"], axis=-3)
        with jax.named_scope("hist_build"):
            if params.hist_impl in ("pallas", "pallas2", "fused"):
                # reuse the batched VMEM kernel (slot 0 = the all-zero
                # root leaf ids): the xla scan at pallas-sized short
                # blocks would round-trip a materialized one-hot per
                # block through HBM
                root_slots = jnp.full(K, -1, jnp.int32).at[0].set(0)
                root_local = build_histogram_batched_t(
                    bins_blocks, stats_blocks,
                    jnp.zeros((nb, block), jnp.int32), root_slots, B,
                    precision, impl=params.hist_impl,
                    packed_rows=params.packed_bins)[0]
            else:
                root_local = build_histogram_t(bins_blocks, stats_blocks,
                                               B, precision)
        if params.has_sparse:
            root_local = merge_sparse_hist(
                root_local[None], jnp.zeros(n_pad, jnp.int32),
                jnp.zeros(1, jnp.int32))[0]
        if sparse_tot:
            # exact per-leaf totals in the ACCUMULATION dtype, reduced
            # from the pre-scatter local histograms (dense_ref's column
            # slice may land on another shard): sum over bins locally,
            # psum the [3] vector — associative for int, exact-in-
            # practice for f64 like every other histogram reduction
            tot_root = preduce_scalar(
                jnp.sum(root_local[meta["dense_ref"][0]], axis=0))
        root_hist = agg_hist(root_local)
        big = jnp.float32(1e30)
        if bynode:
            key, k_root = jax.random.split(key)
            root_fmask = bynode_masks(k_root, ())
        else:
            root_fmask = feature_mask
        # CEGB state persists ACROSS trees (the reference's
        # is_feature_used_in_split_ / feature_used_in_data_ live on the
        # learner, cost_effective_gradient_boosting.hpp:33-48): seeded
        # from meta, returned in the out dict
        if params.has_cegb:
            used0 = meta["cegb_used"]
            if params.has_cegb_lazy:
                paid0 = meta["cegb_paid"]                     # [FG, n_pad] bool
                unpaid_root = jnp.maximum(
                    cnt - jnp.einsum(
                        "fn,n->f", paid0.astype(jnp.float32), row_mask,
                        precision=jax.lax.Precision.HIGHEST),
                    0.0)[None, :]                             # [1, FG]
            else:
                unpaid_root = None
            delta0 = cegb_delta(used0, jnp.reshape(cnt, (1,)),
                                unpaid_root)[0]
        else:
            used0 = jnp.zeros(FG, jnp.float32)
            delta0 = None
        with jax.named_scope("split_search"):
            root_split = select(root_hist, sum_g, sum_h, cnt, -big, big,
                                root_fmask, delta0,
                                tot_root if sparse_tot else None)

        RW = REC_WIDTH + (CB if params.has_cat else 0)
        # the pool stores histograms in the ACCUMULATION dtype: an f32
        # pool under deterministic f64 would silently round every stored
        # leaf histogram back to f32 (and mixed-dtype scatters become
        # errors in future jax) — the reference's deterministic analog
        # keeps f64 HistogramBinEntry end to end (bin.h:33-40).  Int
        # precisions keep the pool in int32 so sibling subtraction stays
        # EXACT (and reduction-order invariant) until select() rescales.
        hist_t = pool_dtype(precision)
        if external_pool:
            # donated scratch: the buffer arrives holding the PREVIOUS
            # iteration's pool, so zero it in place before seeding the
            # root slot — XLA aliases the donated input buffer, so one
            # pool allocation serves every iteration
            if pool_buf.shape != (L, SG, B, 3) or pool_buf.dtype != hist_t:
                raise ValueError(
                    f"external pool must be {(L, SG, B, 3)} {hist_t}; got "
                    f"{pool_buf.shape} {pool_buf.dtype}")
            pool0 = pool_buf.at[:].set(0).at[0].set(root_hist)
        else:
            pool0 = jnp.zeros((L, SG, B, 3), hist_t).at[0].set(root_hist)
        state = {
            "leaf_ids": jnp.zeros(n_pad, jnp.int32),
            # under scatter aggregation the pool holds ONLY this shard's
            # G/P column slice — the P× per-shard HBM saving
            "pool": pool0,
            "leaf_sum_g": jnp.zeros(L, jnp.float32).at[0].set(sum_g),
            "leaf_sum_h": jnp.zeros(L, jnp.float32).at[0].set(sum_h),
            "leaf_cnt": jnp.zeros(L, jnp.float32).at[0].set(cnt),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_output": jnp.zeros(L, jnp.float32).at[0].set(
                leaf_output(sum_g, sum_h, params.l1, params.l2,
                            params.max_delta_step)),
            # stored best split per leaf
            "bs_gain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(root_split.gain),
            "bs_feat": jnp.zeros(L, jnp.int32).at[0].set(root_split.feature),
            "bs_thr": jnp.zeros(L, jnp.int32).at[0].set(root_split.threshold),
            "bs_dleft": jnp.zeros(L, jnp.bool_).at[0].set(root_split.default_left),
            "bs_lg": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_g),
            "bs_lh": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_sum_h),
            "bs_lc": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_count),
            "bs_lo": jnp.zeros(L, jnp.float32).at[0].set(root_split.left_output),
            "bs_ro": jnp.zeros(L, jnp.float32).at[0].set(root_split.right_output),
            # categorical best-split carry: flag + bins-going-left mask
            "bs_iscat": jnp.zeros(L, jnp.bool_).at[0].set(root_split.is_cat),
            "bs_catmask": jnp.zeros((L, CB), jnp.float32).at[0].set(
                root_split.cat_mask),
            # monotone value constraints per leaf (propagated on split)
            "leaf_min": jnp.full(L, -1e30, jnp.float32),
            "leaf_max": jnp.full(L, 1e30, jnp.float32),
            # records buffer: K slack rows so the last round's full-width
            # write stays in bounds; trimmed to [L-1] on return
            "records": jnp.zeros((L - 1 + K, RW), jnp.float32),
            "n_splits": jnp.int32(0),
        }
        if bynode:
            state["key"] = key
        if params.has_cegb:
            state["used"] = used0
            if params.has_cegb_lazy:
                state["paid"] = paid0
        if sparse_tot:
            # exact [L, 3] per-leaf totals in the accumulation dtype: the
            # sparse zero-bin source the scattered slices cannot derive
            # from dense_ref locally; maintained like the pool (smaller
            # child summed+psum'd, sibling by subtraction)
            state["leaf_tot"] = jnp.zeros((L, 3), hist_t).at[0].set(tot_root)

        def cand_gains(state):
            depth_ok = jnp.logical_or(
                params.max_depth <= 0,
                state["leaf_depth"] < params.max_depth)
            return jnp.where(depth_ok, state["bs_gain"], K_MIN_SCORE)

        def cond(state):
            return ((state["n_splits"] < L - 1)
                    & (jnp.max(cand_gains(state)) > 0.0))

        def scatter_set(arr, idx, val, valid):
            # invalid slots write out of bounds -> dropped
            safe = jnp.where(valid, idx, arr.shape[0])
            return arr.at[safe].set(val, mode="drop")

        def fix_bundle_col(raw, off, nbf, fixed):
            """Bundle column -> feature-space bin (elementwise; scalars or
            [n] vectors broadcast).  Bins outside the feature's
            [offset+1, offset+num_bin-1] range are some OTHER bundle
            member's value, i.e. this feature sits at its all-default
            bin 0 (reference src/io/dataset.cpp:91-263)."""
            rel = raw - off
            in_rng = (rel >= 1) & (rel < nbf)
            return jnp.where(fixed, jnp.where(in_rng, rel, 0), raw)

        def exec_round(state, sel, vals, do_k, sel_feat, sel_thr, sel_dleft,
                       sel_iscat, cmask_sel, lg, lh, lc, lo, ro):
            """Execute up to Kr splits (slot k: leaf sel[k] on feature
            sel_feat[k]) — partition, batched child histograms, child
            search, state/record updates.  Shared by the best-gain round
            body (Kr=K), the ramp pre-rounds (Kr = 1, 2, 4, ...) and the
            unrolled forced-split rounds; the round width is the static
            shape of the slot operands."""
            leaf_ids = state["leaf_ids"]
            Kr = sel.shape[0]
            kar = jnp.arange(Kr, dtype=jnp.int32)
            # dtype pinned: under x64 (deterministic mode) jnp.sum would
            # promote to int64 and break the while_loop carry contract
            num_do = jnp.sum(do_k, dtype=jnp.int32)
            new_ids = state["n_splits"] + 1 + kar
            pg = state["leaf_sum_g"][sel]
            ph = state["leaf_sum_h"][sel]
            pc = state["leaf_cnt"][sel]
            rg, rh, rc = pg - lg, ph - lh, pc - lc

            # ---- partition all K splits at once (reference dense_bin.hpp
            # Split / SplitCategorical semantics).  With feature sharding
            # the bins are replicated, so sel_feat's GLOBAL ids index
            # bins_t/meta directly in both lowerings — no column
            # broadcast ----
            if params.partition_impl == "select":
                # K unrolled scalar-broadcast passes: each split reads ONE
                # bin row (dynamic slice) and updates its own rows with
                # elementwise compares.  No per-row table gathers — XLA's
                # TPU gather for tiny tables serializes per element, and at
                # ~8 gathers/round x ~20 rounds it dominated tree time.
                def unpack_feature_row(pr):
                    # packed 4-bit row [n_pad/2] -> [n_pad]; unpack2d is
                    # the single definition of the stride layout
                    return unpack2d(pr.reshape(nb, bcols)).reshape(-1)

                new_leaf = leaf_ids
                for k in range(Kr):
                    f_k = sel_feat[k]
                    if params.has_bundles:
                        raw_k = jax.lax.dynamic_index_in_dim(
                            bins_t, meta["bundle_idx"][f_k], 0,
                            keepdims=False)
                        col_k = fix_bundle_col(
                            raw_k, meta["bin_offset"][f_k],
                            meta["num_bin"][f_k],
                            meta["needs_fix"][f_k] > 0)
                    elif params.has_sparse:
                        # dense read via the feature->column map; sparse
                        # features materialize their column on the fly:
                        # every unstored row sits at the zero bin, the
                        # O(nnz) stored entries scatter over it (pad
                        # entries index n_pad -> dropped)
                        col_k = jax.lax.dynamic_index_in_dim(
                            bins_t, meta["dense_col"][f_k], 0,
                            keepdims=False)
                        slot_k = meta["sparse_slot"][f_k]
                        si_k = jax.lax.dynamic_index_in_dim(
                            sp_idx_t, slot_k, 0, keepdims=False)
                        sb_k = jax.lax.dynamic_index_in_dim(
                            sp_bin_t, slot_k, 0, keepdims=False)
                        scol_k = jnp.full(
                            n_pad, meta["default_bin"][f_k],
                            col_k.dtype).at[si_k].set(
                                sb_k.astype(col_k.dtype), mode="drop")
                        col_k = jnp.where(meta["is_sparse"][f_k] > 0,
                                          scol_k, col_k)
                    else:
                        col_k = jax.lax.dynamic_index_in_dim(
                            bins_t, f_k, 0, keepdims=False)
                        if params.packed_bins:
                            col_k = unpack_feature_row(col_k)
                    go_left_k = numeric_go_left(
                        col_k, meta["missing_type"][f_k],
                        meta["num_bin"][f_k], meta["default_bin"][f_k],
                        sel_thr[k], sel_dleft[k])
                    if params.has_cat:
                        cm_r = jnp.take(cmask_sel[k], col_k)
                        go_left_k = jnp.where(sel_iscat[k], cm_r > 0.5,
                                              go_left_k)
                    in_k = (leaf_ids == sel[k]) & do_k[k]
                    new_leaf = jnp.where(in_k & (~go_left_k),
                                         new_ids[k], new_leaf)
                leaf_ids = new_leaf
            elif params.partition_impl == "vselect":
                # vectorized single-block form of "select": ONE [K, n]
                # row gather + one fused elementwise block instead of K
                # unrolled passes — K fewer program points for launch
                # overhead at ~3 [K, n] intermediates of HBM traffic.
                # Candidate for the non-contraction time (PERF_NOTES
                # round-4); same math as "select" bit-for-bit.
                feat_rows = (meta["bundle_idx"][sel_feat]
                             if params.has_bundles else
                             meta["dense_col"][sel_feat]
                             if params.has_sparse else sel_feat)
                cols = bins_t[feat_rows]                     # [K, n_cols]
                if params.packed_bins:
                    cols = unpack2d(
                        cols.reshape(Kr, nb, bcols)).reshape(Kr, -1)
                if params.has_sparse:
                    # vectorized on-the-fly materialization of the K
                    # chosen columns' sparse variants (see the "select"
                    # branch for the semantics)
                    slots = meta["sparse_slot"][sel_feat]    # [K]
                    si = sp_idx_t[slots]                     # [K, M]
                    sb = sp_bin_t[slots]
                    scols = jnp.broadcast_to(
                        meta["default_bin"][sel_feat][:, None].astype(
                            cols.dtype), (Kr, n_pad)).at[
                        jnp.arange(Kr, dtype=jnp.int32)[:, None], si].set(
                        sb.astype(cols.dtype), mode="drop")
                    cols = jnp.where(
                        (meta["is_sparse"][sel_feat] > 0)[:, None],
                        scols, cols)
                if params.has_bundles:
                    cols = fix_bundle_col(
                        cols, meta["bin_offset"][sel_feat][:, None],
                        meta["num_bin"][sel_feat][:, None],
                        (meta["needs_fix"][sel_feat] > 0)[:, None])
                go_left = numeric_go_left(
                    cols, meta["missing_type"][sel_feat][:, None],
                    meta["num_bin"][sel_feat][:, None],
                    meta["default_bin"][sel_feat][:, None],
                    sel_thr[:, None], sel_dleft[:, None])    # [K, n]
                if params.has_cat:
                    # per-row gather from the tiny [K, CB] mask table —
                    # the pattern "select" exists to avoid on TPU; see
                    # the config.py tpu_partition_impl caveat
                    cm = jnp.take_along_axis(cmask_sel, cols, axis=1)
                    go_left = jnp.where(sel_iscat[:, None], cm > 0.5,
                                        go_left)
                move = ((leaf_ids[None, :] == sel[:, None])
                        & do_k[:, None] & (~go_left))        # [K, n]
                # each row sits in at most one frontier leaf, so a max
                # over slots recovers its (unique) new id; -1 = stay
                moved_to = jnp.max(
                    jnp.where(move, new_ids[:, None], -1), axis=0)
                leaf_ids = jnp.where(moved_to >= 0, moved_to, leaf_ids)
            elif params.partition_impl == "kernel":
                # pallas row-partition (ops/fused.py): one VMEM pass over
                # the row blocks with the exact "vselect" integer math —
                # plain dense numerical columns only (validated at build)
                cols = bins_t[sel_feat]                      # [K, n_pad]
                leaf_ids = partition_rows(
                    cols, leaf_ids, sel, new_ids, sel_thr, sel_dleft,
                    meta["missing_type"][sel_feat],
                    meta["num_bin"][sel_feat],
                    meta["default_bin"][sel_feat], do_k, nb, block)
            else:
                # single-pass gather form: row->slot via an [L]-table
                # lookup, then [K]-table lookups per row
                leaf_to_slot = jnp.full(L, -1, jnp.int32).at[
                    jnp.where(do_k, sel, L)].set(kar, mode="drop")
                k_of_r = leaf_to_slot[leaf_ids]                  # [n]
                valid_r = k_of_r >= 0
                kk_r = jnp.maximum(k_of_r, 0)
                f_r = sel_feat[kk_r]
                if params.has_bundles:
                    g_r = meta["bundle_idx"][f_r]
                    c_r = jnp.take_along_axis(bins_t, g_r[None, :],
                                              axis=0)[0]
                    col_r = fix_bundle_col(
                        c_r, meta["bin_offset"][f_r],
                        meta["num_bin"][f_r],
                        meta["needs_fix"][f_r] > 0)
                else:
                    col_r = jnp.take_along_axis(
                        bins_t, f_r[None, :], axis=0)[0]
                nb_k = meta["num_bin"][sel_feat]
                db_k = meta["default_bin"][sel_feat]
                go_left = numeric_go_left(
                    col_r, meta["missing_type"][sel_feat][kk_r],
                    nb_k[kk_r], db_k[kk_r],
                    sel_thr[kk_r], sel_dleft[kk_r])
                if params.has_cat:
                    # bitset membership: bins in the stored mask go left,
                    # everything else (incl. the NaN bin) goes right
                    # (reference CategoricalDecisionInner, tree.h:307-318)
                    cm_r = cmask_sel.reshape(-1)[kk_r * CB + col_r]
                    go_left = jnp.where(sel_iscat[kk_r], cm_r > 0.5,
                                        go_left)
                leaf_ids = jnp.where(valid_r & (~go_left), new_ids[kk_r],
                                     leaf_ids)

            # ---- monotone constraint propagation -----------------------
            # (reference serial_tree_learner.cpp:840-851); computed before
            # the histograms because the fused megakernel's in-VMEM scan
            # needs the child constraint bounds in its ctx operand
            p_min = state["leaf_min"][sel]
            p_max = state["leaf_max"][sel]
            mono_k = meta["monotone"][sel_feat]
            mid = (lo + ro) / 2.0
            l_min = jnp.where(mono_k < 0, mid, p_min)
            l_max = jnp.where(mono_k > 0, mid, p_max)
            r_min = jnp.where(mono_k > 0, mid, p_min)
            r_max = jnp.where(mono_k < 0, mid, p_max)

            # ---- histograms: all K smaller children in one contraction,
            # siblings by subtraction (on the aggregated slice) ----
            smaller_is_left = lc <= rc
            smaller_ids = jnp.where(
                do_k, jnp.where(smaller_is_left, sel, new_ids), -1)
            parent_hist = state["pool"][sel]             # [K, F/P, B, 3]
            if fused_scan:
                # megakernel: histogram build + sibling subtraction + the
                # split gain scan leave the kernel as [2K, F, RW] records;
                # dead slots (do_k false) carry garbage records that the
                # do_k-gated scatters below drop, exactly like the unfused
                # path's garbage SplitResults
                Cr = 2 * Kr
                use_small = jnp.concatenate(
                    [smaller_is_left, ~smaller_is_left]).astype(jnp.float32)
                ctx = jnp.zeros((Cr + 1, 8), jnp.float32)
                ctx = (ctx.at[:Cr, 0].set(jnp.concatenate([lg, rg]))
                       .at[:Cr, 1].set(jnp.concatenate([lh, rh]))
                       .at[:Cr, 2].set(jnp.concatenate([lc, rc]))
                       .at[:Cr, 3].set(jnp.concatenate([l_min, r_min]))
                       .at[:Cr, 4].set(jnp.concatenate([l_max, r_max]))
                       .at[:Cr, 5].set(use_small)
                       .at[Cr, 0].set(qscale[0])
                       .at[Cr, 1].set(qscale[1])
                       .at[Cr, 2].set(qscale[2]))
                with jax.named_scope("fused_grow"):
                    h_local, srecs = fused_hist_scan(
                        bins_blocks, stats_blocks,
                        leaf_ids.reshape(nb, block), smaller_ids,
                        parent_hist, ctx, fmeta_i, fmeta_f, B, precision,
                        split_kw=split_kw)
                hist_small = h_local        # serial: agg_hist is identity
            else:
                # named_scope: the telemetry span names (hist_build /
                # split_search) appear inside xprof device traces too —
                # trace-time metadata, zero runtime cost
                with jax.named_scope("hist_build"):
                    h_local = build_histogram_batched_t(
                        bins_blocks, stats_blocks,
                        leaf_ids.reshape(nb, block),
                        smaller_ids, B, precision,
                        impl=params.hist_impl,
                        packed_rows=params.packed_bins)      # [K, F, B, 3]
                    h_local = merge_sparse_hist(h_local, leaf_ids,
                                                smaller_ids)
                    if sparse_tot:
                        tot_small = preduce_scalar(jnp.sum(
                            h_local[:, meta["dense_ref"][0]],
                            axis=1))                         # [K, 3]
                    hist_small = agg_hist(h_local)       # [K, F/P, B, 3]
            hist_large = parent_hist - hist_small
            sl = smaller_is_left[:, None, None, None]
            hist_left = jnp.where(sl, hist_small, hist_large)
            hist_right = jnp.where(sl, hist_large, hist_small)

            pool = scatter_set(state["pool"], sel, hist_left, do_k)
            pool = scatter_set(pool, new_ids, hist_right, do_k)

            # ---- best splits for all 2K children -----------------------
            new_state = dict(state)
            if sparse_tot:
                tot_parent = state["leaf_tot"][sel]          # [K, 3]
                tot_large = tot_parent - tot_small
                sl3 = smaller_is_left[:, None]
                tot_left = jnp.where(sl3, tot_small, tot_large)
                tot_right = jnp.where(sl3, tot_large, tot_small)
                lt = scatter_set(state["leaf_tot"], sel, tot_left, do_k)
                new_state["leaf_tot"] = scatter_set(lt, new_ids, tot_right,
                                                    do_k)
                tot_children = jnp.concatenate([tot_left, tot_right])
            else:
                tot_children = None
            if bynode:
                nkey, k_nodes = jax.random.split(state["key"])
                child_masks = bynode_masks(k_nodes, (2 * Kr,))
                new_state["key"] = nkey
            else:
                child_masks = feature_mask
            if params.has_cegb:
                prev_used = state["used"]
                used = scatter_set(prev_used, sel_feat,
                                   jnp.ones(Kr, jnp.float32), do_k)
                new_state["used"] = used
                cnt_children = jnp.concatenate([lc, rc])      # [2K]
                unpaid = None
                if params.has_cegb_lazy:
                    paid = state["paid"]                  # [FG, n_pad] bool
                    # pay the applied splits' costs FIRST: all parent-leaf
                    # rows (pre-partition membership, like the reference
                    # marking bits before DataPartition::Split,
                    # serial_tree_learner.cpp:775-797)
                    pre_memb = ((state["leaf_ids"][None, :] == sel[:, None])
                                & (row_mask[None, :] > 0)
                                & do_k[:, None])
                    pay = jnp.zeros_like(paid).at[sel_feat].max(
                        pre_memb, mode="drop")
                    paid = paid | pay
                    new_state["paid"] = paid
                    # per-child unpaid-row counts for the lazy charge
                    child_ids = jnp.concatenate([sel, new_ids])
                    memb = ((leaf_ids[None, :] == child_ids[:, None])
                            .astype(jnp.float32) * row_mask[None, :])
                    paid_sum = jnp.einsum("kn,fn->kf", memb,
                                          paid.astype(jnp.float32),
                                          precision=jax.lax.Precision.HIGHEST)
                    unpaid = jnp.maximum(
                        cnt_children[:, None] - paid_sum, 0.0)
                delta = cegb_delta(used, cnt_children, unpaid)  # [2K, FG]
                # newly-used features re-credit other leaves' STORED best
                # gains (UpdateLeafBestSplits,
                # cost_effective_gradient_boosting.hpp:64-77); children
                # slots are overwritten by the fresh uncharged search
                # below.  Known bounded approximation vs the reference:
                # only the stored BEST split per leaf is re-credited — a
                # runner-up split on the newly-freed feature cannot be
                # promoted, because per-(leaf, feature) candidate storage
                # ([L, F] SplitInfo, splits_per_leaf_) does not exist in
                # the batched-frontier design
                newly = used - prev_used
                credit = (cegb_tradeoff
                          * meta["cegb_coupled"][state["bs_feat"]]
                          * newly[state["bs_feat"]])
                live = state["bs_gain"] > K_MIN_SCORE / 2
                new_state["bs_gain"] = state["bs_gain"] + \
                    jnp.where(live, credit, 0.0)
            else:
                delta = None
            with jax.named_scope("split_search"):
                if fused_scan:
                    # consume the megakernel's device records: per child,
                    # plain argmax over per-feature gains (features ascend,
                    # so first-max == the serial lowest-feature tie-break)
                    # and the same finalize_split the unfused fin_plain
                    # applies — select() never sees these children
                    def child_from_records(rec_c, sgc, shc, min_c, max_c):
                        pf = unpack_pf_records(rec_c)
                        bf = jnp.argmax(pf.gain).astype(jnp.int32)
                        res = finalize_split(
                            pf, bf, sgc, shc, l1=params.l1, l2=params.l2,
                            max_delta_step=params.max_delta_step,
                            min_constraint=min_c, max_constraint=max_c)
                        return res._replace(
                            is_cat=jnp.asarray(False),
                            cat_mask=jnp.zeros(CB, jnp.float32))

                    ch = jax.vmap(child_from_records)(
                        srecs, jnp.concatenate([lg, rg]),
                        jnp.concatenate([lh, rh]),
                        jnp.concatenate([l_min, r_min]),
                        jnp.concatenate([l_max, r_max]))
                else:
                    ch = vselect(
                        jnp.concatenate([hist_left, hist_right], axis=0),
                        jnp.concatenate([lg, rg]),
                        jnp.concatenate([lh, rh]),
                        jnp.concatenate([lc, rc]),
                        jnp.concatenate([l_min, r_min]),
                        jnp.concatenate([l_max, r_max]),
                        child_masks, delta, tot_children)

            new_state["leaf_ids"] = leaf_ids
            new_state["pool"] = pool
            for key, li, ri in (("leaf_sum_g", lg, rg), ("leaf_sum_h", lh, rh),
                                ("leaf_cnt", lc, rc), ("leaf_output", lo, ro),
                                ("leaf_min", l_min, r_min),
                                ("leaf_max", l_max, r_max)):
                arr = scatter_set(new_state[key], sel, li, do_k)
                new_state[key] = scatter_set(arr, new_ids, ri, do_k)
            d_child = state["leaf_depth"][sel] + 1
            d = scatter_set(state["leaf_depth"], sel, d_child, do_k)
            new_state["leaf_depth"] = scatter_set(d, new_ids, d_child, do_k)
            for key, cv in (("bs_gain", ch.gain), ("bs_feat", ch.feature),
                            ("bs_thr", ch.threshold),
                            ("bs_dleft", ch.default_left),
                            ("bs_lg", ch.left_sum_g), ("bs_lh", ch.left_sum_h),
                            ("bs_lc", ch.left_count), ("bs_lo", ch.left_output),
                            ("bs_ro", ch.right_output),
                            ("bs_iscat", ch.is_cat),
                            ("bs_catmask", ch.cat_mask)):
                arr = scatter_set(new_state[key], sel, cv[:Kr], do_k)
                new_state[key] = scatter_set(arr, new_ids, cv[Kr:], do_k)

            # ---- records: contiguous [K, W] block at row n_splits -------
            rec = jnp.stack([
                sel.astype(jnp.float32), sel_feat.astype(jnp.float32),
                sel_thr.astype(jnp.float32), sel_dleft.astype(jnp.float32),
                vals, lo, ro, lc, rc, lh, rh,
                state["leaf_output"][sel], ph, pc,
                do_k.astype(jnp.float32), sel_iscat.astype(jnp.float32)],
                axis=1)                                      # [K, 16]
            if params.has_cat:
                rec = jnp.concatenate([rec, cmask_sel], axis=1)
            new_state["records"] = jax.lax.dynamic_update_slice(
                state["records"], rec, (state["n_splits"], jnp.int32(0)))
            new_state["n_splits"] = state["n_splits"] + num_do
            return new_state

        def body(state, round_k=None):
            Kr = K if round_k is None else round_k
            vals, sel = jax.lax.top_k(cand_gains(state), Kr)
            sel = sel.astype(jnp.int32)
            kar = jnp.arange(Kr, dtype=jnp.int32)
            budget = (L - 1) - state["n_splits"]
            # vals is sorted descending, so do_k is a prefix mask: records
            # written this round are contiguous
            do_k = (vals > 0.0) & (kar < budget)
            if params.split_batch_alpha > 0.0 and K > 1:
                # near-tie guard (still a prefix: vals descending); alpha
                # is clamped below 1 so slot 0 always qualifies and the
                # while_loop is guaranteed to make progress
                alpha = min(params.split_batch_alpha, 0.999)
                do_k &= vals >= alpha * vals[0]
            return exec_round(
                state, sel, vals, do_k,
                state["bs_feat"][sel], state["bs_thr"][sel],
                state["bs_dleft"][sel], state["bs_iscat"][sel],
                state["bs_catmask"][sel],
                state["bs_lg"][sel], state["bs_lh"][sel],
                state["bs_lc"][sel], state["bs_lo"][sel],
                state["bs_ro"][sel])

        def forced_round(state, ok, parent, feat, thr):
            """One forced split (reference ForceSplits, serial_tree_
            learner.cpp:607-769): leaf `parent` splits on static (feat,
            thr) regardless of best gain; left stats come from the pooled
            histogram at the threshold (GatherInfoForThreshold,
            feature_histogram.hpp:281-419).  A negative forced gain aborts
            this and all remaining forced splits, like the reference's
            aborted_last_force_split."""
            p = jnp.int32(parent)
            iota_b = jnp.arange(B, dtype=jnp.int32)
            mt = meta["missing_type"][feat]
            nb_f = meta["num_bin"][feat]
            db_f = meta["default_bin"][feat]
            nan_excl = (mt == MISSING_NAN) & (iota_b == nb_f - 1)
            mask_b = ((iota_b <= thr) & (iota_b < nb_f)
                      & (~nan_excl)).astype(jnp.float32)
            # the forced feature's pooled column may live on another
            # shard: feature sharding slices the pool by F, scatter
            # aggregation further by SG — the owning shard contributes
            # its sums, everyone else zeros, one psum over the sliced
            # axes broadcasts the result (feat is compile-time constant,
            # so the slice indices stay static)
            f_loc = feat
            own = None
            axes = ()
            if feature_axis:
                own = (f_loc // F) == ax
                f_loc = f_loc % F
                axes += (feature_axis,)
            if pool_scatter:
                own_d = (f_loc // SG) == dax
                f_loc = f_loc % SG
                own = own_d if own is None else (own & own_d)
                # data_axis may itself be an axis TUPLE (hosts, data) —
                # splice its members so the psum sees flat names
                axes += (data_axis if isinstance(data_axis, tuple)
                         else (data_axis,))
            col_hist = state["pool"][p, f_loc]               # [B, 3]
            sums = jnp.sum(col_hist * mask_b[:, None], axis=0)
            if axes:
                sums = axis_psum(
                    jnp.where(own, sums, jnp.zeros_like(sums)), axes)
            if data_axis and voting_k:
                # voting keeps the pool local: forced stats need the
                # global sums
                sums = axis_psum(sums, data_axis)
            lg0, lh0, lc0 = sums[0], sums[1], sums[2]
            pg0 = state["leaf_sum_g"][p]
            ph0 = state["leaf_sum_h"][p]
            pc0 = state["leaf_cnt"][p]
            rg0, rh0, rc0 = pg0 - lg0, ph0 - lh0, pc0 - lc0
            min_c = state["leaf_min"][p]
            max_c = state["leaf_max"][p]
            lo0 = jnp.clip(leaf_output(lg0, lh0, params.l1, params.l2,
                                       params.max_delta_step), min_c, max_c)
            ro0 = jnp.clip(leaf_output(rg0, rh0, params.l1, params.l2,
                                       params.max_delta_step), min_c, max_c)
            shift = leaf_split_gain(pg0, ph0 + 2e-15, params.l1, params.l2,
                                    params.max_delta_step)
            gain0 = (leaf_split_gain(lg0, lh0, params.l1, params.l2,
                                     params.max_delta_step)
                     + leaf_split_gain(rg0, rh0, params.l1, params.l2,
                                       params.max_delta_step)
                     - shift - params.min_gain_to_split)
            do0 = ok & (gain0 >= 0.0) & (lc0 > 0) & (rc0 > 0)
            kar = jnp.arange(K, dtype=jnp.int32)
            first = kar == 0

            def bcast(v, fill=0):
                return jnp.where(first, v, fill)

            dleft0 = (mt == MISSING_ZERO) & (db_f <= thr)
            new_state = exec_round(
                state,
                jnp.full(K, p, jnp.int32),
                bcast(gain0, K_MIN_SCORE),
                first & do0,
                jnp.full(K, feat, jnp.int32),
                jnp.full(K, thr, jnp.int32),
                jnp.broadcast_to(dleft0, (K,)),
                jnp.zeros(K, jnp.bool_),
                jnp.zeros((K, CB), jnp.float32),
                bcast(lg0), bcast(lh0), bcast(lc0), bcast(lo0), bcast(ro0))
            return new_state, do0

        # forced splits run first as statically-unrolled rounds (the
        # forced table is compile-time constant for a training run)
        forced_ok = jnp.asarray(True)
        for parent, feat, thr in params.forced:
            state, forced_ok = forced_round(state, forced_ok,
                                            int(parent), int(feat), int(thr))

        if params.ramp and not params.forced and not bynode and K > 1:
            # frontier ramp (see GrowerParams.ramp): after r rounds the
            # frontier holds <= 2^r leaves, so pre-rounds at K' = 2^r
            # split exactly the leaves the full-K loop would and the tree
            # is bit-identical — only the dead-slot contraction work goes.
            # bynode is excluded: its per-child RNG draw shapes follow the
            # round width, which would change the sampled masks.
            # ramp_step > 2 (the "wide" bucket policy) still covers the
            # frontier (s^i >= 2^i) with fewer unrolled pre-rounds — the
            # grow program's own compile-time lever.
            kr = 1
            while kr < K:
                state = body(state, round_k=kr)
                kr *= int(params.ramp_step)

        state = jax.lax.while_loop(cond, body, state)
        if quantized:
            # leaf-value refit: the tree STRUCTURE came from integer
            # histograms; the final outputs come from the true f32
            # grad/hess sums over each leaf's rows, so leaf values carry
            # no quantization error (LightGBM quantized training's
            # renew-leaf).  f32 psum here is the one reduction whose
            # shard-order ulps can reach the model — turn refit off for
            # strictly bitwise cross-shard model files.  The on/off
            # switch is a TRACED flag (two [L] scatters + a psum are
            # branchless-cheap), so refit on/off shares one program.
            refit_on = mode_flag(MF_QUANT_REFIT,
                                 1.0 if params.quant_refit else 0.0)
            rg = preduce_scalar(
                jnp.zeros(L, jnp.float32).at[state["leaf_ids"]].add(g))
            rh = preduce_scalar(
                jnp.zeros(L, jnp.float32).at[state["leaf_ids"]].add(h))
            refit = jnp.clip(
                leaf_output(rg, rh + jnp.float32(2e-15), params.l1,
                            params.l2, params.max_delta_step),
                state["leaf_min"], state["leaf_max"])
            state["leaf_output"] = jnp.where(
                (state["leaf_cnt"] > 0) & (refit_on > 0),
                refit, state["leaf_output"])
        out = {
            "records": state["records"][:L - 1],  # [L-1, W], REC_* indices
            "leaf_ids": state["leaf_ids"],
            "leaf_output": state["leaf_output"],
            "leaf_cnt": state["leaf_cnt"],
            "leaf_sum_h": state["leaf_sum_h"],
        }
        if external_pool:
            # the (donated, in-place) pool rides back to the caller so
            # the next iteration rewrites the same allocation
            out["pool"] = state["pool"]
        if params.has_cegb:
            # cross-tree CEGB state (the learner threads it into the next
            # tree's meta, matching the reference's learner-lifetime
            # is_feature_used_in_split_ / feature_used_in_data_)
            out["cegb_used"] = state["used"]
            if params.has_cegb_lazy:
                out["cegb_paid"] = state["paid"]
        if debug_hist:
            # the GPU_DEBUG_COMPARE analog (reference gpu_tree_learner.
            # cpp:995-1020): expose the pre-aggregation root histogram so
            # callers can assert the collective math against an
            # independently computed full histogram.  In voting mode this
            # is the LOCAL shard histogram (the pool is local by design);
            # in data mode the psum'd one; in feature mode the shard's
            # feature slice.
            out["root_hist"] = root_hist
        return out

    if not jit:
        return grow
    # the grower's own jit site rides the compile ledger so
    # `tools/perf_probe.py retrace` can attribute every compiled program;
    # with an external pool the 8th arg is donated (in-place reuse)
    jit_kw = {"donate_argnums": (7,)} if external_pool else {}
    return ledger_jit(grow, site="grower.grow", **jit_kw)


# record-row field indices (see `rec` stack in make_grower.body); rows are
# 16 wide, plus a trailing [B] categorical bin mask when has_cat
REC_LEAF, REC_FEATURE, REC_THRESHOLD, REC_DEFAULT_LEFT, REC_GAIN, \
    REC_LEFT_OUTPUT, REC_RIGHT_OUTPUT, REC_LEFT_COUNT, REC_RIGHT_COUNT, \
    REC_LEFT_WEIGHT, REC_RIGHT_WEIGHT, REC_INTERNAL_VALUE, \
    REC_INTERNAL_WEIGHT, REC_INTERNAL_COUNT, REC_DID_SPLIT, \
    REC_IS_CAT = range(16)
REC_WIDTH = 16  # categorical mask starts at REC_WIDTH


def pad_rows(n: int, block_rows: int) -> int:
    """Rows padded up to a whole number of histogram blocks."""
    block = min(block_rows, max(n, 1))
    return ((n + block - 1) // block) * block
