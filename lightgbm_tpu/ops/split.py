"""Best-split search over histograms as masked cumulative sums + argmax.

Re-expresses the reference's sequential two-direction threshold scans
(reference src/treelearner/feature_histogram.hpp:508-644
FindBestThresholdSequence) as vectorized [F, B] tensor ops:

* direction +1 ("missing right"): left stats = prefix sums over bins in
  ascending order, excluding the zero bin for MissingType.Zero features and
  the NaN bin for MissingType.NaN features; right = parent - left, so the
  excluded (missing) mass falls to the right.  default_left = False.
* direction -1 ("missing left"): right stats = suffix sums with the same
  exclusions; left = parent - right, missing mass falls left.
  default_left = True.

Gain math matches feature_histogram.hpp:444-506: L1 soft-thresholded leaf
outputs, L2, max_delta_step clamp, optional monotone-constraint veto; the
reported gain is (left+right gain) - (parent gain + min_gain_to_split),
scaled by the per-feature penalty (CEGB / feature_contri hook).

Tie-breaking mirrors the reference scan order: dir=-1 is scanned first and
keeps the LARGEST threshold among equal gains; dir=+1 replaces only on
strictly greater gain and keeps the smallest threshold.  Across features the
lowest feature index wins ties (ArrayArgs::ArgMax semantics).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

K_MIN_SCORE = -1e30
K_EPSILON = 1e-15

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


def numeric_go_left(col, mt, nbf, db, thr, dleft):
    """Numerical split decision incl. missing-value routing (reference
    dense_bin.hpp Split semantics); elementwise, the single source of
    truth for every partition lowering — the grower's select/vselect/
    gather passes and the fused row-partition kernel (ops/fused.py)
    all route rows through this one function."""
    is_miss = jnp.where(
        mt == MISSING_NAN, col == nbf - 1,
        jnp.where(mt == MISSING_ZERO, col == db, False))
    return jnp.where(is_miss, dleft, col <= thr)


def argbest(gain: jnp.ndarray, feature: jnp.ndarray,
            threshold: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Winner index among candidate splits with the SHARED deterministic
    tie-break: highest gain, ties by lowest global feature id, then by
    lowest threshold bin.

    This is the one rule every cross-candidate winner selection uses —
    the serial/psum per-leaf argmax (features ascending, so plain
    first-max argmax already implements it), the feature-parallel and
    scatter-mode all_gather-of-per-shard-bests syncs, and the voting
    top-k search (whose candidates arrive in VOTE order, where a plain
    argmax would inherit the vote ranking and make equal-gain decisions
    depend on the shard count).  Mirrors the reference's
    ArrayArgs::ArgMax lowest-index semantics lifted to (feature, bin)
    keys.  All comparisons are exact (f32 equality on identically
    computed gains; int keys), so the winner is invariant to the lane
    order of the gathered candidates."""
    elig = gain >= jnp.max(gain)
    big = jnp.int32(2 ** 31 - 1)
    f = jnp.where(elig, feature.astype(jnp.int32), big)
    elig = elig & (feature == jnp.min(f))
    if threshold is not None:
        t = jnp.where(elig, threshold.astype(jnp.int32), big)
        elig = elig & (threshold == jnp.min(t))
    return jnp.argmax(elig).astype(jnp.int32)


def _threshold_l1(s, l1):
    reg = jnp.maximum(0.0, jnp.abs(s) - l1)
    return jnp.sign(s) * reg


def leaf_output(sum_g, sum_h, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:449-456)."""
    out = -_threshold_l1(sum_g, l1) / (sum_h + l2)
    if_clip = (max_delta_step > 0.0)
    clipped = jnp.clip(out, -max_delta_step, max_delta_step)
    return jnp.where(if_clip, clipped, out)


def leaf_split_gain(sum_g, sum_h, l1, l2, max_delta_step):
    """GetLeafSplitGain (feature_histogram.hpp:497-506)."""
    output = leaf_output(sum_g, sum_h, l1, l2, max_delta_step)
    sg_l1 = _threshold_l1(sum_g, l1)
    return -(2.0 * sg_l1 * output + (sum_h + l2) * output * output)


class SplitResult(NamedTuple):
    gain: jnp.ndarray          # scalar f32; <=0 means no valid split
    feature: jnp.ndarray       # i32 index into used features
    threshold: jnp.ndarray     # i32 bin threshold
    default_left: jnp.ndarray  # bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray    # f32
    left_output: jnp.ndarray
    right_output: jnp.ndarray
    is_cat: Optional[jnp.ndarray] = None    # categorical split? (None = no)
    cat_mask: Optional[jnp.ndarray] = None  # [B] f32: bins going LEFT


class PerFeatureBest(NamedTuple):
    """Per-feature best-split candidates (pre cross-feature argmax)."""
    gain: jnp.ndarray        # [F] net gain (min_gain_shift subtracted, penalized)
    threshold: jnp.ndarray   # [F] i32
    default_left: jnp.ndarray  # [F] bool
    left_sum_g: jnp.ndarray  # [F]
    left_sum_h: jnp.ndarray  # [F]
    left_count: jnp.ndarray  # [F]


# flat f32 device-record layout of a PerFeatureBest row: the fused grow
# kernel emits these per (child, feature) and the grower reconstructs the
# candidates without the histograms ever leaving the device.  Every field
# round-trips f32 exactly: gains/sums are f32 already, thresholds are bin
# indices < 2^24, default_left is 0.0/1.0.
PF_REC_GAIN, PF_REC_THRESHOLD, PF_REC_DEFAULT_LEFT, PF_REC_LEFT_G, \
    PF_REC_LEFT_H, PF_REC_LEFT_C = range(6)
PF_RECORD_WIDTH = 8  # padded to a lane-friendly width; fields 6-7 spare


def pack_pf_records(pf: PerFeatureBest) -> jnp.ndarray:
    """[F, PF_RECORD_WIDTH] f32 device records from per-feature bests."""
    F = pf.gain.shape[0]
    return jnp.stack(
        [pf.gain.astype(jnp.float32),
         pf.threshold.astype(jnp.float32),
         pf.default_left.astype(jnp.float32),
         pf.left_sum_g.astype(jnp.float32),
         pf.left_sum_h.astype(jnp.float32),
         pf.left_count.astype(jnp.float32),
         jnp.zeros(F, jnp.float32), jnp.zeros(F, jnp.float32)], axis=1)


def unpack_pf_records(rec: jnp.ndarray) -> PerFeatureBest:
    """Inverse of `pack_pf_records` ([F, PF_RECORD_WIDTH] -> candidates)."""
    return PerFeatureBest(
        gain=rec[:, PF_REC_GAIN],
        threshold=rec[:, PF_REC_THRESHOLD].astype(jnp.int32),
        default_left=rec[:, PF_REC_DEFAULT_LEFT] > 0.5,
        left_sum_g=rec[:, PF_REC_LEFT_G],
        left_sum_h=rec[:, PF_REC_LEFT_H],
        left_count=rec[:, PF_REC_LEFT_C])


def per_feature_best_split(
        hist: jnp.ndarray,        # [F, B, 3] (g, h, cnt)
        sum_g, sum_h, num_data,   # parent totals (scalars, f32)
        num_bin: jnp.ndarray,     # [F] i32 bins per feature
        missing_type: jnp.ndarray,  # [F] i32
        default_bin: jnp.ndarray,   # [F] i32
        monotone: jnp.ndarray,      # [F] i32 in {-1,0,1}
        penalty: jnp.ndarray,       # [F] f32
        feature_mask: jnp.ndarray,  # [F] f32/bool (feature_fraction)
        *, l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: float, min_sum_hessian: float,
        min_gain_to_split: float,
        min_constraint=-1e30, max_constraint=1e30,
        acc_scale=None) -> PerFeatureBest:
    """Best candidate per feature (the voting-parallel building block,
    reference voting_parallel_tree_learner.cpp:327-337 local candidates).

    min/max_constraint are the leaf's monotone value bounds, propagated down
    the tree by the grower (reference serial_tree_learner.cpp:840-851).

    acc_scale (quantized precisions): hist arrives in its int32
    accumulation dtype and the bin cumsums run in int32 — exact and
    reassociation-proof — before the [3] dequantization scales apply.
    Running the scan on pre-dequantized f32 instead would let XLA's
    per-program scan decomposition reassociate the adds, and a last-ulp
    difference in a left sum amplifies through the gain cancellation
    into a visible cross-topology model diff (ROADMAP item 7's residue
    after the bagging-RNG fix)."""
    F, B, _ = hist.shape
    bin_iota = jnp.arange(B, dtype=jnp.int32)[None, :]          # [1, B]
    nb = num_bin[:, None]                                        # [F, 1]

    hg, hh, hc = hist[..., 0], hist[..., 1], hist[..., 2]

    is_zero_missing = (missing_type[:, None] == MISSING_ZERO)
    is_nan_missing = (missing_type[:, None] == MISSING_NAN)
    skip_bin = is_zero_missing & (bin_iota == default_bin[:, None])
    na_bin = is_nan_missing & (bin_iota == nb - 1)
    acc_mask = (~skip_bin) & (~na_bin) & (bin_iota < nb)

    zero = jnp.zeros((), hist.dtype)
    ag = jnp.where(acc_mask, hg, zero)
    ah = jnp.where(acc_mask, hh, zero)
    ac = jnp.where(acc_mask, hc, zero)

    cg = jnp.cumsum(ag, axis=1)                                  # [F, B]
    ch = jnp.cumsum(ah, axis=1)
    cc = jnp.cumsum(ac, axis=1)
    if acc_scale is not None:
        # int32 prefix sums are exact; dequantize at the scan boundary
        cg = cg.astype(jnp.float32) * acc_scale[0]
        ch = ch.astype(jnp.float32) * acc_scale[1]
        cc = cc.astype(jnp.float32) * acc_scale[2]

    gain_shift = leaf_split_gain(sum_g, sum_h + 2 * K_EPSILON,
                                 l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split

    def eval_dir(left_g, left_h, left_c, thr_valid):
        right_g = sum_g - left_g
        right_h = sum_h - left_h
        right_c = num_data - left_c
        ok = (thr_valid
              & (left_c >= min_data_in_leaf) & (right_c >= min_data_in_leaf)
              & (left_h >= min_sum_hessian) & (right_h >= min_sum_hessian))
        lo = jnp.clip(leaf_output(left_g, left_h, l1, l2, max_delta_step),
                      min_constraint, max_constraint)
        ro = jnp.clip(leaf_output(right_g, right_h, l1, l2, max_delta_step),
                      min_constraint, max_constraint)
        mono = monotone[:, None]
        mono_bad = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
        sg_l1_l = _threshold_l1(left_g, l1)
        sg_l1_r = _threshold_l1(right_g, l1)
        g_l = -(2.0 * sg_l1_l * lo + (left_h + l2) * lo * lo)
        g_r = -(2.0 * sg_l1_r * ro + (right_h + l2) * ro * ro)
        gain = jnp.where(mono_bad, 0.0, g_l + g_r)
        gain = jnp.where(ok & (gain > min_gain_shift), gain, K_MIN_SCORE)
        return gain, lo, ro

    # ---- direction +1: left = prefix, missing goes right ----------------
    thr_ok_p1 = (bin_iota <= nb - 2) & (~skip_bin) & \
        jnp.where(is_nan_missing, bin_iota <= nb - 2, True)
    gain_p1, lo_p1, ro_p1 = eval_dir(cg, ch, cc, thr_ok_p1)

    # ---- direction -1: right = suffix, missing goes left ----------------
    # right stats at threshold t = total_acc - prefix[t]
    tg, th, tc = cg[:, -1:], ch[:, -1:], cc[:, -1:]
    left_g_m1 = sum_g - (tg - cg)
    left_h_m1 = sum_h - (th - ch)
    left_c_m1 = num_data - (tc - cc)
    thr_ok_m1 = (bin_iota <= nb - 2 - is_nan_missing.astype(jnp.int32)) & (~skip_bin)
    gain_m1, lo_m1, ro_m1 = eval_dir(left_g_m1, left_h_m1, left_c_m1, thr_ok_m1)

    # ---- per-feature best with reference tie-breaking -------------------
    # dir=-1: largest threshold wins ties -> argmax over reversed bins
    rev = gain_m1[:, ::-1]
    idx_m1 = (B - 1) - jnp.argmax(rev, axis=1)                   # [F]
    best_m1 = jnp.take_along_axis(gain_m1, idx_m1[:, None], axis=1)[:, 0]
    # dir=+1: smallest threshold wins ties -> plain argmax
    idx_p1 = jnp.argmax(gain_p1, axis=1)
    best_p1 = jnp.take_along_axis(gain_p1, idx_p1[:, None], axis=1)[:, 0]

    use_p1 = best_p1 > best_m1                                   # strict >
    feat_gain = jnp.where(use_p1, best_p1, best_m1)
    feat_thr = jnp.where(use_p1, idx_p1, idx_m1).astype(jnp.int32)
    feat_dleft = ~use_p1

    # only-2-bin NaN features get default_left=False in the reference
    # (feature_histogram.hpp:105-108); with a full scan this is cosmetic but
    # keeps model files identical
    two_bin_nan = (num_bin <= 2) & (missing_type == MISSING_NAN)
    feat_dleft = jnp.where(two_bin_nan, False, feat_dleft)

    feat_gain = jnp.where(feature_mask > 0, feat_gain, K_MIN_SCORE)
    out_gain = jnp.where(feat_gain > K_MIN_SCORE / 2,
                         (feat_gain - min_gain_shift) * penalty,
                         K_MIN_SCORE)

    # per-feature left stats at the chosen (threshold, direction)
    f_iota = jnp.arange(F)
    lg = jnp.where(feat_dleft, left_g_m1[f_iota, feat_thr],
                   cg[f_iota, feat_thr])
    lh = jnp.where(feat_dleft, left_h_m1[f_iota, feat_thr],
                   ch[f_iota, feat_thr])
    lc = jnp.where(feat_dleft, left_c_m1[f_iota, feat_thr],
                   cc[f_iota, feat_thr])
    return PerFeatureBest(gain=out_gain, threshold=feat_thr,
                          default_left=feat_dleft,
                          left_sum_g=lg, left_sum_h=lh, left_count=lc)


def finalize_split(pf: PerFeatureBest, best_f, sum_g, sum_h,
                   *, l1: float, l2: float, max_delta_step: float,
                   min_constraint=-1e30, max_constraint=1e30) -> SplitResult:
    """SplitResult for the chosen feature index (post argmax/vote/gather)."""
    g = pf.gain[best_f]
    thr = pf.threshold[best_f]
    dleft = pf.default_left[best_f]
    lg = pf.left_sum_g[best_f]
    lh = pf.left_sum_h[best_f]
    lc = pf.left_count[best_f]
    lo = jnp.clip(leaf_output(lg, lh, l1, l2, max_delta_step),
                  min_constraint, max_constraint)
    ro = jnp.clip(leaf_output(sum_g - lg, sum_h - lh, l1, l2, max_delta_step),
                  min_constraint, max_constraint)
    # the grower's stored-split state is f32; under deterministic f64 the
    # candidate math above runs in f64 and must downcast HERE, at the one
    # boundary, or every .at[].set into the state becomes a mixed-dtype
    # scatter (a future-jax error)
    f32 = lambda x: jnp.asarray(x).astype(jnp.float32)  # noqa: E731
    return SplitResult(
        gain=f32(g),
        feature=best_f.astype(jnp.int32),
        threshold=thr,
        default_left=dleft,
        left_sum_g=f32(lg), left_sum_h=f32(lh), left_count=f32(lc),
        left_output=f32(lo), right_output=f32(ro))


class PerFeatureCatBest(NamedTuple):
    """Per-feature best CATEGORICAL split candidates."""
    gain: jnp.ndarray        # [F] net gain (min_gain_shift subtracted, penalized)
    cat_mask: jnp.ndarray    # [F, B] f32: 1.0 for bins going LEFT
    left_sum_g: jnp.ndarray  # [F]
    left_sum_h: jnp.ndarray  # [F]
    left_count: jnp.ndarray  # [F]
    left_output: jnp.ndarray   # [F] (computed with the categorical l2)
    right_output: jnp.ndarray  # [F]


def _gain_given_outputs(gl, hl, gr, hr, l1, l2, mds, min_c, max_c):
    """GetSplitGains (feature_histogram.hpp:432-447): gain of the two leaf
    outputs after monotone clipping."""
    lo = jnp.clip(leaf_output(gl, hl, l1, l2, mds), min_c, max_c)
    ro = jnp.clip(leaf_output(gr, hr, l1, l2, mds), min_c, max_c)
    g_l = -(2.0 * _threshold_l1(gl, l1) * lo + (hl + l2) * lo * lo)
    g_r = -(2.0 * _threshold_l1(gr, l1) * ro + (hr + l2) * ro * ro)
    return g_l + g_r, lo, ro


def per_feature_best_split_categorical(
        hist: jnp.ndarray,        # [F, B, 3]
        sum_g, sum_h, num_data,
        num_bin: jnp.ndarray,     # [F] i32
        missing_type: jnp.ndarray,  # [F] i32
        penalty: jnp.ndarray,     # [F] f32
        feature_mask: jnp.ndarray,  # [F]
        *, l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: float, min_sum_hessian: float,
        min_gain_to_split: float,
        cat_l2: float, cat_smooth: float, max_cat_threshold: int,
        max_cat_to_onehot: int, min_data_per_group: float,
        min_constraint=-1e30, max_constraint=1e30) -> PerFeatureCatBest:
    """Categorical best-split search (FindBestThresholdCategorical,
    reference feature_histogram.hpp:118-279).

    Two modes per feature, selected by num_bin <= max_cat_to_onehot:
    * one-hot: each category bin vs the rest, vectorized over bins;
    * sorted-CTR subset: bins with count >= cat_smooth sorted by
      sum_g/(sum_h + cat_smooth), prefix-scanned from both ends with the
      reference's min_data_per_group grouping and early-break rules —
      a lax.scan of <=B steps per direction, vmapped over features.

    Returns per-feature candidates whose cat_mask marks the bins (i.e.
    categories) routed LEFT; the grower turns the winning mask into
    Tree.split_categorical bitsets.
    """
    F, B, _ = hist.shape
    hg, hh, hc = hist[..., 0], hist[..., 1], hist[..., 2]
    bin_iota = jnp.arange(B, dtype=jnp.int32)[None, :]

    # used_bin = num_bin - 1 + (missing_type == None)  (hpp:130-131)
    is_full = (missing_type == MISSING_NONE)
    used_bin = num_bin - 1 + is_full.astype(jnp.int32)          # [F]

    gain_shift = leaf_split_gain(sum_g, sum_h, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split

    # ---- one-hot mode (hpp:137-169) ------------------------------------
    in_range = bin_iota < used_bin[:, None]
    oh_hl = hh + K_EPSILON
    oh_hr = sum_h - hh - K_EPSILON
    ok = (in_range
          & (hc >= min_data_in_leaf) & (hh >= min_sum_hessian)
          & ((num_data - hc) >= min_data_in_leaf)
          & (oh_hr >= min_sum_hessian))
    oh_gain, _, _ = _gain_given_outputs(
        sum_g - hg, oh_hr, hg, oh_hl, l1, l2, max_delta_step,
        min_constraint, max_constraint)
    oh_gain = jnp.where(ok & (oh_gain > min_gain_shift), oh_gain, K_MIN_SCORE)
    oh_best_t = jnp.argmax(oh_gain, axis=1)                     # [F]
    f_iota = jnp.arange(F)
    oh_best_gain = oh_gain[f_iota, oh_best_t]
    oh_mask = (bin_iota == oh_best_t[:, None]).astype(jnp.float32)
    oh_lg = hg[f_iota, oh_best_t]
    oh_lh = hh[f_iota, oh_best_t] + K_EPSILON
    oh_lc = hc[f_iota, oh_best_t]

    # ---- sorted-CTR subset mode (hpp:170-243) --------------------------
    l2c = l2 + cat_l2
    valid = in_range & (hc >= cat_smooth)                       # [F, B]
    ctr = hg / (hh + cat_smooth)
    sort_key = jnp.where(valid, ctr, jnp.inf)
    order = jnp.argsort(sort_key, axis=1).astype(jnp.int32)     # [F, B]
    used_cnt = jnp.sum(valid, axis=1).astype(jnp.int32)         # [F]
    max_cat = jnp.minimum(max_cat_threshold, (used_cnt + 1) // 2)

    def scan_dir(order_f, used_f, limit_f, hg_f, hh_f, hc_f, ascending):
        def body(carry, i):
            slg, slh, slc, grp, dead, bg, bi, blg, blh, blc = carry
            pos = jnp.where(ascending, i, used_f - 1 - i)
            t = order_f[jnp.clip(pos, 0, B - 1)]
            active = (i < limit_f) & (~dead)
            slg = slg + jnp.where(active, hg_f[t], 0.0)
            slh = slh + jnp.where(active, hh_f[t], 0.0)
            slc = slc + jnp.where(active, hc_f[t], 0.0)
            grp = grp + jnp.where(active, hc_f[t], 0.0)
            cont1 = (slc < min_data_in_leaf) | (slh < min_sum_hessian)
            rc = num_data - slc
            srh = sum_h - slh
            brk = ((rc < min_data_in_leaf) | (rc < min_data_per_group)
                   | (srh < min_sum_hessian))
            cont2 = grp < min_data_per_group
            evaluate = active & (~cont1) & (~brk) & (~cont2)
            gain, _, _ = _gain_given_outputs(
                slg, slh, sum_g - slg, srh, l1, l2c, max_delta_step,
                min_constraint, max_constraint)
            good = evaluate & (gain > min_gain_shift) & (gain > bg)
            grp = jnp.where(evaluate, 0.0, grp)
            bg = jnp.where(good, gain, bg)
            bi = jnp.where(good, i, bi)
            blg = jnp.where(good, slg, blg)
            blh = jnp.where(good, slh, blh)
            blc = jnp.where(good, slc, blc)
            dead = dead | (active & (~cont1) & brk)
            return (slg, slh, slc, grp, dead, bg, bi, blg, blh, blc), None

        init = (jnp.float32(0.0), jnp.float32(K_EPSILON), jnp.float32(0.0),
                jnp.float32(0.0), jnp.asarray(False),
                jnp.float32(K_MIN_SCORE), jnp.int32(-1),
                jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0))
        carry, _ = jax.lax.scan(body, init, jnp.arange(B, dtype=jnp.int32))
        _, _, _, _, _, bg, bi, blg, blh, blc = carry
        return bg, bi, blg, blh, blc

    def per_feature(order_f, used_f, limit_f, hg_f, hh_f, hc_f):
        g1, i1, lg1, lh1, lc1 = scan_dir(order_f, used_f, limit_f,
                                         hg_f, hh_f, hc_f, True)
        g2, i2, lg2, lh2, lc2 = scan_dir(order_f, used_f, limit_f,
                                         hg_f, hh_f, hc_f, False)
        use2 = g2 > g1                    # dir=+1 scanned first keeps ties
        bg = jnp.where(use2, g2, g1)
        bi = jnp.where(use2, i2, i1)
        lg = jnp.where(use2, lg2, lg1)
        lh = jnp.where(use2, lh2, lh1)
        lc = jnp.where(use2, lc2, lc1)
        # bins routed left: sorted positions 0..bi (asc) / last bi+1 (desc)
        inv = jnp.zeros(B, jnp.int32).at[order_f].set(
            jnp.arange(B, dtype=jnp.int32))
        asc_mask = inv <= bi
        desc_mask = (inv >= used_f - 1 - bi) & (inv < used_f)
        mask = jnp.where(use2, desc_mask, asc_mask) & (bi >= 0)
        return bg, mask.astype(jnp.float32), lg, lh, lc

    so_gain, so_mask, so_lg, so_lh, so_lc = jax.vmap(per_feature)(
        order, used_cnt, max_cat, hg, hh, hc)

    # ---- merge modes per feature (hpp:136 use_onehot) ------------------
    use_oh = num_bin <= max_cat_to_onehot
    gain = jnp.where(use_oh, oh_best_gain, so_gain)
    mask = jnp.where(use_oh[:, None], oh_mask, so_mask)
    lg = jnp.where(use_oh, oh_lg, so_lg)
    lh = jnp.where(use_oh, oh_lh, so_lh)
    lc = jnp.where(use_oh, oh_lc, so_lc)
    l2_out = jnp.where(use_oh, l2, l2c)

    # leaf outputs with the mode's l2 (hpp:244-258)
    lo = jnp.clip(-_threshold_l1(lg, l1) / (lh + l2_out),
                  min_constraint, max_constraint)
    ro = jnp.clip(-_threshold_l1(sum_g - lg, l1) / (sum_h - lh + l2_out),
                  min_constraint, max_constraint)
    if max_delta_step > 0.0:
        lo = jnp.clip(lo, -max_delta_step, max_delta_step)
        ro = jnp.clip(ro, -max_delta_step, max_delta_step)

    gain = jnp.where(feature_mask > 0, gain, K_MIN_SCORE)
    out_gain = jnp.where(gain > K_MIN_SCORE / 2,
                         (gain - min_gain_shift) * penalty,
                         K_MIN_SCORE)
    return PerFeatureCatBest(gain=out_gain, cat_mask=mask,
                             left_sum_g=lg, left_sum_h=lh, left_count=lc,
                             left_output=lo, right_output=ro)


def find_best_split_all_features(
        hist: jnp.ndarray, sum_g, sum_h, num_data,
        num_bin, missing_type, default_bin, monotone, penalty, feature_mask,
        *, l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: float, min_sum_hessian: float,
        min_gain_to_split: float,
        min_constraint=-1e30, max_constraint=1e30) -> SplitResult:
    """Best split for one leaf across all features: per-feature candidates +
    first-max-wins argmax (ArrayArgs::ArgMax semantics)."""
    pf = per_feature_best_split(
        hist, sum_g, sum_h, num_data, num_bin, missing_type, default_bin,
        monotone, penalty, feature_mask,
        l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split,
        min_constraint=min_constraint, max_constraint=max_constraint)
    best_f = jnp.argmax(pf.gain, axis=0).astype(jnp.int32)
    return finalize_split(pf, best_f, sum_g, sum_h,
                          l1=l1, l2=l2, max_delta_step=max_delta_step,
                          min_constraint=min_constraint,
                          max_constraint=max_constraint)
