"""Device-parallel dataset binning: chunked jitted value->bin kernel.

The ingest analog of ops/predict.py: raw rows are quantized into bin ids
on the accelerator instead of column-by-column host numpy.  The kernel
is a batched searchsorted — for every (row, feature) it counts how many
of the feature's bin upper bounds are strictly below the value, which is
exactly `np.searchsorted(ub[:hi], v, side="left")`
(`BinMapper.values_to_bins`, the reference `BinMapper::ValueToBin`,
bin.h:472-508).

Bitwise parity on EVERY backend is non-negotiable (the training bins
feed split decisions), but accelerators run f32 while the host bounds
are f64.  The kernel therefore never compares floats: each f64 is mapped
on the host to its MONOTONE int64 key (sign-flipped IEEE bit pattern —
total order identical to the f64 order, with -0.0 == +0.0 keying to the
same value), shipped as two planes (hi int32, lo uint32), and compared
lexicographically on device.  Integer compares are exact everywhere, so
the device bins match `values_to_bins` bit-for-bit even in x32 mode.

NaN rides a reserved key (INT64_MAX, unreachable by finite/inf keys) and
is routed per the feature's MissingType: last bin when NaN-missing, the
0.0 bin (`default_bin`) otherwise.  Categorical features look up a
flattened per-feature category->bin table; negative / unseen / too-large
categories fall to the last bin like `value_to_bin`.

`DeviceBinner` streams `[chunk, F]` blocks: the host computes chunk
i+1's key planes (cheap vectorized bit twiddling) while the device bins
chunk i — transfer and compute overlap through jax's async dispatch —
and the full `[n, F]` matrix is assembled device-side, never
materialized on the host unless a host consumer asks (see
`TrainingData.bins`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..io.bin_mapper import BinMapper, BinType, MissingType, sort_keys
from ..utils import membudget
from ..utils.compile_ledger import ledger_jit

_NAN_KEY = np.int64(np.iinfo(np.int64).max)
_NAN_KEY_HI = np.int32(_NAN_KEY >> 32)
_NAN_KEY_LO = np.uint32(_NAN_KEY & 0xFFFFFFFF)
_NAN_CAT = -2  # host-side category sentinel for NaN values
# per-feature / total category-LUT capacity: features with larger raw
# category ids fall back to host binning (pandas codes and typical int
# categories sit far below this)
_CAT_LUT_MAX = 1 << 16
_CAT_LUT_TOTAL_MAX = 1 << 22


def split_keys(keys: np.ndarray):
    """int64 keys -> (hi int32, lo uint32) planes for x32-safe compare."""
    return ((keys >> 32).astype(np.int32),
            (keys & np.int64(0xFFFFFFFF)).astype(np.uint32))


@ledger_jit(site="binning.chunk",
            static_argnames=("has_cat", "out_dtype"))
def _bin_chunk_kernel(vhi, vlo, cv, t: Dict[str, jnp.ndarray],
                      has_cat: bool, out_dtype: str):
    """[chunk, F] key planes (+ category codes) -> [chunk, F] bin ids.

    t: bhi/blo [F, B] bound-key planes (padded with the NaN key so
    padding never counts), num_bin/default_bin/nan_is_last [F], and —
    when has_cat — is_cat/cat_offset/cat_width/nan_cat_bin [F] plus the
    flattened category LUT.
    """
    # lexicographic (hi, lo) compare == int64 key compare == f64 '<'
    lt = (t["bhi"][None, :, :] < vhi[:, :, None]) | (
        (t["bhi"][None, :, :] == vhi[:, :, None])
        & (t["blo"][None, :, :] < vlo[:, :, None]))
    num = jnp.sum(lt, axis=-1, dtype=jnp.int32)
    is_nan = (vhi == _NAN_KEY_HI) & (vlo == _NAN_KEY_LO)
    last = t["num_bin"][None, :] - 1
    nan_bin = jnp.where(t["nan_is_last"][None, :] > 0, last,
                        t["default_bin"][None, :])
    out = jnp.where(is_nan, nan_bin, num)
    if has_cat:
        width = t["cat_width"][None, :]
        idx = t["cat_offset"][None, :] + jnp.clip(cv, 0, width - 1)
        catbin = jnp.take(t["cat_lut"], idx, axis=0)
        unseen = (cv < 0) | (cv >= width)
        catbin = jnp.where(unseen, last, catbin)
        catbin = jnp.where(cv == _NAN_CAT, t["nan_cat_bin"][None, :], catbin)
        out = jnp.where(t["is_cat"][None, :] > 0, catbin, out)
    return out.astype(out_dtype)


class DeviceBinner:
    """Streams raw row chunks through the device bin kernel.

    Build once per mapper set (`DeviceBinner.build` returns None when a
    categorical feature's ids exceed the LUT capacity — callers fall
    back to host binning), then `bin_matrix(X)` yields the device
    `[n, F]` binned matrix in the dataset's storage dtype.
    """

    def __init__(self, tables: Dict[str, np.ndarray], used_cols: List[int],
                 has_cat: bool, out_dtype: np.dtype, chunk_rows: int):
        self.used_cols = used_cols
        self.has_cat = has_cat
        self.out_dtype = np.dtype(out_dtype)
        self.chunk_rows = max(int(chunk_rows), 256)
        self._cat_widths = tables["cat_width"].copy() if has_cat else None
        self._is_cat = tables["is_cat"].copy() if has_cat else None
        self._dev_tables = {k: jnp.asarray(v) for k, v in tables.items()}

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, mappers: Sequence[BinMapper], used_cols: Sequence[int],
              out_dtype, chunk_rows: int) -> Optional["DeviceBinner"]:
        used = [int(c) for c in used_cols]
        F = len(used)
        if F == 0:
            return None
        ms = [mappers[c] for c in used]
        # numerical bound tables: ub[:hi] keys, NaN-key padded
        his = [(m.num_bin - 1 - (1 if m.missing_type == MissingType.NAN
                                 else 0))
               if m.bin_type == BinType.NUMERICAL else 0 for m in ms]
        B = max(max(his), 1)
        bkeys = np.full((F, B), _NAN_KEY, np.int64)
        for j, (m, hi) in enumerate(zip(ms, his)):
            if hi > 0:
                bkeys[j, :hi] = sort_keys(m.bin_upper_bound[:hi])
        bhi, blo = split_keys(bkeys)
        num_bin = np.array([m.num_bin for m in ms], np.int32)
        default_bin = np.array([m.default_bin for m in ms], np.int32)
        nan_is_last = np.array(
            [int(m.missing_type == MissingType.NAN) for m in ms], np.int32)
        tables = {"bhi": bhi, "blo": blo, "num_bin": num_bin,
                  "default_bin": default_bin, "nan_is_last": nan_is_last}

        has_cat = any(m.bin_type == BinType.CATEGORICAL for m in ms)
        if has_cat:
            widths = np.zeros(F, np.int64)
            for j, m in enumerate(ms):
                if m.bin_type != BinType.CATEGORICAL:
                    continue
                real = [c for c in m.categorical_2_bin if c >= 0]
                w = (max(real) + 1) if real else 1
                if w > _CAT_LUT_MAX:
                    return None  # ids too large for a dense LUT
                widths[j] = w
            if widths.sum() > _CAT_LUT_TOTAL_MAX:
                return None
            offsets = np.concatenate([[0], np.cumsum(widths)[:-1]])
            lut = np.zeros(max(int(widths.sum()), 1), np.int32)
            nan_cat_bin = np.zeros(F, np.int32)
            for j, m in enumerate(ms):
                if m.bin_type != BinType.CATEGORICAL:
                    continue
                lo, w = int(offsets[j]), int(widths[j])
                lut[lo:lo + w] = m.num_bin - 1  # unmapped -> last bin
                for c, b in m.categorical_2_bin.items():
                    if 0 <= c < w:
                        lut[lo + c] = b
                # NaN: dedicated last bin when NaN-missing, else the
                # category-0 route (values_to_bins nan_cat semantics)
                nan_cat_bin[j] = (m.num_bin - 1
                                  if m.missing_type == MissingType.NAN
                                  else int(lut[lo]) if w > 0
                                  else m.num_bin - 1)
            tables.update({
                "is_cat": np.array(
                    [int(m.bin_type == BinType.CATEGORICAL) for m in ms],
                    np.int32),
                "cat_offset": offsets.astype(np.int32),
                "cat_width": widths.astype(np.int32),
                "cat_lut": lut,
                "nan_cat_bin": nan_cat_bin})
        return cls(tables, used, has_cat, out_dtype, chunk_rows)

    # ------------------------------------------------------------------
    def _prep_chunk(self, block: np.ndarray):
        """Raw f64 [rows, F] -> host key planes (+ category codes)."""
        vals = np.ascontiguousarray(block, dtype=np.float64)
        vhi, vlo = split_keys(sort_keys(vals))
        cv = None
        if self.has_cat:
            # int(v) truncation toward zero; NaN -> sentinel; clip keeps
            # the int32 cast defined for huge/inf values (they are
            # unseen either way)
            isnan = np.isnan(vals)
            t = np.clip(np.trunc(np.where(isnan, -1.0, vals)), -1.0,
                        float(_CAT_LUT_MAX)).astype(np.int32)
            cv = np.where(isnan, np.int32(_NAN_CAT), t)
        return vhi, vlo, cv

    def bin_chunk(self, block: np.ndarray) -> jnp.ndarray:
        """Bin one [rows, F] raw block (guarded ingest-upload site).

        A classified device OOM halves `chunk_rows` and re-bins the
        block in smaller launches — bins are bit-identical at ANY chunk
        size (the PR-3 chunk-boundary contract), so the recovery is
        invisible to training; at the kernel's floor the structured
        DeviceOutOfMemory propagates."""
        rows = block.shape[0]
        if rows == 0:
            return jnp.zeros((0, block.shape[1]), self.out_dtype)
        parts = []
        lo = 0
        while lo < rows:
            sub = block[lo:lo + self.chunk_rows]
            try:
                with membudget.oom_guard("ingest_chunk",
                                         rows=int(sub.shape[0])):
                    parts.append(self._bin_chunk_once(sub))
                lo += sub.shape[0]
            except membudget.DeviceOutOfMemory:
                if not self._shrink_chunk():
                    raise
        if len(parts) == 1:
            return parts[0]
        # the reassembled full block is the single largest allocation
        # here, and a multi-part reassembly only happens right after a
        # shrink — i.e. on a nearly-full device.  Shrinking further
        # cannot help (the output is full-block regardless), so a
        # failure classifies and propagates structured for the
        # mid-train ladder above instead of escaping raw
        with membudget.oom_guard("ingest_chunk", rows=int(rows),
                                 stage="reassemble"):
            return jnp.concatenate(parts, axis=0)

    def _shrink_chunk(self) -> bool:
        """Halve this binner's LOCAL chunk after a classified OOM
        (floor 256, the kernel minimum — below the ladder's 4096 param
        floor because the in-flight stream must finish even on a very
        tight device); logged + counted like every ladder step.  The
        recorded field names the binner-local width, NOT the
        tpu_ingest_chunk_rows param — the config is untouched here
        (the mid-train ladder owns param changes)."""
        from ..utils.log import Log

        if self.chunk_rows <= 256:
            return False
        new = max(self.chunk_rows // 2, 256)
        membudget.note_ladder_step("ingest_chunk", "shrink_chunk_rows",
                                   {"binner_chunk_rows": new})
        Log.warning(f"device OOM in chunked ingest: shrinking the "
                    f"binning chunk {self.chunk_rows} -> {new} and "
                    "re-binning (bins are chunk-invariant)")
        self.chunk_rows = new
        return True

    def _bin_chunk_once(self, block: np.ndarray) -> jnp.ndarray:
        """One [rows, F] kernel launch, padded to the chunk shape so
        every launch reuses ONE compiled program, slicing the pad off
        on device."""
        rows = block.shape[0]
        pad = self.chunk_rows - rows if rows < self.chunk_rows else 0
        if pad:
            block = np.concatenate(
                [block, np.zeros((pad, block.shape[1]), block.dtype)])
        vhi, vlo, cv = self._prep_chunk(block)
        dummy = np.zeros((0,), np.int32)
        out = _bin_chunk_kernel(
            jnp.asarray(vhi), jnp.asarray(vlo),
            jnp.asarray(cv) if cv is not None else jnp.asarray(dummy),
            self._dev_tables, self.has_cat, str(self.out_dtype))
        return out[:rows] if pad else out

    def bin_matrix(self, X: np.ndarray) -> jnp.ndarray:
        """Stream X's used columns through the kernel chunk by chunk.

        Dispatch is async: while the device bins chunk i, the host is
        already building chunk i+1's key planes, overlapping transfer
        with compute (the "Out-of-Core GPU Gradient Boosting" chunked
        ingest pattern).
        """
        return self.bin_stream([X])

    def bin_stream(self, blocks) -> jnp.ndarray:
        """Bin an iterable of raw row blocks, re-chunking across block
        boundaries so only the FINAL kernel launch pads — a file
        reader's chunk size rarely aligns with `chunk_rows`, and padding
        every reader chunk's tail would waste a steady fraction of the
        kernel work on long streams."""
        parts = []
        pend: list = []
        pend_rows = 0
        for block in blocks:
            b = np.asarray(block, dtype=np.float64)[:, self.used_cols]
            pend.append(b)
            pend_rows += b.shape[0]
            while pend_rows >= self.chunk_rows:
                buf = pend[0] if len(pend) == 1 else np.concatenate(pend)
                # snapshot the slice width BEFORE the call: an OOM
                # recovery inside bin_chunk SHRINKS self.chunk_rows,
                # and re-reading it for the remainder slice would keep
                # rows the call already binned (silent duplication)
                c = self.chunk_rows
                parts.append(self.bin_chunk(buf[:c]))
                pend = [buf[c:]]
                pend_rows = pend[0].shape[0]
        if pend_rows > 0 or not parts:
            if not pend:
                return jnp.zeros((0, len(self.used_cols)), self.out_dtype)
            buf = pend[0] if len(pend) == 1 else np.concatenate(pend)
            parts.append(self.bin_chunk(buf))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
