"""Device-resident forest prediction: jitted bin-space traversal.

The training hot loop is asynchronous and device-bound, but every
materialized tree used to score validation data through a host-numpy
walk (`gbdt._predict_binned`) — one synchronous O(depth) full-data pass
per tree per valid set, stalling the pipeline whenever `valid_sets` or
early stopping is on.  This module keeps prediction on the accelerator:

* `pack_trees` flattens host `Tree` models into dense per-tree node
  tables (split feature / threshold-in-bin / decision type / children,
  leaf values, flattened categorical bitset words),
* `forest_leaf_values` traverses all rows x all trees with one
  `lax.fori_loop` over depth — the bin-space analog of
  `NumericalDecisionInner` / `CategoricalDecisionInner` (reference
  tree.h:252-318), including NaN/zero missing routing,
* `forest_class_scores` reduces the [T, n] leaf values into [k, n]
  per-class raw scores (tree i belongs to class i % k),
* `PackedForest` appends newly materialized trees into amortized host
  buffers so the full-forest table is never re-packed per iteration.

Traversal is EXACT per tree: leaf values are gathered as f32 and match
the host walker leaf-for-leaf (`gbdt._predict_binned` stays as the
parity oracle and the tiny-data CPU fallback).  Compile keys are kept
small by bucketing the depth trip count to the next power of two and by
the callers' fixed row chunking.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compile_ledger import ledger_jit

# table keys that are [T, num_internal_nodes] int32
_NODE_KEYS = ("split_feature", "threshold", "decision_type",
              "left_child", "right_child", "cat_start", "cat_width")

# serving-table storage precisions (serving_table_precision):
#   f32   — the training pack verbatim (byte-identical path)
#   bf16  — node tables int16 where ranges fit, leaf values bfloat16
#   int16 — node tables AND leaf values int16; leaves dequantize
#           per-tree through an f32 `leaf_scale` column
SERVING_PRECISIONS = ("f32", "bf16", "int16")


def check_serving_precision(precision: str) -> str:
    if precision not in SERVING_PRECISIONS:
        raise ValueError(
            f"serving_table_precision={precision!r}; expected one of "
            f"{SERVING_PRECISIONS}")
    return precision


def quantize_tables(tables: Dict[str, np.ndarray],
                    precision: str) -> Dict[str, np.ndarray]:
    """Serving-precision copy of a host `pack_trees` table dict.

    Bin-space thresholds, feature ids and child codes are small ints, so
    every node table narrows to int16 whenever its value range fits —
    the traversal compares/steps the SAME integers, keeping decision-path
    parity exact (a table whose range overflows int16, e.g. a >32767-bin
    threshold column or a huge `cat_start` pool offset, stays int32).
    Leaf values store bfloat16 (`bf16`) or int16 with a per-tree f32
    dequantization scale (`int16`, scale = max|leaf|/32767); `f32`
    returns a shallow copy so the default path stays byte-identical.
    The `cat_words` bitset pool is shared uint32 either way.
    """
    p = check_serving_precision(precision)
    out = dict(tables)
    if p == "f32":
        return out
    for key in _NODE_KEYS + ("init_node",):
        v = tables[key]
        if v.size == 0 or (int(v.min()) >= -32768 and int(v.max()) <= 32767):
            out[key] = v.astype(np.int16)
    lv = np.asarray(tables["leaf_value"], np.float32)
    if p == "bf16":
        from ml_dtypes import bfloat16

        out["leaf_value"] = lv.astype(bfloat16)
    else:
        absmax = np.abs(lv).max(axis=1) if lv.size else np.zeros(
            lv.shape[0], np.float32)
        scale = np.where(absmax > 0, absmax / 32767.0, 1.0).astype(np.float32)
        out["leaf_value"] = np.clip(
            np.rint(lv / scale[:, None]), -32767, 32767).astype(np.int16)
        out["leaf_scale"] = scale
    return out

# ---- launch-shape bucket policy -------------------------------------------
# The ONE quantization rule shared by training-time score replay, the
# chunked predict path, serving warmup enumeration, and bench — so every
# layer agrees on which launch shapes can exist and a warmup can
# pre-compile exactly the set a request can trigger.
#
#   "wide" (tpu_bucket_policy default): rows pad to {4096, 16384, chunk}
#     (x4 steps from a 4096 floor), depth trip counts to powers of two
#     floored at 8.  Strictly fewer programs than "fine" — the compile
#     bill for a full predict-size sweep drops from 7 programs to 3 at
#     the default 65536 chunk — at the cost of up to 4x padded rows on
#     small batches (predict work is row-linear, compile is per-shape).
#   "fine": the pre-round-6 behavior — power-of-two rows from 1024,
#     exact power-of-two depth buckets.  Pick it when small-batch
#     predict latency matters more than cold-start compiles.
BUCKET_POLICIES = ("fine", "wide")
_ROW_FLOOR = {"fine": 1024, "wide": 4096}
_ROW_STEP = {"fine": 2, "wide": 4}
_DEPTH_FLOOR = {"fine": 1, "wide": 8}


def _check_policy(policy: str) -> str:
    if policy not in BUCKET_POLICIES:
        raise ValueError(f"tpu_bucket_policy={policy!r}; expected one of "
                         f"{BUCKET_POLICIES}")
    return policy


def _depth_bucket(depth: int, policy: str = "wide") -> int:
    """Round the fori_loop trip count up to a power of two (floored at 8
    under the wide policy) so growing trees reuse a handful of compiled
    programs instead of one per depth."""
    _check_policy(policy)
    d = max(int(depth), _DEPTH_FLOOR[policy])
    return 1 << (d - 1).bit_length()


def row_bucket(rows: int, chunk: int, min_rows: int = 0,
               policy: str = "wide") -> int:
    """Padded row count for one device-predict launch.

    The row-axis analog of `_depth_bucket`: launches are padded up to
    the next bucket of the policy's geometric ladder (floored at the
    policy's minimum, capped at the caller's chunk size) so predicts of
    arbitrary batch sizes reuse a handful of compiled programs instead
    of one per distinct n.  Every `forest_leaf_values` /
    `forest_class_scores` caller that wants a bounded compile cache must
    pad through this ONE formula — the serving warmup enumerates its
    sweep from it."""
    _check_policy(policy)
    rows = max(int(rows), 1)
    if rows >= chunk:
        return chunk
    floor = max(int(min_rows), _ROW_FLOOR[policy])
    step = _ROW_STEP[policy]
    b = floor
    while b < rows:
        b *= step
    return min(chunk, b)


def predict_row_buckets(max_rows: int, chunk: int, min_rows: int = 0,
                        policy: str = "wide") -> List[int]:
    """Ascending distinct launch shapes `row_bucket` can produce for
    predicts of 1..max_rows rows — the exact set a serving warmup must
    pre-compile so no request size triggers a cold jit."""
    _check_policy(policy)
    out: List[int] = []
    b = max(int(min_rows), _ROW_FLOOR[policy])
    while True:
        bucket = min(b, chunk)
        if bucket not in out:
            out.append(bucket)
        if b >= max_rows or bucket >= chunk:
            break
        b *= _ROW_STEP[policy]
    return out


def pack_trees(trees: Sequence, leaf_width: int = 0,
               pad_cat_words: bool = False
               ) -> Tuple[Dict[str, np.ndarray], int]:
    """Flatten host Tree models into dense [T, ...] node tables.

    Returns (tables, max_depth).  Leaves stay encoded as `~leaf_idx` in
    the child columns; a constant tree starts at node `~0` so the
    traversal loop is a no-op for it.  Categorical nodes carry a
    (start, width) window into the shared `cat_words` bitset pool; word
    0 of the pool is a permanent zero so non-categorical nodes can point
    at it harmlessly.

    `leaf_width` pins the leaf axis (callers on a jit hot path pass the
    config num_leaves so every tree packs to ONE shape);
    `pad_cat_words` pads the bitset pool to the next power of two for
    the same reason — zero words are inert, the per-node windows ignore
    them.
    """
    T = len(trees)
    L = max([t.num_leaves for t in trees] + [max(int(leaf_width), 1)])
    ni_w = max(L - 1, 1)
    sf = np.zeros((T, ni_w), np.int32)
    thr = np.zeros((T, ni_w), np.int32)
    dt = np.zeros((T, ni_w), np.int32)
    lc = np.zeros((T, ni_w), np.int32)
    rc = np.zeros((T, ni_w), np.int32)
    cs = np.zeros((T, ni_w), np.int32)
    cw = np.zeros((T, ni_w), np.int32)
    lv = np.zeros((T, L), np.float32)
    init = np.zeros(T, np.int32)
    words: List[np.ndarray] = [np.zeros(1, np.uint32)]
    woff = 1
    depth = 1
    for ti, t in enumerate(trees):
        nl = int(t.num_leaves)
        lv[ti, :nl] = t.leaf_value[:nl]
        ni = nl - 1
        if ni <= 0:
            init[ti] = -1  # ~0: already at leaf 0
            continue
        sf[ti, :ni] = t.split_feature_inner[:ni]
        thr[ti, :ni] = t.threshold_in_bin[:ni]
        dt[ti, :ni] = t.decision_type[:ni].astype(np.int32) & 0xF
        lc[ti, :ni] = t.left_child[:ni]
        rc[ti, :ni] = t.right_child[:ni]
        depth = max(depth, int(t.max_depth()))
        if t.num_cat > 0:
            cb = np.asarray(t.cat_boundaries_inner, np.int64)
            tw = np.asarray(t.cat_threshold_inner, np.uint32)
            is_cat = (dt[ti, :ni] & 1) != 0
            ci = np.clip(thr[ti, :ni], 0, max(len(cb) - 2, 0))
            cs[ti, :ni] = np.where(is_cat, woff + cb[ci], 0)
            cw[ti, :ni] = np.where(is_cat, cb[ci + 1] - cb[ci], 0)
            if len(tw):
                words.append(tw)
                woff += len(tw)
    pool = np.concatenate(words)
    if pad_cat_words:
        target = 1 << (len(pool) - 1).bit_length()
        if len(pool) < target:
            pool = np.concatenate(
                [pool, np.zeros(target - len(pool), np.uint32)])
    tables = {"split_feature": sf, "threshold": thr, "decision_type": dt,
              "left_child": lc, "right_child": rc, "cat_start": cs,
              "cat_width": cw, "leaf_value": lv, "init_node": init,
              "cat_words": pool}
    return tables, depth


def device_tables(tables: Dict[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
    """Host tables -> device arrays (one transfer per array)."""
    return {k: jnp.asarray(v) for k, v in tables.items()}


def _leaf_values_impl(tables, bins, num_bin, default_bin, missing_type,
                      depth: int, has_cat: bool):
    """[T, n] f32 leaf values: every tree walked over every row.

    bins is [n, F] int32 (the TrainingData.device_bins layout); the
    features-major transpose lives INSIDE the jit so XLA fuses it into
    the per-node feature gather instead of materializing a copy per
    call.  The loop body mirrors gbdt._predict_binned exactly: missing
    routing first, numerical compare, categorical bitset override, then
    the child step — inactive lanes (node < 0, already at a leaf) keep
    their state.
    """
    bins_t = bins.T                                        # [F, n]
    T = tables["leaf_value"].shape[0]
    # int32 traversal state regardless of table storage width: quantized
    # serving tables (int16 node columns) promote through the compares
    # and child steps, so the walked path is the same exact integers
    node0 = jnp.broadcast_to(tables["init_node"].astype(jnp.int32)[:, None],
                             (T, bins_t.shape[1]))

    def body(_, node):
        nid = jnp.maximum(node, 0)
        f = jnp.take_along_axis(tables["split_feature"], nid, axis=1)
        fbin = jnp.take_along_axis(bins_t, f, axis=0)          # [T, n]
        mt = jnp.take(missing_type, f)
        is_missing = jnp.where(
            mt == 2, fbin == jnp.take(num_bin, f) - 1,
            (mt == 1) & (fbin == jnp.take(default_bin, f)))
        dt = jnp.take_along_axis(tables["decision_type"], nid, axis=1)
        thr = jnp.take_along_axis(tables["threshold"], nid, axis=1)
        go_left = jnp.where(is_missing, (dt & 2) != 0, fbin <= thr)
        if has_cat:
            cs = jnp.take_along_axis(tables["cat_start"], nid, axis=1)
            width = jnp.take_along_axis(tables["cat_width"], nid, axis=1)
            word_idx = fbin // 32
            word = jnp.take(
                tables["cat_words"],
                jnp.clip(cs + word_idx, 0, tables["cat_words"].shape[0] - 1))
            bit = (word >> (fbin % 32).astype(jnp.uint32)) & jnp.uint32(1)
            go_cat = (word_idx < width) & (bit == jnp.uint32(1))
            go_left = jnp.where((dt & 1) != 0, go_cat, go_left)
        nxt = jnp.where(go_left,
                        jnp.take_along_axis(tables["left_child"], nid, axis=1),
                        jnp.take_along_axis(tables["right_child"], nid,
                                            axis=1)).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    node = lax.fori_loop(0, depth, body, node0)
    leaf = jnp.where(node < 0, ~node, 0)
    vals = jnp.take_along_axis(tables["leaf_value"], leaf, axis=1)
    if vals.dtype != jnp.float32:
        # quantized serving storage: accumulate in f32 regardless
        vals = vals.astype(jnp.float32)
    if "leaf_scale" in tables:
        vals = vals * tables["leaf_scale"][:, None]
    return vals


# the standalone jitted entry; `_class_scores_kernel` inlines the impl
# directly so the ledger never counts an under-trace call as a program
_leaf_values_kernel = ledger_jit(
    _leaf_values_impl, site="predict.leaf_values",
    static_argnames=("depth", "has_cat"))


@ledger_jit(site="predict.class_scores",
            static_argnames=("depth", "has_cat", "k"))
def _class_scores_kernel(tables, bins, num_bin, default_bin, missing_type,
                         scale, depth: int, has_cat: bool, k: int):
    """[k, n] f32 per-class raw scores: tree i adds to class i % k."""
    vals = _leaf_values_impl(tables, bins, num_bin, default_bin,
                             missing_type, depth, has_cat) * scale
    T = vals.shape[0]
    if k == 1:
        return vals.sum(axis=0, keepdims=True)
    cid = jnp.arange(T, dtype=jnp.int32) % k
    return jax.ops.segment_sum(vals, cid, num_segments=k)


def feature_meta_dev(meta) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Device (num_bin, default_bin, missing_type) per-feature triple."""
    return (jnp.asarray(np.asarray(meta["num_bin"], np.int32)),
            jnp.asarray(np.asarray(meta["default_bin"], np.int32)),
            jnp.asarray(np.asarray(meta["missing_type"], np.int32)))


def forest_leaf_values(tables_dev: Dict[str, jnp.ndarray], bins_dev,
                       meta_dev, depth: int,
                       policy: str = "wide") -> jnp.ndarray:
    """[T, n] device leaf values.  `bins_dev` is [n, F] int32 (the
    TrainingData.device_bins layout); `meta_dev` the
    (num_bin, default_bin, missing_type) triple from `feature_meta_dev`."""
    nb, db, mt = meta_dev
    has_cat = int(tables_dev["cat_words"].shape[0]) > 1
    return _leaf_values_kernel(tables_dev, bins_dev, nb, db, mt,
                               _depth_bucket(depth, policy), has_cat)


def forest_class_scores(tables_dev: Dict[str, jnp.ndarray], bins_dev,
                        meta_dev, k: int, depth: int,
                        scale: float = 1.0,
                        policy: str = "wide") -> jnp.ndarray:
    """[k, n] device per-class raw scores (tree i -> class i % k)."""
    nb, db, mt = meta_dev
    has_cat = int(tables_dev["cat_words"].shape[0]) > 1
    return _class_scores_kernel(tables_dev, bins_dev, nb, db, mt,
                                jnp.float32(scale),
                                _depth_bucket(depth, policy),
                                has_cat, int(k))


class PackedForest:
    """Appendable forest tables: amortized host buffers + device cache.

    `sync(models)` packs only the trees added since the last call into
    capacity-doubling host buffers (never the whole forest), then
    refreshes the device copy iff the tree count changed.  Growing leaf
    width (a wider tree than any seen) forces one full repack — rare,
    since `num_leaves` is fixed per config.  In-place leaf mutation
    (DART shrinkage, refit, set_leaf_value) must drop the instance —
    same invalidation contract as the native ForestTables cache.
    """

    def __init__(self):
        self._count = 0
        self._cap = 0
        self._host: Optional[Dict[str, np.ndarray]] = None
        self._depth = 1
        self._dev: Optional[Dict[str, jnp.ndarray]] = None
        self._dev_count = -1

    @property
    def depth(self) -> int:
        return self._depth

    def sync(self, models: Sequence) -> int:
        """Append models[self._count:]; returns the packed tree count."""
        new = models[self._count:]
        if not new:
            return self._count
        width = max([t.num_leaves for t in new] + [1])
        if self._host is None or width > self._host["leaf_value"].shape[1]:
            # first pack, or a wider tree arrived: rebuild at full width
            tables, depth = pack_trees(list(models))
            self._host = tables
            self._depth = max(self._depth, depth)
            self._cap = len(models)
            self._count = len(models)
        else:
            tables, depth = pack_trees(
                list(new), leaf_width=self._host["leaf_value"].shape[1])
            self._depth = max(self._depth, depth)
            need = self._count + len(new)
            if need > self._cap:
                self._cap = max(need, 2 * self._cap)
                for key in _NODE_KEYS + ("leaf_value", "init_node"):
                    old = self._host[key]
                    grown = np.zeros((self._cap,) + old.shape[1:], old.dtype)
                    grown[:self._count] = old[:self._count]
                    self._host[key] = grown
            base = int(self._host["cat_words"].shape[0])
            for key in _NODE_KEYS + ("leaf_value", "init_node"):
                self._host[key][self._count:need] = tables[key]
            # rebase the new trees' bitset windows past the existing pool
            # (pack_trees numbered them from its own word 1)
            if tables["cat_words"].shape[0] > 1:
                cs = self._host["cat_start"][self._count:need]
                cs[cs > 0] += base - 1
                self._host["cat_words"] = np.concatenate(
                    [self._host["cat_words"], tables["cat_words"][1:]])
            self._count = need
        return self._count

    def host(self, num_trees: int = -1) -> Dict[str, np.ndarray]:
        """HOST tables for the first `num_trees` trees (-1 = all) — the
        same slicing contract as `device`, zero uploads: the fleet
        registry quantizes from these before placing per-device
        replicas (ISSUE 19)."""
        host = {k: (v[:self._count] if k != "cat_words" else v)
                for k, v in self._host.items()}
        if num_trees < 0 or num_trees >= self._count:
            return host
        return {k: (v[:num_trees] if k != "cat_words" else v)
                for k, v in host.items()}

    def device(self, num_trees: int = -1) -> Dict[str, jnp.ndarray]:
        """Device tables for the first `num_trees` trees (-1 = all)."""
        if self._dev_count != self._count:
            host = {k: (v[:self._count] if k != "cat_words" else v)
                    for k, v in self._host.items()}
            self._dev = device_tables(host)
            self._dev_count = self._count
        if num_trees < 0 or num_trees >= self._count:
            return self._dev
        return {k: (v[:num_trees] if k != "cat_words" else v)
                for k, v in self._dev.items()}
