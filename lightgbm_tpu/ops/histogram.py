"""Histogram construction on the MXU: the framework's hottest op.

The reference builds per-(leaf,feature) histograms with 4-way unrolled gather
loops on CPU (reference src/io/dense_bin.hpp:71-132) and with per-workgroup
local-memory atomic adds on GPU (reference src/treelearner/ocl/
histogram256.cl:78-120).  TPUs have neither fast random scatter nor atomics —
the idiomatic formulation is a ONE-HOT CONTRACTION:

    hist[s, f*B + b] = sum_r stats[s, r] * (bins[r, f] == b)

i.e. a [S, n] x [n, F*B] matmul whose RHS is a one-hot encoding of the bin
matrix, generated on the fly block-by-block.  The MXU reduces over rows; the
one-hot is exact in bf16, so all precision lies in the stats operand.

Precision modes (`tpu_hist_precision`):
  * "hilo" (default): each f32 stat row is split into bf16 hi + lo rows
    (hi = bf16(x), lo = bf16(x - hi)).  The MXU accumulates in f32, so the
    result carries ~16 mantissa bits of the inputs at full bf16 speed —
    the moral equivalent of the reference GPU's `gpu_use_dp` toggle
    (reference gpu_tree_learner.cpp:306).  The stats matrix is [5, n]:
    rows (g_hi, g_lo, h_hi, h_lo, cnt); the batched kernel packs K leaf
    slots x 5 rows onto the 128-lane axis, so a lean S means more leaves
    per pass (K=25 -> N=125, one 128-lane MXU tile).
  * "f32": full f32 matmul with HIGHEST precision (slowest, exact).
  * "bf16": single bf16 pass (fastest, ~8 mantissa bits).
  * "int16" / "int8": QUANTIZED gradients (the Booster-accelerator /
    LightGBM-quantized-training idea): grad/hess are stochastically
    rounded per iteration onto a fixed-point grid (`quantize_values`,
    scales = per-class max-abs / `quant_limit`), the stats matrix is a
    [3, n] int8/int16 plane, and the MXU contracts narrow-int operands
    with EXACT int32 accumulation (`preferred_element_type=int32`).
    Integer sums are associative, so data-parallel psum'd histograms are
    bit-identical for any shard count — the fast deterministic mode —
    and the stats operand is 2-4x narrower than hilo's [5, n] bf16.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compile_ledger import ledger_jit

# --------------------------------------------------------------------------
# Quantized-gradient support (tpu_hist_precision=int16|int8)
# --------------------------------------------------------------------------

_INT_STAT_DTYPES = {"int8": jnp.int8, "int16": jnp.int16}
_INT_TYPE_MAX = {"int8": 127, "int16": 32767}


def _dot_spec(precision: str):
    """(operand dtype, accumulator dtype, lax precision) for a histogram
    contraction — the ONE table every builder below reads, so the xla and
    pallas backends can never disagree on the int32-exact contract."""
    if precision in _INT_STAT_DTYPES:
        # integer dots ignore lax.Precision; int32 accumulation is exact
        return (_INT_STAT_DTYPES[precision], jnp.int32,
                jax.lax.Precision.DEFAULT)
    if precision == "f64":
        return jnp.float64, jnp.float64, jax.lax.Precision.HIGHEST
    if precision == "f32":
        return jnp.float32, jnp.float32, jax.lax.Precision.HIGHEST
    return jnp.bfloat16, jnp.float32, jax.lax.Precision.DEFAULT


def quant_limit(precision: str, total_rows: int) -> int:
    """Largest |quantized| stat value such that a worst-case histogram bin
    (every row landing in it at max magnitude) still fits int32.

    The grid narrows below the dtype's own range once total_rows exceeds
    2^31 / type_max (~65k rows for int16, ~16.9M for int8): the stats
    still ship/contract at the narrow dtype's width, only the effective
    mantissa shrinks — overflow is impossible by construction, on one
    shard or across any psum of shards (the bound is on GLOBAL rows)."""
    cap = (2 ** 31 - 1) // max(int(total_rows), 1)
    q = min(_INT_TYPE_MAX[precision], cap)
    if q < 1:
        raise ValueError(
            f"{total_rows} rows overflow int32 histogram accumulation even "
            "at 1-bit quantization; use a float tpu_hist_precision")
    return q


def _hash_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Stateless PCG-style avalanche over uint32 counters (wrapping
    arithmetic): the per-row randomness source for stochastic rounding.
    Keyed on the GLOBAL row index so the draw is invariant to how rows
    are sharded — a requirement for bit-identical data-parallel
    quantization, which jax.random's shape-keyed streams cannot give
    under shard_map."""
    x = x * jnp.uint32(747796405) + jnp.uint32(2891336453)
    w = ((x >> ((x >> jnp.uint32(28)) + jnp.uint32(4))) ^ x) \
        * jnp.uint32(277803737)
    return (w >> jnp.uint32(22)) ^ w


def hashed_uniform(idx: jnp.ndarray, seed_a, seed_b, salt: int
                   ) -> jnp.ndarray:
    """[n] uniforms in [0, 1) from uint32 row counters + two key words."""
    h = _hash_u32(idx.astype(jnp.uint32)
                  ^ (jnp.asarray(seed_a, jnp.uint32) ^ jnp.uint32(salt)))
    h = _hash_u32(h + jnp.asarray(seed_b, jnp.uint32))
    return (h >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def key_words(key: jnp.ndarray):
    """Two uint32 words from a PRNG key (raw uint32[2] or typed)."""
    try:
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            key = jax.random.key_data(key)
    except (AttributeError, TypeError):  # pragma: no cover - old jax
        pass
    kw = jnp.ravel(key).astype(jnp.uint32)
    return kw[0], kw[-1]


def quantize_values(x: jnp.ndarray, scale, qmax: int, mode: str,
                    seed_a=0, seed_b=0, row_offset=0, salt: int = 0,
                    stochastic=None) -> jnp.ndarray:
    """f32 [n] -> int32 grid values in [-qmax, qmax]: x ~= result * scale.

    mode="stochastic" rounds floor(q) up with probability frac(q) —
    unbiased (E[result] * scale == x on-grid) and deterministic given the
    seed words; the randomness comes from `hashed_uniform` over GLOBAL
    row indices (row_offset = this shard's first global row), so the
    rounded values are identical under any row sharding.
    mode="nearest" is plain round-half-to-even.

    `stochastic` (optional TRACED scalar, >0 = stochastic) folds the
    rounding-mode switch into the program instead of keying a distinct
    compile on `mode`: both roundings are elementwise-cheap, so ONE
    program serves either value (the grower passes its traced mode flag
    here; `mode` is ignored then).  Each selected branch is bit-identical
    to the corresponding static `mode`."""
    q = jnp.clip(x / scale, -float(qmax), float(qmax))
    if stochastic is None and mode == "nearest":
        return jnp.rint(q).astype(jnp.int32)
    fl = jnp.floor(q)
    idx = (jnp.arange(x.shape[0], dtype=jnp.uint32)
           + jnp.asarray(row_offset).astype(jnp.uint32))
    r = hashed_uniform(idx, seed_a, seed_b, salt)
    sto = (fl + (r < (q - fl))).astype(jnp.int32)
    if stochastic is None:
        return sto
    return jnp.where(stochastic > 0, sto, jnp.rint(q).astype(jnp.int32))


def bench_hist_operands(bins_np: np.ndarray, precision: str, block: int,
                        seed: int = 0):
    """Blocked operands for histogram micro-benchmarks (bench.py's
    hist_rows_per_sec and tools/perf_probe.py's hist sweep — ONE
    implementation so the stats layout and quantization call can't
    drift between them): slice to whole blocks, transpose to the
    [nb, F, block] layout, draw synthetic grad/hess, quantize for int
    precisions.  Returns (bins_t_blocks, stats_blocks, n_use)."""
    n, F = bins_np.shape
    nb = n // block
    if nb < 1:
        raise ValueError(f"need >= {block} rows, have {n}")
    n_use = nb * block
    rng = np.random.default_rng(seed)
    g = jnp.asarray(rng.normal(size=n_use).astype(np.float32))
    h = jnp.asarray((np.abs(rng.normal(size=n_use)) + 0.1)
                    .astype(np.float32))
    ones = jnp.ones(n_use, jnp.float32)
    if precision in _INT_STAT_DTYPES:
        q = quant_limit(precision, n_use)
        g = quantize_values(g, jnp.max(jnp.abs(g)) / q, q, "nearest")
        h = quantize_values(h, jnp.max(jnp.abs(h)) / q, q, "nearest")
    stats = pack_stats(g, h, ones, precision)
    bins_tb = jnp.asarray(np.ascontiguousarray(bins_np[:n_use].T)
                          .reshape(F, nb, block).transpose(1, 0, 2))
    return bins_tb, stats.reshape(-1, nb, block), n_use


def pack_stats(grad: jnp.ndarray, hess: jnp.ndarray, mask: jnp.ndarray,
               precision: str = "hilo") -> jnp.ndarray:
    """Pack per-row gradient/hessian/count-mask into histogram stat rows.

    grad/hess must already be multiplied by `mask` by the caller if masking
    is intended (mask also serves as the count row).
    Returns [5, n] bf16 for "hilo", [3, n] bf16/f32/f64 otherwise.

    "f64" is the deterministic-parity mode (requires jax_enable_x64): all
    accumulation runs in doubles like the reference's HistogramBinEntry
    (reference include/LightGBM/bin.h:33-40), so serial and data-parallel
    split decisions agree bit-for-bit on real data regardless of psum
    reduction order.

    "int8"/"int16": grad/hess must ALREADY be quantized int values from
    `quantize_values` (within +-quant_limit); the return is the narrow
    [3, n] integer stats plane the int32-accumulating contraction reads.
    """
    if precision in _INT_STAT_DTYPES:
        dt = _INT_STAT_DTYPES[precision]
        return jnp.stack([grad.astype(dt), hess.astype(dt),
                          mask.astype(dt)])
    if precision == "f64":
        return jnp.stack([grad, hess, mask]).astype(jnp.float64)
    if precision == "f32":
        return jnp.stack([grad, hess, mask]).astype(jnp.float32)
    if precision == "bf16":
        return jnp.stack([grad, hess, mask]).astype(jnp.bfloat16)
    # hilo
    g_hi = grad.astype(jnp.bfloat16)
    g_lo = (grad - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    h_hi = hess.astype(jnp.bfloat16)
    h_lo = (hess - h_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    cnt = mask.astype(jnp.bfloat16)  # exact: 0.0 or 1.0
    return jnp.stack([g_hi, g_lo, h_hi, h_lo, cnt])


def _unpack_hist(raw: jnp.ndarray, precision: str) -> jnp.ndarray:
    """[S, F*B] accumulated rows -> [F*B, 3] (g, h, cnt).

    Int precisions stay int32 here: the grower's pool, psum, and sibling
    subtraction all run on exact integers; rescaling to f32 happens once
    per leaf at the split-search boundary (ops/grower.py select)."""
    if precision in ("f32", "f64", "bf16", "int8", "int16"):
        g, h, c = raw[0], raw[1], raw[2]
    else:
        g = raw[0] + raw[1]
        h = raw[2] + raw[3]
        c = raw[4]
    return jnp.stack([g, h, c], axis=-1)


@ledger_jit(site="histogram.build",
            static_argnames=("num_bins", "block_rows", "precision"))
def build_histogram(bins: jnp.ndarray, stats: jnp.ndarray, num_bins: int,
                    block_rows: int = 16384, precision: str = "hilo"
                    ) -> jnp.ndarray:
    """hist[f, b, (g,h,cnt)] over all rows.

    bins:  [n, F] int (bin index per row/feature, 0 <= bin < num_bins)
    stats: packed rows from `pack_stats` ([S, n])
    Returns [F, B, 3] f32.

    Rows are processed in blocks via lax.scan so the materialized one-hot is
    [block, F*B] (bf16) rather than [n, F*B]; XLA fuses the compare+select
    into the matmul operand.
    """
    n, num_features = bins.shape
    dot_dtype, acc_dtype, prec = _dot_spec(precision)

    block = min(block_rows, max(n, 1))
    num_blocks = (n + block - 1) // block
    pad = num_blocks * block - n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        stats = jnp.pad(stats, ((0, 0), (0, pad)))  # zero stats: no contribution

    bins_blocks = bins.reshape(num_blocks, block, num_features)
    stats_blocks = stats.reshape(stats.shape[0], num_blocks, block)
    iota = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, xs):
        b_blk, s_blk = xs  # [block, F], [S, block]
        onehot = (b_blk[:, :, None] == iota).astype(dot_dtype)
        onehot = onehot.reshape(block, num_features * num_bins)
        acc = acc + jnp.dot(s_blk.astype(dot_dtype), onehot,
                            precision=prec,
                            preferred_element_type=acc_dtype)
        return acc, None

    init = jnp.zeros((stats.shape[0], num_features * num_bins), acc_dtype)
    raw, _ = jax.lax.scan(
        body, init, (bins_blocks, jnp.moveaxis(stats_blocks, 1, 0)))
    hist = _unpack_hist(raw, precision)
    return hist.reshape(num_features, num_bins, 3)


def build_histogram_batched_t(bins_t_blocks, stats_blocks, leaf_blocks,
                              slot_leaf_ids, num_bins: int,
                              precision: str = "hilo",
                              impl: str = "xla",
                              packed_rows: bool = False) -> jnp.ndarray:
    """Transposed-layout batched histogram: rows on the lane axis.

    Same contraction as `build_histogram_batched_inline` but with the bin
    matrix stored [F, n] so every operand keeps rows in the 128-lane minor
    dimension (bins [F, blk], stats [S, blk], leaf [1, blk]) — no 28-lane
    padding waste and no layout changes between the one-hot generation and
    the MXU feed.

    bins_t_blocks: [nb, F, block] integer bins (uint8 when
        bins fit — the narrow dense storage — else int32)
    stats_blocks:  [S, nb, block]
    leaf_blocks:   [nb, block] int32
    slot_leaf_ids: [K] int32 (-1 = dead slot)
    impl: "xla" (lax.scan + dot_general) or "pallas" (fused VMEM kernel)
    Returns [K, F, B, 3] f32.
    """
    if impl in ("pallas", "pallas2", "fused"):
        # "fused" rides the perfeature VMEM accumulator here: the in-kernel
        # split scan lives in ops/fused.py and only engages on the grower's
        # frontier step — every other call site (root pass, streamed
        # blocks, probes) builds plain histograms with the same kernel
        return _hist_pallas(
            bins_t_blocks, stats_blocks, leaf_blocks, slot_leaf_ids,
            num_bins, precision,
            variant="flat" if impl == "pallas" else "perfeature",
            packed_rows=packed_rows)
    if packed_rows:
        raise ValueError("packed (4-bit) bins require a pallas impl")
    nb, num_features, block = bins_t_blocks.shape
    S = stats_blocks.shape[0]
    K = slot_leaf_ids.shape[0]
    dot_dtype, acc_dtype, prec = _dot_spec(precision)

    def body(acc, xs):
        b_t, s_blk, l_blk = xs  # [F, blk], [S, blk], [blk]
        iota = jax.lax.broadcasted_iota(jnp.int32,
                                        (num_features, num_bins, block), 1)
        onehot = (b_t[:, None, :] == iota).astype(dot_dtype)
        onehot = onehot.reshape(num_features * num_bins, block)
        slot_oh = (slot_leaf_ids[:, None] == l_blk[None, :]).astype(dot_dtype)
        sexp = (slot_oh[:, None, :] * s_blk[None, :, :].astype(dot_dtype))
        sexp = sexp.reshape(K * S, block)
        acc = acc + jax.lax.dot_general(
            onehot, sexp, (((1,), (1,)), ((), ())),
            precision=prec, preferred_element_type=acc_dtype)
        return acc, None

    init = jnp.zeros((num_features * num_bins, K * S), acc_dtype)
    raw, _ = jax.lax.scan(
        body, init, (bins_t_blocks, jnp.moveaxis(stats_blocks, 1, 0),
                     leaf_blocks))
    raw = jnp.transpose(
        raw.reshape(num_features * num_bins, K, S), (1, 2, 0))
    hist = jax.vmap(lambda r: _unpack_hist(r, precision))(raw)
    return hist.reshape(K, num_features, num_bins, 3)


def build_histogram_sparse(sidx: jnp.ndarray, sbin: jnp.ndarray,
                           stats: jnp.ndarray, leaf_ids: jnp.ndarray,
                           slot_leaf_ids: jnp.ndarray, num_bins: int,
                           precision: str = "hilo",
                           block_entries: int = 2048) -> jnp.ndarray:
    """Batched histograms for COO-stored sparse feature groups.

    The dense contraction sweeps every row per group; sparse groups store
    only their nonzero-bin entries (reference OrderedSparseBin,
    src/io/ordered_sparse_bin.hpp — delta-encoded there, padded COO
    here), so the sweep is O(nnz) per group: gather the stats and leaf
    ids at the stored row ids, then run the SAME one-hot x slot-one-hot
    contraction per group over the entry axis.

    sidx: [Gs, M] int32 stored row ids; padding entries may hold any
        value (e.g. n_pad) — their sbin must be num_bins, whose one-hot
        row is all-zero, so they contribute nothing regardless of what
        the (clipped) gather returns.
    sbin: [Gs, M] int32 stored bins in [0, B); padding = num_bins.
    stats: [S, n_pad] packed rows from `pack_stats`.
    leaf_ids: [n_pad] int32 current leaf per row.
    slot_leaf_ids: [K] int32 (-1 = dead slot).
    Returns [K, Gs, B, 3] f32/f64 — WITHOUT the implicit zero-bin mass
    (every unstored row); the grower reconstructs it from leaf totals
    exactly like FixHistogram (reference dataset.cpp:1044-1063).
    """
    Gs, M = sidx.shape
    S = stats.shape[0]
    K = slot_leaf_ids.shape[0]
    dot_dtype, acc_dtype, prec = _dot_spec(precision)

    mb = min(block_entries, M)
    nmb = (M + mb - 1) // mb
    if nmb * mb != M:  # static pad to whole blocks; pads contribute 0
        padw = nmb * mb - M
        sidx = jnp.pad(sidx, ((0, 0), (0, padw)))
        sbin = jnp.pad(sbin, ((0, 0), (0, padw)),
                       constant_values=num_bins)
    sidx_b = jnp.moveaxis(sidx.reshape(Gs, nmb, mb), 1, 0)  # [nmb, Gs, mb]
    sbin_b = jnp.moveaxis(sbin.reshape(Gs, nmb, mb), 1, 0)
    iota_b = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, xs):
        si, sb = xs                              # [Gs, mb] each
        safe = jnp.clip(si, 0, stats.shape[1] - 1)
        st = stats[:, safe]                      # [S, Gs, mb] gather
        lf = leaf_ids[safe]                      # [Gs, mb]
        slot_oh = (slot_leaf_ids[:, None, None] == lf[None]).astype(dot_dtype)
        onehot = (sb[:, None, :] == iota_b[None, :, None]).astype(dot_dtype)
        sexp = (slot_oh[:, None, :, :]                    # [K, 1, Gs, mb]
                * st[None, :, :, :].astype(dot_dtype))    # [1, S, Gs, mb]
        sexp = jnp.moveaxis(sexp.reshape(K * S, Gs, mb), 1, 0)  # [Gs, KS, mb]
        acc = acc + jax.lax.dot_general(
            onehot, sexp, (((2,), (2,)), ((0,), (0,))),
            precision=prec, preferred_element_type=acc_dtype)  # [Gs, B, KS]
        return acc, None

    init = jnp.zeros((Gs, num_bins, K * S), acc_dtype)
    raw, _ = jax.lax.scan(body, init, (sidx_b, sbin_b))
    raw = jnp.transpose(raw.reshape(Gs, num_bins, K, S),
                        (2, 3, 0, 1))            # [K, S, Gs, B]
    raw = raw.reshape(K, S, Gs * num_bins)
    hist = jax.vmap(lambda r: _unpack_hist(r, precision))(raw)
    return hist.reshape(K, Gs, num_bins, 3)


# VMEM budget for one feature chunk's accumulator block in the perfeature
# pallas kernel; the remaining ~10 MB of VMEM holds the [Bp, blk] one-hot,
# the [K*S, blk] expanded stats, and the double-buffered input DMAs
_PERFEATURE_OUT_BUDGET = 6 * 1024 * 1024


def unpack2d(b2):
    """[.., blk/2] packed two-rows-per-byte uint8 -> [.., blk] int32.

    The SINGLE definition of the 4-bit stride layout (low nibbles are a
    block's first half of rows, high nibbles the second): the pallas
    kernels and the grower's partition unpack must agree or packed
    histograms and packed partitions silently diverge."""
    return jnp.concatenate(
        [(b2 & 0xF).astype(jnp.int32), (b2 >> 4).astype(jnp.int32)],
        axis=-1)


def _hist_pallas(bins_t_blocks, stats_blocks, leaf_blocks, slot_leaf_ids,
                 num_bins: int, precision: str, variant: str,
                 packed_rows: bool = False) -> jnp.ndarray:
    """Pallas kernel: fused one-hot + slot-expansion + MXU contraction.

    The TPU answer to the reference GPU kernel's workgroup-local
    sub-histograms (reference src/treelearner/ocl/histogram256.cl:78-120):
    the accumulator stays resident in VMEM across the row-block grid, and
    neither the one-hot nor the expanded stats ever round-trip to HBM.

    Two kernel-body variants share this scaffolding:

    * "flat" (impl "pallas"): one [F*B, blk] one-hot dot per grid step.
      Hardware-validated at 256-row blocks (1.93 it/s on the Higgs-1M
      bench shape, docs/PERF_NOTES.md); the monolithic one-hot costs a
      multi-MB VMEM retiling copy per step (merging the [F, B, blk]
      iota-compare into dot operand layout) and caps the block at 256
      rows before VMEM overflows, putting ~4k grid steps of accumulator
      read-modify-write on the critical path.
    * "perfeature" (impl "pallas2", the hardware-validated auto default:
      3.14 it/s on the Higgs-1M bench shape at 8192-row blocks with
      hilo precision + frontier ramp, round-3 sweep in
      docs/PERF_NOTES.md): the one-hot is generated per feature ([Bp, blk],
      statically-unrolled dots), so the largest temporary shrinks from
      [F*B, blk] to [Bp, blk], blocks of 2-8k rows fit, and the grid
      shrinks ~16x.  Each feature's bin rows live at a sublane-aligned
      Bp = ceil(B/8)*8 offset in the accumulator.  When the full [F*Bp,
      K*S] accumulator would overflow VMEM (wide data: Epsilon/Bosch
      F*B shapes), the grid gains a FEATURE axis: features are processed
      in the largest divisor-of-F chunk whose [fblk*Bp, K*S] out block
      fits, and the row-block axis iterates innermost so each feature
      chunk's accumulator stays VMEM-resident across its row sweep.
    """
    from jax.experimental import pallas as pl

    nb, F, bins_block = bins_t_blocks.shape
    # packed 4-bit storage (the reference dense_nbits_bin.hpp analog,
    # max_bin<=16): each uint8 byte holds TWO rows of one block — row j in
    # the low nibble, row j + block/2 in the high nibble — so the kernel's
    # row-sweep DMA traffic halves.  Unpacking is a nibble mask/shift plus
    # a lane-axis concat of two half-blocks (the stride layout exists so
    # the concat IS the row order).
    block = bins_block * 2 if packed_rows else bins_block
    S = stats_blocks.shape[0]
    K = slot_leaf_ids.shape[0]
    B = num_bins
    # sublane-aligned per-feature row offset (perfeature variant only)
    Bp = -(-B // 8) * 8 if variant == "perfeature" else B
    # int accumulator twins: narrow-int operands, exact int32 VMEM
    # accumulator — the [3, n] int8 stats plane is 2-4x leaner than
    # hilo's [5, n] bf16, so larger row blocks fit the same VMEM budget
    if precision in _INT_STAT_DTYPES:
        dot_dtype, acc_dtype, dot_prec = _dot_spec(precision)
    else:
        dot_dtype = jnp.float32 if precision == "f32" else jnp.bfloat16
        acc_dtype = jnp.float32
        dot_prec = (jax.lax.Precision.HIGHEST if precision == "f32"
                    else jax.lax.Precision.DEFAULT)

    def expand_slots(stats_ref, leaf_ref, slots_ref):
        """[K*S, blk] per-slot stats: slot one-hot x packed stat rows."""
        s = stats_ref[0]                        # [S, blk]
        l = leaf_ref[0]                         # [1, blk] i32
        slots = slots_ref[:]                    # [K, 1] i32
        slot_oh = (slots == l).astype(dot_dtype)            # [K, blk]
        sexp = (slot_oh[:, None, :] * s[None, :, :].astype(dot_dtype))
        return sexp.reshape(K * S, block)

    def accumulate(i, out_ref, rows, acc):
        @pl.when(i == 0)
        def _():
            out_ref[rows, :] = acc

        @pl.when(i > 0)
        def _():
            out_ref[rows, :] += acc

    def kernel_flat(bins_ref, stats_ref, leaf_ref, slots_ref, out_ref):
        i = pl.program_id(0)
        # explicit upcast: bins may arrive uint8 (narrow dense storage) and
        # Mosaic's compare wants a full-width integer operand
        b_t = (unpack2d(bins_ref[0]) if packed_rows
               else bins_ref[0].astype(jnp.int32))   # [F, blk]
        sexp = expand_slots(stats_ref, leaf_ref, slots_ref)
        iota = jax.lax.broadcasted_iota(jnp.int32, (F, B, block), 1)
        onehot = (b_t[:, None, :] == iota).astype(dot_dtype)
        onehot = onehot.reshape(F * B, block)
        acc = jax.lax.dot_general(
            onehot, sexp, (((1,), (1,)), ((), ())),
            precision=dot_prec, preferred_element_type=acc_dtype)
        accumulate(i, out_ref, slice(None), acc)

    def kernel_perfeature_chunk(fblk):
        def kernel(bins_ref, stats_ref, leaf_ref, slots_ref, out_ref):
            i = pl.program_id(1)  # row-block axis (innermost)
            sexp = expand_slots(stats_ref, leaf_ref, slots_ref)
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (Bp, block), 0)
            for f in range(fblk):
                if packed_rows:
                    b_f = unpack2d(bins_ref[0, f])          # [blk]
                else:
                    b_f = bins_ref[0, f].astype(jnp.int32)  # [blk]
                onehot = (b_f[None, :] == iota_b).astype(dot_dtype)
                acc = jax.lax.dot_general(
                    onehot, sexp, (((1,), (1,)), ((), ())),
                    precision=dot_prec,
                    preferred_element_type=acc_dtype)
                accumulate(i, out_ref, slice(f * Bp, (f + 1) * Bp), acc)
        return kernel

    # Mosaic block-shape rule: the last two dims of every block must be
    # (8k, 128k)-aligned or equal the array's dims.  All operands are laid
    # out [nb, ..., block] so each grid step's block matches the trailing
    # dims exactly; the S/leaf axes ride along whole.
    stats_nb = jnp.moveaxis(stats_blocks, 1, 0)             # [nb, S, blk]
    interpret = jax.devices()[0].platform not in ("tpu",)
    if variant == "flat":
        raw = pl.pallas_call(
            kernel_flat,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((1, F, bins_block), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, S, block), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, 1, block), lambda i: (i, 0, 0)),
                pl.BlockSpec((K, 1), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((F * B, K * S), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((F * B, K * S), acc_dtype),
            interpret=interpret,
        )(bins_t_blocks, stats_nb, leaf_blocks.reshape(nb, 1, block),
          slot_leaf_ids.reshape(K, 1))
    else:
        # feature chunking: largest divisor of F whose out block fits the
        # VMEM budget.  Mosaic block-shape rules constrain the candidates:
        # the bins block's second-minor dim (fblk) must be sublane-tile-
        # aligned for the bins dtype unless it equals the array dim F, and
        # the accumulator's lane width pads to 128.  When F has no
        # aligned divisor that fits (e.g. F = 2000 = 2^4 * 5^3 for uint8
        # bins), the kernel stays single-chunk — identical to the
        # pre-chunking behavior; the learner pads the column axis to a
        # 32-multiple for pallas2 precisely to unlock chunking.
        ks_pad = -(-(K * S) // 128) * 128
        budget = _PERFEATURE_OUT_BUDGET
        # sublane tile of the bins dtype: 32 rows for uint8, 16 for
        # 2-byte, 8 for int32 — the chunk width must stay tile-aligned
        step = {1: 32, 2: 16, 4: 8}[bins_t_blocks.dtype.itemsize]

        def fits(c):
            return c * Bp * ks_pad * 4 <= budget

        fblk = F
        if not fits(F):
            cands = [c for c in range(step, F, step)
                     if F % c == 0 and fits(c)]
            if cands:
                fblk = max(cands)
        nf = F // fblk
        # grid order: the row-block axis is LAST (innermost), so each
        # feature chunk's accumulator block stays resident while the row
        # sweep accumulates into it
        raw = pl.pallas_call(
            kernel_perfeature_chunk(fblk),
            grid=(nf, nb),
            in_specs=[
                pl.BlockSpec((1, fblk, bins_block), lambda fi, i: (i, fi, 0)),
                pl.BlockSpec((1, S, block), lambda fi, i: (i, 0, 0)),
                pl.BlockSpec((1, 1, block), lambda fi, i: (i, 0, 0)),
                pl.BlockSpec((K, 1), lambda fi, i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((fblk * Bp, K * S),
                                   lambda fi, i: (fi, 0)),
            out_shape=jax.ShapeDtypeStruct((F * Bp, K * S), acc_dtype),
            interpret=interpret,
        )(bins_t_blocks, stats_nb, leaf_blocks.reshape(nb, 1, block),
          slot_leaf_ids.reshape(K, 1))
    if variant == "perfeature":
        raw = jnp.transpose(raw.reshape(F, Bp, K, S)[:, :B], (2, 3, 0, 1))
        raw = raw.reshape(K, S, F * B)
    else:
        raw = jnp.transpose(raw.reshape(F * B, K, S), (1, 2, 0))
    hist = jax.vmap(lambda r: _unpack_hist(r.reshape(S, F * B), precision))(
        raw)
    return hist.reshape(K, F, B, 3)


def build_histogram_t(bins_t_blocks, stats_blocks, num_bins: int,
                      precision: str = "hilo") -> jnp.ndarray:
    """Single-histogram (root) pass in the transposed layout.

    bins_t_blocks: [nb, F, block]; stats_blocks: [S, nb, block].
    Returns [F, B, 3] f32.
    """
    nb, num_features, block = bins_t_blocks.shape
    dot_dtype, acc_dtype, prec = _dot_spec(precision)

    def body(acc, xs):
        b_t, s_blk = xs
        iota = jax.lax.broadcasted_iota(jnp.int32,
                                        (num_features, num_bins, block), 1)
        onehot = (b_t[:, None, :] == iota).astype(dot_dtype)
        onehot = onehot.reshape(num_features * num_bins, block)
        acc = acc + jax.lax.dot_general(
            onehot, s_blk.astype(dot_dtype), (((1,), (1,)), ((), ())),
            precision=prec, preferred_element_type=acc_dtype)
        return acc, None

    init = jnp.zeros((num_features * num_bins, stats_blocks.shape[0]),
                     acc_dtype)
    raw, _ = jax.lax.scan(
        body, init, (bins_t_blocks, jnp.moveaxis(stats_blocks, 1, 0)))
    hist = _unpack_hist(raw.T, precision)
    return hist.reshape(num_features, num_bins, 3)


def build_histogram_batched_inline(bins_blocks, stats_blocks, leaf_blocks,
                                   slot_leaf_ids, num_bins: int,
                                   precision: str = "hilo") -> jnp.ndarray:
    """Histograms of K leaves in ONE contraction — the perf-critical kernel.

    The single-leaf formulation ([S, n] x [n, F*B]) is an M=8 matmul: at most
    8/128 of the MXU's systolic rows ever light up (~3% MFU measured on
    v5e).  Batching K leaves widens the small axis to K*S = 128+ lanes:

        hist[(f,b), (k,s)] = sum_r onehot[r, (f,b)] * stats[s, r]
                                    * (leaf_ids[r] == slot_leaf_ids[k])

    i.e. a [F*B, block] x [block, K*S] dot_general per row block — M=F*B,
    N=K*S, both MXU-shaped.  Total FLOPs per tree are unchanged versus K
    single-leaf passes (each row contributes to exactly one leaf slot; the
    rest of the dense work was always wasted), but utilization rises ~10x
    and the tree takes ~254/K passes instead of 254.  This is the TPU analog
    of the reference GPU kernel histogramming many features per workgroup
    (reference src/treelearner/ocl/histogram256.cl:78-120).

    bins_blocks:   [nb, block, F] int32
    stats_blocks:  [S, nb, block] packed rows from `pack_stats`
    leaf_blocks:   [nb, block] int32 current leaf id per row
    slot_leaf_ids: [K] int32 leaf id wanted in each slot (-1 = dead slot)
    Returns [K, F, B, 3] f32.
    """
    nb, block, num_features = bins_blocks.shape
    S = stats_blocks.shape[0]
    K = slot_leaf_ids.shape[0]
    dot_dtype, _, prec = _dot_spec(precision)
    acc_dtype = (jnp.int32 if precision in _INT_STAT_DTYPES
                 else jnp.float32)
    iota = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, xs):
        b_blk, s_blk, l_blk = xs  # [block, F], [S, block], [block]
        onehot = (b_blk[:, :, None] == iota).astype(dot_dtype)
        onehot = onehot.reshape(block, num_features * num_bins)
        slot_oh = (l_blk[:, None] == slot_leaf_ids[None, :]).astype(dot_dtype)
        sexp = (slot_oh[:, :, None]
                * jnp.swapaxes(s_blk, 0, 1).astype(dot_dtype)[:, None, :])
        sexp = sexp.reshape(block, K * S)
        acc = acc + jax.lax.dot_general(
            onehot, sexp, (((0,), (0,)), ((), ())),
            precision=prec, preferred_element_type=acc_dtype)
        return acc, None

    init = jnp.zeros((num_features * num_bins, K * S), acc_dtype)
    raw, _ = jax.lax.scan(
        body, init, (bins_blocks, jnp.moveaxis(stats_blocks, 1, 0),
                     leaf_blocks))
    # [F*B, K*S] -> [K, S, F*B] -> unpack -> [K, F, B, 3]
    raw = jnp.transpose(raw.reshape(num_features * num_bins, K, S), (1, 2, 0))
    hist = jax.vmap(lambda r: _unpack_hist(r, precision))(raw)
    return hist.reshape(K, num_features, num_bins, 3)


def build_histogram_inline(bins_blocks, stats_blocks, num_bins: int,
                           precision: str = "hilo") -> jnp.ndarray:
    """Non-jitted variant for use INSIDE an outer jit/scan (the tree grower).

    bins_blocks: [nb, block, F], stats_blocks: [S, nb, block] (already padded).
    """
    nb, block, num_features = bins_blocks.shape
    dot_dtype, _, prec = _dot_spec(precision)
    acc_dtype = (jnp.int32 if precision in _INT_STAT_DTYPES
                 else jnp.float32)
    iota = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, xs):
        b_blk, s_blk = xs
        onehot = (b_blk[:, :, None] == iota).astype(dot_dtype)
        onehot = onehot.reshape(block, num_features * num_bins)
        acc = acc + jnp.dot(s_blk.astype(dot_dtype), onehot,
                            precision=prec,
                            preferred_element_type=acc_dtype)
        return acc, None

    init = jnp.zeros((stats_blocks.shape[0], num_features * num_bins),
                     acc_dtype)
    raw, _ = jax.lax.scan(body, init, (bins_blocks, jnp.moveaxis(stats_blocks, 1, 0)))
    return _unpack_hist(raw, precision).reshape(num_features, num_bins, 3)
