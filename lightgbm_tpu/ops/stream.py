"""Out-of-core streamed tree growth: host-resident bins, blocked H2D.

The resident grower (ops/grower.py) assumes the transposed [G, n_pad]
bin matrix lives in HBM for the whole run — dataset size is capped by
device memory.  This module removes that cap (ROADMAP: rows x features
stops being a refusal): the binned matrix stays HOST-resident, rows are
partitioned into fixed-size stream blocks, and each grower round streams
the blocks through two device slots so block i+1's H2D copy overlaps
block i's histogram contraction (the out-of-core GBDT scheme of
arXiv 2005.09148, with the per-block histogram work kept device-shaped
as in arXiv 1706.08359).

Structure — the resident grower's ONE `lax.while_loop` program becomes a
small, BOUNDED family of jitted programs driven by a host loop (one
host sync per round, on a single `cont` scalar):

* ``prep``        — gradient quantization, packed stats, scalar sums
                    (the resident root preamble, verbatim math);
* ``root_block``  / ``block_step`` — per-stream-block histogram
                    accumulation (+ the round's row partition), donated
                    accumulators, one compiled shape per block width
                    (full R and the final partial block — no per-block
                    retrace);
* ``root_finish`` / ``round_head`` / ``round_update`` — the resident
    round body split at the histogram seam: everything except the
    contraction runs on [L]/[K]-sized state, device-resident between
    programs;
* ``finish``      — quantized leaf refit + the out dict;
* ``replay_block``— recover leaf ids for GOSS-skipped blocks by
    replaying the split records (one partition-only pass per skipped
    block at tree end);
* ``goss_plan``   — per-block sum|g*h| scores + PCG uniforms keyed on
    each block's first GLOBAL row index (graftlint D101: invariant to
    padding and topology).

Bitwise contract: the histogram accumulator is block-partitioned in the
ACCUMULATION dtype.  For int8/int16 precisions every sum is int32 and
therefore associative, the row padding, quantization grid (same n_pad
as the resident layout) and stochastic-rounding hash (GLOBAL row
indices, row0=0) are identical — so streamed model files are
BYTE-IDENTICAL to resident ones.  Float precisions (f32/f64/hilo/bf16)
reassociate across the stream-block seam and are numerically close but
not bitwise.  GOSS block sampling changes which rows build each tree,
so it deliberately trades the bitwise-vs-resident guarantee for fewer
H2D copies per iteration.

Restrictions (validated by the streamed learner): serial tree_learner,
numerical features only, no EFB bundling, no sparse COO storage, no
CEGB, no forced splits, no per-node feature sampling, no 4-bit packing.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..utils.compile_ledger import ledger_jit
from .grower import (GrowerParams, K_MIN_SCORE, MF_QUANT_REFIT,
                     MF_STOCHASTIC, pool_dtype)
from .histogram import (build_histogram_batched_t, build_histogram_t,
                        hashed_uniform, key_words, pack_stats, quant_limit,
                        quantize_values)
from .split import (MISSING_NAN, MISSING_ZERO, finalize_split, leaf_output,
                    per_feature_best_split)

# record-row indices mirrored from ops/grower.py (REC_*): replay_block
# reads the same packed layout the round body writes
from .grower import (REC_DEFAULT_LEFT, REC_DID_SPLIT, REC_FEATURE,
                     REC_LEAF, REC_THRESHOLD, REC_WIDTH)


def stream_supported(params: GrowerParams) -> Optional[str]:
    """None when the streamed layout can serve these grower params, else
    a human-readable reason it cannot (the learner raises / the planner
    refuses to auto-select on it)."""
    if params.has_cat:
        return "categorical features"
    if params.has_bundles:
        return "EFB bundling (enable_bundle)"
    if params.has_sparse:
        return "sparse COO storage (tpu_sparse_threshold)"
    if params.has_cegb or params.has_cegb_lazy:
        return "CEGB penalties"
    if params.forced:
        return "forced splits"
    if params.feature_fraction_bynode < 1.0:
        return "feature_fraction_bynode"
    if params.packed_bins:
        return "packed 4-bit bins (tpu_pack_bins)"
    return None


def _numeric_go_left(col, mt, nbf, db, thr, dleft):
    """Numerical split decision incl. missing routing — the resident
    grower's `numeric_go_left`, duplicated (it is nested inside
    make_grower) so the streamed partition and replay use the SAME
    elementwise math bit for bit."""
    is_miss = jnp.where(
        mt == MISSING_NAN, col == nbf - 1,
        jnp.where(mt == MISSING_ZERO, col == db, False))
    return jnp.where(is_miss, dleft, col <= thr)


def _scatter_set(arr, idx, val, valid):
    # invalid slots write out of bounds -> dropped (resident scatter_set)
    safe = jnp.where(valid, idx, arr.shape[0])
    return arr.at[safe].set(val, mode="drop")


def _hist_geometry(params: GrowerParams, rows: int):
    """Inner histogram-scan blocking for a stream block of `rows` rows —
    the resident grower's block derivation applied to the block width
    (int32 accumulation makes the decomposition value-invariant)."""
    block = min(params.block_rows, rows)
    nbi = max(rows // block, 1)
    return nbi, rows // nbi


@functools.lru_cache(maxsize=16)
def _build_stream_programs(params: GrowerParams, G: int, n_pad: int):
    """The bounded jitted-program family for one (params, shape) pair.

    Memoized like `_build_grower` so a ladder rebuild at the same shape
    reuses the compiled executables.  Every program's shapes are fixed
    except the stream-block width of `root_block` / `block_step` /
    `replay_block`, which admits exactly two values (the full block R
    and the final partial block) — the compile-ledger gate in
    tests/test_stream.py pins the total program count.
    """
    L = params.num_leaves
    B = params.num_bins
    K = max(1, min(int(params.split_batch), L - 1))
    precision = params.precision
    quantized = precision in ("int8", "int16")
    hist_t = pool_dtype(precision)
    big = jnp.float32(1e30)

    split_kw = dict(l1=params.l1, l2=params.l2,
                    max_delta_step=params.max_delta_step,
                    min_data_in_leaf=params.min_data_in_leaf,
                    min_sum_hessian=params.min_sum_hessian,
                    min_gain_to_split=params.min_gain_to_split)

    def select_one(hist, sg, sh, cnt, min_c, max_c, fmask, qscale, meta):
        """The resident select() restricted to the streamed feature set
        (serial, numerical, no bundles/sparse/cat/CEGB): identical ops
        in identical order, so split decisions match bit for bit."""
        acc = qscale if quantized else None
        if not quantized and hist.dtype != jnp.float32:
            # f64 deterministic pool: the search consumes the
            # accumulation dtype directly (resident dequant is identity)
            pass
        pf = per_feature_best_split(
            hist, sg, sh, cnt,
            meta["num_bin"], meta["missing_type"], meta["default_bin"],
            meta["monotone"], meta["penalty"], fmask,
            min_constraint=min_c, max_constraint=max_c,
            acc_scale=acc, **split_kw)
        bf = jnp.argmax(pf.gain).astype(jnp.int32)
        res = finalize_split(pf, bf, sg, sh,
                             l1=params.l1, l2=params.l2,
                             max_delta_step=params.max_delta_step,
                             min_constraint=min_c, max_constraint=max_c)
        return res._replace(is_cat=jnp.asarray(False),
                            cat_mask=jnp.zeros(1, jnp.float32))

    vselect = jax.vmap(select_one,
                       in_axes=(0, 0, 0, 0, 0, 0, None, None, None))

    # ---- prep: quantization + packed stats + scalar sums --------------
    def prep(grad, hess, row_mask, w_blocks, key, mf, block_width):
        # per-row GOSS block weight: w_blocks[nbs] expanded by global
        # row -> block index (all-ones when GOSS is off, making every
        # product exact and the path bit-identical to resident)
        nbs = w_blocks.shape[0]
        w_row = w_blocks[jnp.minimum(
            jax.lax.iota(jnp.int32, n_pad) // jnp.int32(block_width),
            jnp.int32(nbs - 1))]
        mask = row_mask * (w_row > 0).astype(jnp.float32)
        g = grad * w_row * mask
        h = hess * w_row * mask
        if quantized:
            qmax = quant_limit(precision, n_pad)
            amax_g = jnp.max(jnp.abs(g))
            amax_h = jnp.max(jnp.abs(h))
            g_scale = jnp.maximum(amax_g, jnp.float32(1e-30)) / qmax
            h_scale = jnp.maximum(amax_h, jnp.float32(1e-30)) / qmax
            seed_a, seed_b = key_words(jax.random.fold_in(key, 0x5154))
            sto = mf[MF_STOCHASTIC]
            g_q = quantize_values(g, g_scale, qmax, "stochastic",
                                  seed_a, seed_b, 0, salt=0x9E3779B9,
                                  stochastic=sto)
            h_q = quantize_values(h, h_scale, qmax, "stochastic",
                                  seed_a, seed_b, 0, salt=0x85EBCA6B,
                                  stochastic=sto)
            qscale = jnp.stack([g_scale, h_scale, jnp.float32(1.0)])
            sum_g = (jnp.sum(g_q, dtype=jnp.int32).astype(jnp.float32)
                     * g_scale)
            sum_h = (jnp.sum(h_q, dtype=jnp.int32).astype(jnp.float32)
                     * h_scale)
            cnt = (jnp.sum(mask.astype(jnp.int32), dtype=jnp.int32)
                   .astype(jnp.float32))
            stats = pack_stats(g_q, h_q, mask, precision)
        else:
            sum_t = jnp.float64 if precision == "f64" else jnp.float32
            sum_g = jnp.sum(g, dtype=sum_t).astype(jnp.float32)
            sum_h = jnp.sum(h, dtype=sum_t).astype(jnp.float32)
            cnt = jnp.sum(mask, dtype=sum_t).astype(jnp.float32)
            qscale = jnp.ones(3, jnp.float32)  # unused placeholder
            stats = pack_stats(g, h, mask, precision)
        return stats, g, h, sum_g, sum_h, cnt, qscale

    # ---- per-block histogram programs ---------------------------------
    def root_block(acc, bins_blk, stats, row0):
        rows = bins_blk.shape[1]
        nbi, block = _hist_geometry(params, rows)
        S = stats.shape[0]
        bins_blocks = jnp.moveaxis(bins_blk.reshape(G, nbi, block), 1, 0)
        stats_blk = jax.lax.dynamic_slice(stats, (0, row0), (S, rows))
        stats_blocks = stats_blk.reshape(S, nbi, block)
        with jax.named_scope("hist_build"):
            # "fused" rides the same perfeature contraction here: the
            # streamed round body keeps its own partition/scan structure,
            # so fused degrades to pallas2-equivalent hist + the shared
            # select() — bit-identical by int32 associativity
            if params.hist_impl in ("pallas", "pallas2", "fused"):
                root_slots = jnp.full(K, -1, jnp.int32).at[0].set(0)
                part = build_histogram_batched_t(
                    bins_blocks, stats_blocks,
                    jnp.zeros((nbi, block), jnp.int32), root_slots, B,
                    precision, impl=params.hist_impl,
                    packed_rows=False)[0]
            else:
                part = build_histogram_t(bins_blocks, stats_blocks, B,
                                         precision)
        return acc + part

    def block_step(acc, leaf_ids, bins_blk, stats, row0,
                   sel, do_k, new_ids, smaller_ids,
                   sel_feat, sel_thr, sel_dleft, meta):
        """Partition this block's rows for the round's K splits, then
        accumulate their contribution to the K smaller-child histograms
        — the per-row math of the resident exec_round 'select' lowering,
        applied to the [rows] slice at row0."""
        rows = bins_blk.shape[1]
        nbi, block = _hist_geometry(params, rows)
        S = stats.shape[0]
        leaf_blk = jax.lax.dynamic_slice(leaf_ids, (row0,), (rows,))
        new_leaf = leaf_blk
        for k in range(K):
            f_k = sel_feat[k]
            col_k = jax.lax.dynamic_index_in_dim(bins_blk, f_k, 0,
                                                 keepdims=False)
            go_left_k = _numeric_go_left(
                col_k, meta["missing_type"][f_k],
                meta["num_bin"][f_k], meta["default_bin"][f_k],
                sel_thr[k], sel_dleft[k])
            in_k = (leaf_blk == sel[k]) & do_k[k]
            new_leaf = jnp.where(in_k & (~go_left_k), new_ids[k],
                                 new_leaf)
        leaf_ids = jax.lax.dynamic_update_slice(leaf_ids, new_leaf,
                                                (row0,))
        bins_blocks = jnp.moveaxis(bins_blk.reshape(G, nbi, block), 1, 0)
        stats_blk = jax.lax.dynamic_slice(stats, (0, row0), (S, rows))
        stats_blocks = stats_blk.reshape(S, nbi, block)
        with jax.named_scope("hist_build"):
            part = build_histogram_batched_t(
                bins_blocks, stats_blocks, new_leaf.reshape(nbi, block),
                smaller_ids, B, precision, impl=params.hist_impl,
                packed_rows=False)
        return acc + part, leaf_ids

    # ---- root finish: state init from the accumulated root hist -------
    def root_finish(acc, sum_g, sum_h, cnt, qscale, fmask, meta):
        root_hist = acc
        with jax.named_scope("split_search"):
            root_split = select_one(root_hist, sum_g, sum_h, cnt,
                                    -big, big, fmask, qscale, meta)
        state = {
            "pool": jnp.zeros((L, G, B, 3), hist_t).at[0].set(root_hist),
            "leaf_sum_g": jnp.zeros(L, jnp.float32).at[0].set(sum_g),
            "leaf_sum_h": jnp.zeros(L, jnp.float32).at[0].set(sum_h),
            "leaf_cnt": jnp.zeros(L, jnp.float32).at[0].set(cnt),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_output": jnp.zeros(L, jnp.float32).at[0].set(
                leaf_output(sum_g, sum_h, params.l1, params.l2,
                            params.max_delta_step)),
            "bs_gain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(
                root_split.gain),
            "bs_feat": jnp.zeros(L, jnp.int32).at[0].set(
                root_split.feature),
            "bs_thr": jnp.zeros(L, jnp.int32).at[0].set(
                root_split.threshold),
            "bs_dleft": jnp.zeros(L, jnp.bool_).at[0].set(
                root_split.default_left),
            "bs_lg": jnp.zeros(L, jnp.float32).at[0].set(
                root_split.left_sum_g),
            "bs_lh": jnp.zeros(L, jnp.float32).at[0].set(
                root_split.left_sum_h),
            "bs_lc": jnp.zeros(L, jnp.float32).at[0].set(
                root_split.left_count),
            "bs_lo": jnp.zeros(L, jnp.float32).at[0].set(
                root_split.left_output),
            "bs_ro": jnp.zeros(L, jnp.float32).at[0].set(
                root_split.right_output),
            "leaf_min": jnp.full(L, -1e30, jnp.float32),
            "leaf_max": jnp.full(L, 1e30, jnp.float32),
            "records": jnp.zeros((L - 1 + K, REC_WIDTH), jnp.float32),
            "n_splits": jnp.int32(0),
        }
        return state

    # ---- round head: top-K slot selection (pre-histogram) -------------
    def round_head(state):
        depth_ok = jnp.logical_or(
            params.max_depth <= 0,
            state["leaf_depth"] < params.max_depth)
        cand = jnp.where(depth_ok, state["bs_gain"], K_MIN_SCORE)
        cont = ((state["n_splits"] < L - 1) & (jnp.max(cand) > 0.0))
        vals, sel = jax.lax.top_k(cand, K)
        sel = sel.astype(jnp.int32)
        kar = jnp.arange(K, dtype=jnp.int32)
        budget = (L - 1) - state["n_splits"]
        do_k = (vals > 0.0) & (kar < budget)
        if params.split_batch_alpha > 0.0 and K > 1:
            alpha = min(params.split_batch_alpha, 0.999)
            do_k &= vals >= alpha * vals[0]
        new_ids = state["n_splits"] + 1 + kar
        lc = state["bs_lc"][sel]
        rc = state["leaf_cnt"][sel] - lc
        smaller_is_left = lc <= rc
        smaller_ids = jnp.where(
            do_k, jnp.where(smaller_is_left, sel, new_ids), -1)
        head = dict(
            cont=cont, sel=sel, vals=vals, do_k=do_k, new_ids=new_ids,
            smaller_ids=smaller_ids,
            sel_feat=state["bs_feat"][sel], sel_thr=state["bs_thr"][sel],
            sel_dleft=state["bs_dleft"][sel],
            lg=state["bs_lg"][sel], lh=state["bs_lh"][sel], lc=lc,
            lo=state["bs_lo"][sel], ro=state["bs_ro"][sel])
        acc0 = jnp.zeros((K, G, B, 3), hist_t)
        return head, acc0

    # ---- round update: everything after the histogram seam ------------
    def round_update(state, acc, sel, vals, do_k, new_ids,
                     sel_feat, sel_thr, sel_dleft,
                     lg, lh, lc, lo, ro, fmask, qscale, meta):
        num_do = jnp.sum(do_k, dtype=jnp.int32)
        pg = state["leaf_sum_g"][sel]
        ph = state["leaf_sum_h"][sel]
        pc = state["leaf_cnt"][sel]
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        smaller_is_left = lc <= rc
        hist_small = acc                              # [K, G, B, 3]
        parent_hist = state["pool"][sel]
        hist_large = parent_hist - hist_small
        sl = smaller_is_left[:, None, None, None]
        hist_left = jnp.where(sl, hist_small, hist_large)
        hist_right = jnp.where(sl, hist_large, hist_small)
        pool = _scatter_set(state["pool"], sel, hist_left, do_k)
        pool = _scatter_set(pool, new_ids, hist_right, do_k)

        p_min = state["leaf_min"][sel]
        p_max = state["leaf_max"][sel]
        mono_k = meta["monotone"][sel_feat]
        mid = (lo + ro) / 2.0
        l_min = jnp.where(mono_k < 0, mid, p_min)
        l_max = jnp.where(mono_k > 0, mid, p_max)
        r_min = jnp.where(mono_k > 0, mid, p_min)
        r_max = jnp.where(mono_k < 0, mid, p_max)

        new_state = dict(state)
        with jax.named_scope("split_search"):
            ch = vselect(
                jnp.concatenate([hist_left, hist_right], axis=0),
                jnp.concatenate([lg, rg]), jnp.concatenate([lh, rh]),
                jnp.concatenate([lc, rc]),
                jnp.concatenate([l_min, r_min]),
                jnp.concatenate([l_max, r_max]),
                fmask, qscale, meta)

        new_state["pool"] = pool
        for key_, li, ri in (("leaf_sum_g", lg, rg),
                             ("leaf_sum_h", lh, rh),
                             ("leaf_cnt", lc, rc), ("leaf_output", lo, ro),
                             ("leaf_min", l_min, r_min),
                             ("leaf_max", l_max, r_max)):
            arr = _scatter_set(new_state[key_], sel, li, do_k)
            new_state[key_] = _scatter_set(arr, new_ids, ri, do_k)
        d_child = state["leaf_depth"][sel] + 1
        d = _scatter_set(state["leaf_depth"], sel, d_child, do_k)
        new_state["leaf_depth"] = _scatter_set(d, new_ids, d_child, do_k)
        for key_, cv in (("bs_gain", ch.gain), ("bs_feat", ch.feature),
                         ("bs_thr", ch.threshold),
                         ("bs_dleft", ch.default_left),
                         ("bs_lg", ch.left_sum_g),
                         ("bs_lh", ch.left_sum_h),
                         ("bs_lc", ch.left_count),
                         ("bs_lo", ch.left_output),
                         ("bs_ro", ch.right_output)):
            arr = _scatter_set(new_state[key_], sel, cv[:K], do_k)
            new_state[key_] = _scatter_set(arr, new_ids, cv[K:], do_k)

        rec = jnp.stack([
            sel.astype(jnp.float32), sel_feat.astype(jnp.float32),
            sel_thr.astype(jnp.float32), sel_dleft.astype(jnp.float32),
            vals, lo, ro, lc, rc, lh, rh,
            state["leaf_output"][sel], ph, pc,
            do_k.astype(jnp.float32),
            jnp.zeros(K, jnp.float32)],                # REC_IS_CAT
            axis=1)                                    # [K, 16]
        new_state["records"] = jax.lax.dynamic_update_slice(
            state["records"], rec, (state["n_splits"], jnp.int32(0)))
        new_state["n_splits"] = state["n_splits"] + num_do
        return new_state

    # ---- finish: quantized leaf refit + out dict ----------------------
    def finish(state, leaf_ids, g, h, mf):
        leaf_out = state["leaf_output"]
        if quantized:
            refit_on = mf[MF_QUANT_REFIT]
            rg = jnp.zeros(L, jnp.float32).at[leaf_ids].add(g)
            rh = jnp.zeros(L, jnp.float32).at[leaf_ids].add(h)
            refit = jnp.clip(
                leaf_output(rg, rh + jnp.float32(2e-15), params.l1,
                            params.l2, params.max_delta_step),
                state["leaf_min"], state["leaf_max"])
            leaf_out = jnp.where(
                (state["leaf_cnt"] > 0) & (refit_on > 0),
                refit, leaf_out)
        return {
            "records": state["records"][:L - 1],
            "leaf_output": leaf_out,
            "leaf_cnt": state["leaf_cnt"],
            "leaf_sum_h": state["leaf_sum_h"],
        }

    # ---- replay: leaf ids for GOSS-skipped blocks ---------------------
    def replay_block(leaf_ids, bins_blk, records, row0, meta):
        rows = bins_blk.shape[1]
        leaf_blk = jax.lax.dynamic_slice(leaf_ids, (row0,), (rows,))

        def body(j, lb):
            rec = records[j]
            did = rec[REC_DID_SPLIT] > 0.5
            parent = rec[REC_LEAF].astype(jnp.int32)
            feat = rec[REC_FEATURE].astype(jnp.int32)
            thr = rec[REC_THRESHOLD].astype(jnp.int32)
            dleft = rec[REC_DEFAULT_LEFT] > 0.5
            col = jax.lax.dynamic_index_in_dim(bins_blk, feat, 0,
                                               keepdims=False)
            go_left = _numeric_go_left(
                col, meta["missing_type"][feat], meta["num_bin"][feat],
                meta["default_bin"][feat], thr, dleft)
            # record row j created leaf id j+1 (do_k is a prefix mask,
            # so records are contiguous and new_ids = n_splits + 1 + k)
            move = did & (lb == parent) & (~go_left)
            return jnp.where(move, jnp.int32(j) + 1, lb)

        lb = jax.lax.fori_loop(0, L - 1, body, leaf_blk)
        return jax.lax.dynamic_update_slice(leaf_ids, lb, (row0,))

    # ---- GOSS plan: block scores + uniforms ---------------------------
    def goss_plan(grad, hess, row_mask, key, w_len, block_width):
        # w_len/block_width are static (closure-free ints via
        # static_argnames): [nbs] per-block sum|g*h| over real rows, and
        # one PCG uniform per block keyed on its first GLOBAL row index
        v = jnp.abs(grad * hess) * row_mask
        bidx = jnp.minimum(
            jax.lax.iota(jnp.int32, n_pad) // jnp.int32(block_width),
            jnp.int32(w_len - 1))
        scores = jnp.zeros(w_len, jnp.float32).at[bidx].add(v)
        seed_a, seed_b = key_words(jax.random.fold_in(key, 0x51B5))
        starts = (jnp.arange(w_len, dtype=jnp.uint32)
                  * jnp.uint32(block_width))
        u = hashed_uniform(starts, seed_a, seed_b, 0x60553)
        return scores, u

    class _P:
        pass

    p = _P()
    p.prep = ledger_jit(prep, site="stream.prep",
                        static_argnames=("block_width",))
    p.root_block = ledger_jit(root_block, site="stream.root_block",
                              donate_argnums=(0,))
    p.block_step = ledger_jit(block_step, site="stream.block_step",
                              donate_argnums=(0, 1))
    p.root_finish = ledger_jit(root_finish, site="stream.root_finish")
    p.round_head = ledger_jit(round_head, site="stream.round_head")
    p.round_update = ledger_jit(round_update, site="stream.round_update",
                                donate_argnums=(0,))
    p.finish = ledger_jit(finish, site="stream.finish")
    p.replay_block = ledger_jit(replay_block, site="stream.replay_block",
                                donate_argnums=(0,))
    p.goss_plan = ledger_jit(goss_plan, site="stream.goss_plan",
                             static_argnames=("w_len", "block_width"))
    return p


class StreamGrower:
    """Host-loop driver for the streamed tree growth.

    Owns the per-block H2D schedule (double-buffered device slots), the
    GOSS block-sampling plan, and the per-tree overlap telemetry.  The
    compiled programs come from `_build_stream_programs` (memoized), so
    a ladder rebuild at the same shapes reuses the executables.
    """

    def __init__(self, params: GrowerParams, num_columns: int,
                 n_pad: int, stream_rows: int,
                 double_buffer: bool = True,
                 goss_top: float = 0.0, goss_other: float = 0.0):
        reason = stream_supported(params)
        if reason is not None:
            raise NotImplementedError(
                f"streamed training layout does not support {reason}; "
                "set tpu_stream_mode=resident")
        if stream_rows <= 0:
            raise ValueError(f"stream_rows={stream_rows} must be positive")
        self.params = params
        self.G = int(num_columns)
        self.n_pad = int(n_pad)
        self.R = min(int(stream_rows), self.n_pad)
        self.nbs = -(-self.n_pad // self.R)
        tail = self.n_pad - (self.nbs - 1) * self.R
        for rows in sorted({self.R, tail}):
            nbi, blk = _hist_geometry(params, rows)
            if nbi * blk != rows:
                raise ValueError(
                    f"stream block of {rows} rows does not decompose "
                    f"into whole histogram scan blocks "
                    f"(block_rows={params.block_rows}); use "
                    "resolve_stream_rows() to size tpu_stream_block_rows")
        self.double_buffer = bool(double_buffer)
        self.goss_top = float(goss_top)
        self.goss_other = float(goss_other)
        self.goss_on = self.goss_top > 0.0 or self.goss_other > 0.0
        self._progs = _build_stream_programs(params, self.G, self.n_pad)
        # per-tree telemetry, harvested by the learner / bench / probes
        self.last_stats: Dict[str, float] = {}
        self._h2d_rate: Optional[float] = None  # seconds per byte

    # ------------------------------------------------------------------
    def _block_bounds(self, i: int):
        row0 = i * self.R
        return row0, min(self.R, self.n_pad - row0)

    def _goss_weights(self, grad, hess, row_mask, key):
        """Host-side GOSS block plan from device scores/uniforms:
        weights [nbs] (0 = skipped), deterministic given the key (the
        uniforms hash each block's first GLOBAL row index, the ordering
        tie-break is the stable block index)."""
        scores, u = self._progs.goss_plan(grad, hess, row_mask, key,
                                          w_len=self.nbs,
                                          block_width=self.R)
        scores = np.asarray(scores)
        u = np.asarray(u)
        nbs = self.nbs
        top_k = int(np.ceil(self.goss_top * nbs)) if self.goss_top > 0 \
            else 0
        order = np.argsort(-scores, kind="stable")
        w = np.zeros(nbs, np.float32)
        top = order[:top_k]
        w[top] = 1.0
        rest = order[top_k:]
        if self.goss_other > 0 and len(rest):
            amp = (1.0 - self.goss_top) / self.goss_other
            picked = rest[u[rest] < self.goss_other]
            w[picked] = np.float32(amp)
        if not (w > 0).any():
            # degenerate fractions: always stream at least the
            # highest-scored block, or the tree would see zero rows
            w[order[0]] = 1.0
        return w

    def _stream_blocks(self, host_blocks: List[np.ndarray], indices,
                       consume):
        """Drive `consume(i, dev_block, row0)` over the selected blocks
        with (optionally) double-buffered H2D: block i+1's device_put is
        issued before block i's result is consumed, so on accelerators
        with async transfers the copy rides under the previous block's
        histogram contraction.  Records per-block copy/stall walls for
        the overlap estimate."""
        indices = list(indices)
        if not indices:
            return
        puts = {}

        def _put(i):
            t0 = time.perf_counter()
            dev = jax.device_put(host_blocks[i])
            if not self.double_buffer:
                dev.block_until_ready()
            return dev, time.perf_counter() - t0, host_blocks[i].nbytes

        # calibrate the copy wall on the first block (nothing to overlap
        # with there anyway): a synchronous timed put
        i0 = indices[0]
        t0 = time.perf_counter()
        with obs.span("stream_h2d", block=i0,
                      bytes=int(host_blocks[i0].nbytes)):
            dev0 = jax.device_put(host_blocks[i0])
            dev0.block_until_ready()
        wall0 = time.perf_counter() - t0
        if host_blocks[i0].nbytes:
            self._h2d_rate = wall0 / host_blocks[i0].nbytes
        puts[i0] = (dev0, wall0, host_blocks[i0].nbytes)
        self._t_h2d += wall0
        self._copy_est += wall0

        for pos, i in enumerate(indices):
            if self.double_buffer and pos + 1 < len(indices):
                nxt = indices[pos + 1]
                if nxt not in puts:
                    with obs.span("stream_h2d", block=nxt,
                                  bytes=int(host_blocks[nxt].nbytes)):
                        puts[nxt] = _put(nxt)
            if i not in puts:
                with obs.span("stream_h2d", block=i,
                              bytes=int(host_blocks[i].nbytes)):
                    puts[i] = _put(i)
            dev, issue_wall, nbytes = puts.pop(i)
            if pos > 0:
                est = (nbytes * self._h2d_rate if self._h2d_rate
                       else issue_wall)
                t_w = time.perf_counter()
                dev.block_until_ready()
                stall = time.perf_counter() - t_w
                if not self.double_buffer:
                    # serial copies: the full copy wall was paid at the
                    # put — nothing was hidden by construction
                    stall = est
                self._copy_est += est
                self._hidden += max(0.0, est - stall)
                self._t_h2d += stall + issue_wall
            row0, _rows = self._block_bounds(i)
            consume(i, dev, row0)

    # ------------------------------------------------------------------
    def grow(self, host_blocks: List[np.ndarray], grad, hess, row_mask,
             feature_mask, meta, key):
        """Grow one tree over the host-resident blocked bin matrix.

        host_blocks: [G, rows_i] C-contiguous host arrays (rows_i = R
        except the final partial block).  Returns the resident grower's
        out dict (records / leaf_ids / leaf_output / leaf_cnt /
        leaf_sum_h)."""
        P = self._progs
        t_tree = time.perf_counter()
        self._t_h2d = 0.0
        self._copy_est = 0.0
        self._hidden = 0.0

        if self.goss_on:
            w = self._goss_weights(grad, hess, row_mask, key)
        else:
            w = np.ones(self.nbs, np.float32)
        sampled = [i for i in range(self.nbs) if w[i] > 0]
        skipped = [i for i in range(self.nbs) if w[i] <= 0]

        stats, g, h, sum_g, sum_h, cnt, qscale = P.prep(
            grad, hess, row_mask, jnp.asarray(w), key,
            meta["mode_flags"], block_width=self.R)

        # ---- root histogram over the sampled blocks ----
        acc = jnp.zeros((self.G, self.params.num_bins, 3),
                        pool_dtype(self.params.precision))
        t_hist = time.perf_counter()
        with obs.span("hist_build", streamed=True, phase="root"):
            box = {"acc": acc}

            def root_consume(i, dev, row0):
                with obs.span("stream_block", block=i):
                    box["acc"] = P.root_block(box["acc"], dev, stats,
                                              jnp.int32(row0))

            self._stream_blocks(host_blocks, sampled, root_consume)
            acc = box["acc"]
        state = P.root_finish(acc, sum_g, sum_h, cnt, qscale,
                              feature_mask, meta)
        leaf_ids = jnp.zeros(self.n_pad, jnp.int32)

        # ---- rounds: one host sync per round on the cont scalar ----
        rounds = 0
        while True:
            head, acc_k = P.round_head(state)
            if not bool(head["cont"]):
                break
            rounds += 1
            with obs.span("hist_build", streamed=True, round=rounds):
                box = {"acc": acc_k, "leaf_ids": leaf_ids}

                def round_consume(i, dev, row0):
                    with obs.span("stream_block", block=i):
                        box["acc"], box["leaf_ids"] = P.block_step(
                            box["acc"], box["leaf_ids"], dev, stats,
                            jnp.int32(row0), head["sel"], head["do_k"],
                            head["new_ids"], head["smaller_ids"],
                            head["sel_feat"], head["sel_thr"],
                            head["sel_dleft"], meta)

                self._stream_blocks(host_blocks, sampled, round_consume)
                acc_k, leaf_ids = box["acc"], box["leaf_ids"]
            state = P.round_update(
                state, acc_k, head["sel"], head["vals"], head["do_k"],
                head["new_ids"], head["sel_feat"], head["sel_thr"],
                head["sel_dleft"], head["lg"], head["lh"], head["lc"],
                head["lo"], head["ro"], feature_mask, qscale, meta)
        t_hist = time.perf_counter() - t_hist

        out = dict(P.finish(state, leaf_ids, g, h, meta["mode_flags"]))

        # ---- GOSS-skipped blocks: one replay partition pass each ----
        if skipped:
            box = {"leaf_ids": leaf_ids}

            def replay_consume(i, dev, row0):
                with obs.span("stream_block", block=i, replay=True):
                    box["leaf_ids"] = P.replay_block(
                        box["leaf_ids"], dev, out["records"],
                        jnp.int32(row0), meta)

            with obs.span("hist_build", streamed=True, phase="replay"):
                self._stream_blocks(host_blocks, skipped, replay_consume)
            leaf_ids = box["leaf_ids"]
        out["leaf_ids"] = leaf_ids

        wall = time.perf_counter() - t_tree
        overlap = (100.0 * self._hidden / self._copy_est
                   if self._copy_est > 0 else 0.0)
        self.last_stats = {
            "tree_wall_s": wall,
            "h2d_wall_s": self._t_h2d,
            "hist_wall_s": max(t_hist - self._t_h2d, 0.0),
            "copy_est_s": self._copy_est,
            "overlap_pct": overlap,
            "rounds": float(rounds),
            "blocks_streamed": float(len(sampled)),
            "blocks_skipped": float(len(skipped)),
            "rows_per_sec": (self.n_pad * max(rounds, 1)) / max(wall,
                                                                1e-9),
        }
        obs.event("stream_tree", **self.last_stats)
        return out


def resolve_stream_rows(cfg_rows: int, n_pad: int, bytes_per_row: int,
                        inner_block: int,
                        budget_bytes: Optional[int] = None) -> int:
    """Resolve tpu_stream_block_rows to the actual stream-block width.

    The width is a multiple of the grower's inner histogram scan block
    (so per-block programs reuse the resident contraction geometry and
    the tail block stays a whole number of scan blocks), clamped to
    [inner_block, n_pad].  cfg_rows=0 = auto: two device slots sized to
    fit under ~1/8 of the HBM budget, floored at 64k rows.
    """
    b0 = max(1, min(int(inner_block), int(n_pad)))
    if cfg_rows > 0:
        r = int(cfg_rows)
    else:
        r = 65536
        if budget_bytes and bytes_per_row > 0:
            r = max(r, int((budget_bytes // 8) // (2 * bytes_per_row)))
    r = min(max(r, b0), int(n_pad))
    return max(r // b0, 1) * b0


def make_host_blocks(bins_t: np.ndarray, stream_rows: int
                     ) -> List[np.ndarray]:
    """Partition a host [G, n_pad] transposed bin matrix into
    C-contiguous per-block [G, rows_i] arrays (the H2D unit: contiguous
    blocks device_put without a host-side gather).  Works for plain
    ndarrays and np.memmap sources (the PR-3 chunked-ingest layout) —
    each block materializes at most G * stream_rows bytes."""
    G, n_pad = bins_t.shape
    out = []
    for row0 in range(0, n_pad, stream_rows):
        out.append(np.ascontiguousarray(
            bins_t[:, row0:row0 + stream_rows]))
    return out
