"""Fused frontier growth: the grow megakernel and the partition kernel.

The per-iteration critical path used to be several XLA ops with HBM
round-trips between them: the batched histogram contraction writes the
[K, F, B, 3] smaller-child histograms to HBM, the sibling subtraction
reads them back next to the pool, and the split scan reads the children
again to run its bin cumsums.  This module fuses the frontier step into
ONE Pallas kernel (`fused_hist_scan`):

* the per-feature one-hot MXU accumulation of ops/histogram.py's
  "perfeature" kernel runs unchanged over the row-block grid, its
  accumulator resident in VMEM;
* at the LAST row block — while the finished accumulator is still in
  VMEM — the kernel subtracts each slot's block from the parent's pooled
  histogram (sibling subtraction) and runs the split gain scan
  (ops/split.py per_feature_best_split, pure jnp, traced into the kernel
  body) over every child's bins, emitting per-feature best
  `(gain, threshold, default_left, left stats)` records directly;
* the grower's `select()` consumes those flat f32 records
  (split.pack_pf_records layout) instead of dequantized histograms, so
  split search never leaves the device and the full child histograms
  never round-trip to HBM for the scan.

The in-kernel scan is restricted to the QUANTIZED precisions (int8 /
int16) on the serial learner: int32 bin cumsums are exact and
reassociation-proof, and the f32 gain math after the dequantize boundary
is the same exactly-rounded elementwise code the XLA path runs — so
fused and unfused model files are byte-identical (the acceptance gate
tests/test_fused_grow.py enforces).  Float precisions and sharded
learners fall back to the plain perfeature histogram kernel + the
existing device-side `select()` (still one compiled grow program; only
the scan fusion is forgone).

`partition_rows` is the row→leaf scatter kernel (tpu_partition_impl=
"kernel"): the K-way frontier partition as one VMEM pass over the row
blocks, mirroring the "vselect" lowering's integer math bit-for-bit
(split.numeric_go_left is the shared decision function).

Runtime validation (`mosaic_int16_ok` / `fused_scan_ok`): Mosaic support
for int16 MXU dots and for the traced scan body differs across TPU
generations, so `auto` resolution never *assumes* — it runs a tiny eager
probe (un-jitted: invisible to the compile ledger) against the XLA
reference and falls back LOUDLY on exception or mismatch.  On CPU the
kernels run in interpret mode (plain jnp) and the probes pass trivially.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import (_INT_STAT_DTYPES, _dot_spec, _unpack_hist,
                        bench_hist_operands, build_histogram_batched_t)
from .split import (PF_RECORD_WIDTH, pack_pf_records, numeric_go_left,
                    per_feature_best_split, unpack_pf_records)

LOG = logging.getLogger("lightgbm_tpu.fused")

# VMEM budget for the fused kernel's resident blocks (accumulator +
# parent histograms + records); smaller than the plain perfeature
# kernel's budget because the parent block doubles the residency
_FUSED_OUT_BUDGET = 4 * 1024 * 1024

# ctx-row column layout (see `fused_hist_scan` child_ctx)
CTX_SUM_G, CTX_SUM_H, CTX_COUNT, CTX_MIN_C, CTX_MAX_C, CTX_USE_SMALL = \
    range(6)


def fused_supported(precision: str, *, data_axis=None, feature_axis=None,
                    voting_k: int = 0, bynode: bool = False,
                    has_cat: bool = False, has_bundles: bool = False,
                    has_sparse: bool = False, has_cegb: bool = False,
                    forced: bool = False, packed_bins: bool = False):
    """Reason the in-kernel split scan cannot engage, or None if it can.

    The grower computes the same predicate structurally; this helper
    exists so the learner/autotuner can explain a fallback to the user
    instead of silently degrading."""
    if precision not in _INT_STAT_DTYPES:
        return (f"precision={precision!r} (the in-kernel scan needs the "
                "exact int32 accumulation of int8/int16)")
    if data_axis is not None or feature_axis is not None or voting_k:
        return "sharded learner (aggregation must precede the scan)"
    if bynode:
        return "feature_fraction_bynode (per-node masks)"
    for flag, name in ((has_cat, "categorical splits"),
                       (has_bundles, "EFB bundling"),
                       (has_sparse, "sparse storage"),
                       (has_cegb, "CEGB"),
                       (forced, "forced splits"),
                       (packed_bins, "packed 4-bit bins")):
        if flag:
            return name
    return None


def fused_hist_scan(bins_t_blocks, stats_blocks, leaf_blocks,
                    slot_leaf_ids, parent_hist, child_ctx, meta_i, meta_f,
                    num_bins: int, precision: str, *, split_kw: dict):
    """The grow megakernel: histograms + sibling subtraction + split scan.

    bins_t_blocks: [nb, F, block] integer bins
    stats_blocks:  [S, nb, block] packed int stats (S == 3)
    leaf_blocks:   [nb, block] int32 current leaf per row
    slot_leaf_ids: [K] int32 smaller-child leaf per slot (-1 = dead)
    parent_hist:   [K, F, B, 3] int32 pooled parent histograms
    child_ctx:     [2K+1, 8] f32 — row j < 2K is child j's
        (sum_g, sum_h, count, min_constraint, max_constraint, use_small)
        where children are ordered [left 0..K-1, right 0..K-1] like the
        grower's vselect concatenation and use_small > 0 means the child
        is the freshly-histogrammed (smaller) sibling; row 2K carries the
        dequantization scales (g_scale, h_scale, 1.0).
    meta_i: [F, 8] int32 — cols (num_bin, missing_type, default_bin,
        monotone); meta_f: [F, 8] f32 — cols (penalty, feature_mask).
    split_kw: the six static split scalars for per_feature_best_split.

    Returns (hist [K, F, B, 3] int32 smaller-child histograms — identical
    to the perfeature kernel's output, for the pool update — and records
    [2K, F, PF_RECORD_WIDTH] f32 per-child per-feature best splits).
    """
    from jax.experimental import pallas as pl

    nb, F, block = bins_t_blocks.shape
    S = stats_blocks.shape[0]
    K = slot_leaf_ids.shape[0]
    B = num_bins
    C = 2 * K
    if S != 3 or precision not in _INT_STAT_DTYPES:
        raise ValueError("the fused scan requires quantized [3, n] stats")
    Bp = -(-B // 8) * 8
    dot_dtype, acc_dtype, dot_prec = _dot_spec(precision)
    RW = PF_RECORD_WIDTH

    # parent histograms pre-shaped to the kernel's flat accumulator
    # layout [F*Bp, K*3] so the in-VMEM subtraction is a plain slice
    par = jnp.transpose(parent_hist.astype(acc_dtype), (1, 2, 0, 3))
    if Bp != B:
        par = jnp.pad(par, ((0, 0), (0, Bp - B), (0, 0), (0, 0)))
    par_flat = par.reshape(F * Bp, K * 3)

    # feature chunking mirrors the perfeature kernel: largest divisor of
    # F whose resident blocks (accumulator + parent) fit the budget
    ks_pad = -(-(K * S) // 128) * 128
    step = {1: 32, 2: 16, 4: 8}[bins_t_blocks.dtype.itemsize]

    def fits(c):
        return c * Bp * (ks_pad + K * 3) * 4 <= _FUSED_OUT_BUDGET

    fblk = F
    if not fits(F):
        cands = [c for c in range(step, F, step)
                 if F % c == 0 and fits(c)]
        if cands:
            fblk = max(cands)
    nf = F // fblk
    kw = dict(split_kw)

    def kernel(bins_ref, stats_ref, leaf_ref, slots_ref, par_ref,
               ctx_ref, mi_ref, mf_ref, out_ref, rec_ref):
        i = pl.program_id(1)  # row-block axis (innermost)
        # ---- accumulate: identical math to the perfeature kernel ----
        s = stats_ref[0]                            # [S, blk]
        l = leaf_ref[0]                             # [1, blk] i32
        slots = slots_ref[:]                        # [K, 1] i32
        slot_oh = (slots == l).astype(dot_dtype)
        sexp = (slot_oh[:, None, :] * s[None, :, :].astype(dot_dtype))
        sexp = sexp.reshape(K * S, block)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (Bp, block), 0)
        for f in range(fblk):
            b_f = bins_ref[0, f].astype(jnp.int32)
            onehot = (b_f[None, :] == iota_b).astype(dot_dtype)
            acc = jax.lax.dot_general(
                onehot, sexp, (((1,), (1,)), ((), ())),
                precision=dot_prec, preferred_element_type=acc_dtype)

            @pl.when(i == 0)
            def _(f=f, acc=acc):
                out_ref[f * Bp:(f + 1) * Bp, :] = acc

            @pl.when(i > 0)
            def _(f=f, acc=acc):
                out_ref[f * Bp:(f + 1) * Bp, :] += acc

        @pl.when(i == 0)
        def _():
            rec_ref[...] = jnp.zeros_like(rec_ref[...])

        # ---- device-resident split search at the final row block ----
        # (the accumulator just completed and is still in VMEM: sibling
        # subtraction + the bin gain scan run here, never touching HBM)
        @pl.when(i == nb - 1)
        def _():
            accs = out_ref[...].reshape(fblk, Bp, K * S)
            parb = par_ref[...].reshape(fblk, Bp, K, 3)
            qs = jnp.stack([ctx_ref[C, 0], ctx_ref[C, 1], ctx_ref[C, 2]])
            nbin = mi_ref[:, 0]
            mtyp = mi_ref[:, 1]
            dbin = mi_ref[:, 2]
            mono = mi_ref[:, 3]
            pen = mf_ref[:, 0]
            fmask = mf_ref[:, 1]
            for j in range(C):
                k = j % K
                small = accs[:, :B, k * S:(k + 1) * S]   # [fblk, B, 3]
                large = parb[:, :B, k, :] - small
                hs = jnp.where(ctx_ref[j, CTX_USE_SMALL] > 0, small, large)
                pf = per_feature_best_split(
                    hs, ctx_ref[j, CTX_SUM_G], ctx_ref[j, CTX_SUM_H],
                    ctx_ref[j, CTX_COUNT], nbin, mtyp, dbin, mono, pen,
                    fmask, min_constraint=ctx_ref[j, CTX_MIN_C],
                    max_constraint=ctx_ref[j, CTX_MAX_C],
                    acc_scale=qs, **kw)
                rec_ref[:, j * RW:(j + 1) * RW] = pack_pf_records(pf)

    interpret = jax.devices()[0].platform not in ("tpu",)
    stats_nb = jnp.moveaxis(stats_blocks, 1, 0)
    raw, recs = pl.pallas_call(
        kernel,
        grid=(nf, nb),
        in_specs=[
            pl.BlockSpec((1, fblk, block), lambda fi, i: (i, fi, 0)),
            pl.BlockSpec((1, S, block), lambda fi, i: (i, 0, 0)),
            pl.BlockSpec((1, 1, block), lambda fi, i: (i, 0, 0)),
            pl.BlockSpec((K, 1), lambda fi, i: (0, 0)),
            pl.BlockSpec((fblk * Bp, K * 3), lambda fi, i: (fi, 0)),
            pl.BlockSpec((C + 1, 8), lambda fi, i: (0, 0)),
            pl.BlockSpec((fblk, 8), lambda fi, i: (fi, 0)),
            pl.BlockSpec((fblk, 8), lambda fi, i: (fi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((fblk * Bp, K * S), lambda fi, i: (fi, 0)),
            pl.BlockSpec((fblk, C * RW), lambda fi, i: (fi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((F * Bp, K * S), acc_dtype),
            jax.ShapeDtypeStruct((F, C * RW), jnp.float32),
        ],
        interpret=interpret,
    )(bins_t_blocks, stats_nb, leaf_blocks.reshape(nb, 1, block),
      slot_leaf_ids.reshape(K, 1), par_flat, child_ctx,
      meta_i, meta_f)
    raw = jnp.transpose(raw.reshape(F, Bp, K, S)[:, :B], (2, 3, 0, 1))
    raw = raw.reshape(K, S, F * B)
    hist = jax.vmap(lambda r: _unpack_hist(r, precision))(raw)
    hist = hist.reshape(K, F, B, 3)
    records = jnp.transpose(recs.reshape(F, C, RW), (1, 0, 2))
    return hist, records


def partition_rows(cols, leaf_ids, sel, new_ids, thr, dleft, mt, nbf, db,
                   do_k, nb: int, block: int):
    """Row→leaf partition kernel (tpu_partition_impl="kernel").

    One VMEM pass over the row blocks replaces the partition's separate
    XLA program points: each block evaluates all K split decisions
    vectorized ([K, blk] broadcast of the per-slot scalars) and resolves
    each row's unique destination with a max-reduce — the exact integer
    math of the "vselect" lowering, so the two are bit-identical.

    cols:     [K, n_pad] int32 — the chosen features' bin columns
              (gathered by the caller; plain dense storage only)
    leaf_ids: [n_pad] int32 current assignment
    sel/new_ids/thr: [K] i32; dleft/do_k: [K] bool; mt/nbf/db: [K] i32
    Returns the updated [n_pad] int32 leaf ids.
    """
    from jax.experimental import pallas as pl

    K = cols.shape[0]
    n_pad = leaf_ids.shape[0]
    ints = jnp.stack(
        [sel, new_ids, thr, dleft.astype(jnp.int32), mt, nbf, db,
         do_k.astype(jnp.int32)], axis=1).astype(jnp.int32)  # [K, 8]

    def kernel(cols_ref, ints_ref, leaf_ref, out_ref):
        cb = cols_ref[...]                       # [K, blk]
        li = leaf_ref[...]                       # [1, blk]
        p_sel = ints_ref[:, 0:1]
        p_new = ints_ref[:, 1:2]
        p_thr = ints_ref[:, 2:3]
        p_dl = ints_ref[:, 3:4] > 0
        p_mt = ints_ref[:, 4:5]
        p_nb = ints_ref[:, 5:6]
        p_db = ints_ref[:, 6:7]
        p_do = ints_ref[:, 7:8] > 0
        go_left = numeric_go_left(cb, p_mt, p_nb, p_db, p_thr, p_dl)
        move = (li == p_sel) & p_do & (~go_left)          # [K, blk]
        moved = jnp.max(jnp.where(move, p_new, -1), axis=0,
                        keepdims=True)                    # [1, blk]
        out_ref[...] = jnp.where(moved >= 0, moved, li)

    interpret = jax.devices()[0].platform not in ("tpu",)
    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((K, block), lambda i: (0, i)),
            pl.BlockSpec((K, 8), lambda i: (0, 0)),
            pl.BlockSpec((1, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(cols.astype(jnp.int32), ints, leaf_ids.reshape(1, n_pad))
    return out.reshape(n_pad)


# --------------------------------------------------------------------------
# Runtime (hardware) validation probes — eager, tiny, invisible to the
# compile ledger; memoized so each backend pays once per process
# --------------------------------------------------------------------------

def _probe_operands(precision: str, seed: int = 0):
    """Tiny deterministic operands shared by the validation probes."""
    rng = np.random.default_rng(seed)
    n, F, B, block = 256, 8, 16, 128
    bins_np = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    bins_tb, stats, _ = bench_hist_operands(bins_np, precision, block,
                                            seed=seed)
    nb = n // block
    leaf_np = rng.integers(0, 2, size=(nb, block)).astype(np.int32)
    return bins_tb, stats, jnp.asarray(leaf_np), F, B, nb, block


@functools.lru_cache(maxsize=4)
def mosaic_int16_ok() -> bool:
    """Hardware-validate the Mosaic int16 histogram dot.

    Compares the pallas2 perfeature kernel's int16 contraction against
    the XLA reference on tiny operands, eagerly (no jit → no ledger
    site).  int32 accumulation is exact, so anything but bitwise
    equality means the backend mis-lowers the int16 dot and auto must
    keep pinning int16 to XLA there.  On CPU the kernel runs in
    interpret mode and the probe passes trivially; on TPU it is a true
    Mosaic compile + execute check."""
    try:
        bins_tb, stats, leaf, F, B, nb, block = _probe_operands("int16")
        slots = jnp.full(4, -1, jnp.int32).at[0].set(0).at[1].set(1)
        ref = build_histogram_batched_t(bins_tb, stats, leaf, slots, B,
                                        "int16", impl="xla")
        got = build_histogram_batched_t(bins_tb, stats, leaf, slots, B,
                                        "int16", impl="pallas2")
        ok = bool(jnp.array_equal(ref, got))
    except Exception as exc:  # Mosaic validation/compile failure
        LOG.warning(
            "mosaic int16 probe FAILED (%s: %s) — tpu_hist_impl=auto "
            "keeps int16 pinned to the XLA contraction on this backend",
            type(exc).__name__, exc)
        return False
    if not ok:
        LOG.warning(
            "mosaic int16 probe MISMATCHED the XLA reference — "
            "tpu_hist_impl=auto keeps int16 pinned to XLA on this backend")
    return ok


@functools.lru_cache(maxsize=8)
def fused_scan_ok(precision: str = "int8") -> bool:
    """Validate the fused kernel's in-kernel split scan on this backend.

    Runs `fused_hist_scan` eagerly on tiny operands and compares its
    records bitwise against the reference composition (XLA batched
    histograms → sibling subtraction → per_feature_best_split).  A
    Mosaic lowering failure (the traced scan uses 1-D iota/gather
    patterns some TPU generations reject) or any f32 divergence returns
    False, and auto resolution falls back — loudly — to the plain
    perfeature kernel + device select()."""
    try:
        bins_tb, stats, leaf, F, B, nb, block = _probe_operands(precision)
        K = 2
        slots = jnp.asarray([0, 1], jnp.int32)
        # reference smaller-child histograms + a synthetic parent pool
        small_ref = build_histogram_batched_t(bins_tb, stats, leaf, slots,
                                              B, precision, impl="xla")
        total = jnp.sum(small_ref, axis=0)
        parent = jnp.broadcast_to(total, small_ref.shape) * 2
        qs = jnp.asarray([0.5, 0.25, 1.0], jnp.float32)
        C = 2 * K
        ctx = np.zeros((C + 1, 8), np.float32)
        for j in range(C):
            ctx[j] = [1.0 + j, 2.0 + j, 128.0, -1e30, 1e30,
                      1.0 if j % 2 == 0 else 0.0, 0.0, 0.0]
        ctx[C, :3] = np.asarray(qs)
        ctx = jnp.asarray(ctx)
        meta_i = jnp.stack(
            [jnp.full(F, B, jnp.int32), jnp.zeros(F, jnp.int32),
             jnp.zeros(F, jnp.int32), jnp.zeros(F, jnp.int32)]
            + [jnp.zeros(F, jnp.int32)] * 4, axis=1)
        meta_f = jnp.stack(
            [jnp.ones(F, jnp.float32), jnp.ones(F, jnp.float32)]
            + [jnp.zeros(F, jnp.float32)] * 6, axis=1)
        kw = dict(l1=0.0, l2=1.0, max_delta_step=0.0,
                  min_data_in_leaf=1.0, min_sum_hessian=1e-3,
                  min_gain_to_split=0.0)
        hist, recs = fused_hist_scan(
            bins_tb, stats, leaf, slots, parent, ctx, meta_i, meta_f,
            B, precision, split_kw=kw)
        if not bool(jnp.array_equal(hist, small_ref)):
            raise AssertionError("fused histogram != XLA reference")
        for j in range(C):
            k = j % K
            hs = jnp.where(ctx[j, CTX_USE_SMALL] > 0, small_ref[k],
                           parent[k] - small_ref[k])
            pf = per_feature_best_split(
                hs, ctx[j, CTX_SUM_G], ctx[j, CTX_SUM_H],
                ctx[j, CTX_COUNT], meta_i[:, 0], meta_i[:, 1],
                meta_i[:, 2], meta_i[:, 3], meta_f[:, 0], meta_f[:, 1],
                min_constraint=ctx[j, CTX_MIN_C],
                max_constraint=ctx[j, CTX_MAX_C], acc_scale=qs, **kw)
            if not bool(jnp.array_equal(recs[j], pack_pf_records(pf))):
                raise AssertionError(f"fused records diverge (child {j})")
        return True
    except Exception as exc:
        LOG.warning(
            "fused grow-scan probe FAILED (%s: %s) — falling back to the "
            "perfeature histogram kernel + device select() on this "
            "backend", type(exc).__name__, exc)
        return False


def children_from_records(records, finalize):
    """[2K, F, RW] records → batched SplitResult via the caller-supplied
    per-child finalizer (the grower binds its static split scalars and
    constraint bounds there).  Split out for the oracle test's reuse."""
    return jax.vmap(finalize)(records)


__all__ = [
    "CTX_SUM_G", "CTX_SUM_H", "CTX_COUNT", "CTX_MIN_C", "CTX_MAX_C",
    "CTX_USE_SMALL", "children_from_records", "fused_hist_scan",
    "fused_scan_ok", "fused_supported", "mosaic_int16_ok",
    "partition_rows", "unpack_pf_records",
]
