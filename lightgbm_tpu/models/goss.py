"""GOSS: gradient-based one-side sampling (reference src/boosting/goss.hpp).

Keep the `top_rate` fraction of rows with the largest sum_k |g*h|, sample
`other_rate` of the rest and upscale their grad/hess by (1-a)/b
(reference goss.hpp:91-139), after a warm-up of 1/learning_rate full
iterations (goss.hpp:144).

TPU-first: the sampling runs INSIDE the fused device train step (top-k by
sort + Bernoulli keep, see learner.make_train_step) — no host round trip.
The reference's exact without-replacement draw of other_k rows becomes a
Bernoulli keep with the same expectation (XLA-friendly; no sequential
rejection loop).  Renew-objectives fall back to the host path below.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from .gbdt import GBDT


class GOSS(GBDT):
    def init(self, config, train_data) -> None:
        top_rate = float(config.top_rate)
        other_rate = float(config.other_rate)
        if top_rate + other_rate > 1.0:
            raise ValueError("top_rate + other_rate must be <= 1.0 for GOSS")
        if top_rate <= 0.0 or other_rate <= 0.0:
            raise ValueError("top_rate and other_rate must be > 0 for GOSS")
        if int(config.bagging_freq) > 0 and float(config.bagging_fraction) != 1.0:
            raise ValueError("Cannot use bagging in GOSS")
        lr = float(config.learning_rate)
        self._goss_cfg = {
            "top_rate": top_rate,
            "other_rate": other_rate,
            "warmup": int(1.0 / lr) if lr > 0 else 0,
        }
        super().init(config, train_data)
        self._goss_rng = np.random.default_rng(int(config.bagging_seed))

    def _goss_host(self, grad: np.ndarray, hess: np.ndarray):
        """Host-side GOSS for the sync path (renew/host-only objectives).

        grad/hess: [k, n] numpy.  Returns (grad', hess', row_mask f32[n])."""
        n = grad.shape[1]
        gh = np.abs(grad * hess).sum(axis=0)
        top_k = max(1, int(n * self._goss_cfg["top_rate"]))
        other_k = max(1, int(n * self._goss_cfg["other_rate"]))
        thr = np.partition(gh, n - top_k)[n - top_k]
        keep_top = gh >= thr
        rest = np.flatnonzero(~keep_top)
        sampled = self._goss_rng.choice(
            rest, size=min(other_k, len(rest)), replace=False)
        multiply = (n - top_k) / other_k
        mask = keep_top.copy()
        mask[sampled] = True
        grad = grad.copy()
        hess = hess.copy()
        grad[:, sampled] *= multiply
        hess[:, sampled] *= multiply
        return grad, hess, mask.astype(np.float32)

    def _train_one_iter_sync(self, grad=None, hess=None) -> bool:
        # mirror GBDT sync path but inject GOSS sampling after gradients
        if grad is not None or hess is not None:
            return super()._train_one_iter_sync(grad, hess)
        init_scores = [0.0] * self.num_tree_per_iteration
        for k in range(self.num_tree_per_iteration):
            init_scores[k] = self._boost_from_average(k)
        import jax
        g, h = self.objective.get_gradients(self.train_scores.scores)
        g = np.asarray(jax.device_get(g), np.float32).reshape(
            self.num_tree_per_iteration, -1)
        h = np.asarray(jax.device_get(h), np.float32).reshape(
            self.num_tree_per_iteration, -1)
        mask = None
        if self.iter_ >= self._goss_cfg["warmup"]:
            g, h, mask_np = self._goss_host(g, h)
            mask = jnp.asarray(mask_np)

        self._materialize()
        should_continue = False
        from .gbdt import K_EPSILON
        from .tree import Tree
        for k in range(self.num_tree_per_iteration):
            need = (self.objective is None
                    or self.objective.class_need_train(k))
            tree = None
            if need:
                tree, leaf_ids, out = self.learner.train(
                    jnp.asarray(g[k]), jnp.asarray(h[k]), mask)
            if tree is not None and tree.num_leaves > 1:
                should_continue = True
                self._renew_and_update(tree, leaf_ids, k, mask)
                if abs(init_scores[k]) > K_EPSILON:
                    tree.add_bias(init_scores[k])
            else:
                tree = Tree(2)
                if len(self.models) < self.num_tree_per_iteration:
                    output = (init_scores[k] if need or self.objective is None
                              else self.objective.boost_from_score(k))
                    tree.as_constant_tree(output)
                    self.train_scores.add_constant(output, k)
                    for vs in self.valid_scores:
                        vs.add_constant(output, k)
            self.models.append(tree)

        if not should_continue:
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            self._stopped = True
            return True
        self.iter_ += 1
        return False
