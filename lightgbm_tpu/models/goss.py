"""GOSS sampling (reference src/boosting/goss.hpp) — full logic in M4."""

from .gbdt import GBDT


class GOSS(GBDT):
    pass
