"""Objective functions: gradients/hessians as jitted elementwise device ops.

Interface contract mirrors the reference ObjectiveFunction (reference
include/LightGBM/objective_function.h:29-70): `get_gradients`,
`boost_from_score`, `convert_output`, `num_model_per_iteration`,
`is_constant_hessian`, `renew_tree_output`.

Formulas cite the reference implementation per class.  Gradients are
computed on device ([k, n] f32) since they feed the histogram kernel
directly; RenewTreeOutput percentile refits run on host (they are per-leaf
sorts, cheap relative to histogram work).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Metadata


class Objective:
    name = "none"
    num_class = 1

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label)
        self.weights = (None if metadata.weight is None
                        else jnp.asarray(metadata.weight))

    # -- contract ------------------------------------------------------
    def num_model_per_iteration(self) -> int:
        return 1

    def is_constant_hessian(self) -> bool:
        return False

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """score: [k, n] raw scores -> (grad, hess) [k, n]."""
        raise NotImplementedError

    #: whether renew_tree_output does anything (lets the driver skip
    #: device->host transfers of scores/leaf ids on the hot path)
    needs_renew = False

    #: objectives whose gradients cannot be traced into the fused device
    #: step (host RNG, data-dependent per-query work); the driver uses the
    #: synchronous path for these
    host_only = False

    def renew_tree_output(self, tree, score: np.ndarray,
                          leaf_ids: np.ndarray, row_mask: np.ndarray) -> None:
        """Post-hoc leaf re-fit (L1/quantile/MAPE family). Default: no-op."""

    def class_need_train(self, class_id: int) -> bool:
        return True

    def to_model_string(self) -> str:
        return self.name


def _apply_weight(grad, hess, weights):
    if weights is None:
        return grad, hess
    return grad * weights, hess * weights


class BinaryLogloss(Objective):
    """reference src/objective/binary_objective.hpp:20-213.

    `is_pos_fn` customizes label binarization — the hook MulticlassOVA uses
    to build its per-class losses (reference multiclass_objective.hpp:186).
    """
    name = "binary"

    def __init__(self, config: Config, is_pos_fn=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            raise ValueError("sigmoid must be > 0")
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            raise ValueError("cannot set is_unbalance and scale_pos_weight together")
        self._is_pos_fn = is_pos_fn

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        label = np.asarray(metadata.label)
        is_pos = (label > 0 if self._is_pos_fn is None
                  else self._is_pos_fn(label))
        cnt_pos = int(is_pos.sum())
        cnt_neg = num_data - cnt_pos
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self._sign = jnp.where(jnp.asarray(is_pos), 1.0, -1.0).astype(jnp.float32)
        self._lw = jnp.where(jnp.asarray(is_pos), w_pos, w_neg).astype(jnp.float32)

    def class_need_train(self, class_id: int) -> bool:
        return self.need_train

    def get_gradients(self, score):
        sig = self.sigmoid

        def f(s):
            response = -self._sign * sig / (1.0 + jnp.exp(self._sign * sig * s))
            ar = jnp.abs(response)
            g = response * self._lw
            h = ar * (sig - ar) * self._lw
            return _apply_weight(g, h, self.weights)
        return f(score[0])

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label)
        is_pos = ((label > 0) if self._is_pos_fn is None
                  else self._is_pos_fn(label)).astype(np.float64)
        w = self.metadata.weight
        if w is not None:
            suml = float((is_pos * w).sum())
            sumw = float(np.asarray(w, np.float64).sum())
        else:
            suml = float(is_pos.sum())
            sumw = float(self.num_data)
        pavg = min(max(suml / sumw, 1e-15), 1.0 - 1e-15)
        init = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_model_string(self) -> str:
        return f"binary sigmoid:{self.sigmoid:g}"


class RegressionL2(Objective):
    """reference src/objective/regression_objective.hpp:78-158."""
    name = "regression"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lbl = np.asarray(metadata.label, np.float64)
            self.trans_label = jnp.asarray(
                np.sign(lbl) * np.sqrt(np.abs(lbl)), dtype=jnp.float32)
        else:
            self.trans_label = self.label

    def is_constant_hessian(self) -> bool:
        return self.metadata.weight is None

    def get_gradients(self, score):
        g = score[0] - self.trans_label
        h = jnp.ones_like(g)
        return _apply_weight(g, h, self.weights)

    def boost_from_score(self, class_id: int) -> float:
        lbl = np.asarray(self.trans_label, np.float64)
        w = self.metadata.weight
        if w is not None:
            return float((lbl * w).sum() / np.asarray(w, np.float64).sum())
        return float(lbl.mean())

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_model_string(self) -> str:
        return "regression"


_REGISTRY: Dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


for _cls in (BinaryLogloss, RegressionL2):
    register(_cls)


def create_objective(config: Config) -> Optional[Objective]:
    """Objective factory (reference src/objective/objective_function.cpp:16-53)."""
    name = config.objective
    if name in ("none", ""):
        return None
    # late imports so the extended zoo registers itself
    from . import objectives_ext  # noqa: F401
    if name not in _REGISTRY:
        raise ValueError(f"unknown objective {name!r}")
    return _REGISTRY[name](config)


def create_objective_from_model_string(spec: str) -> Optional[Objective]:
    """Rebuild an objective from the model-file 'objective=...' line."""
    toks = spec.split()
    if not toks:
        return None
    name = toks[0]
    params = {}
    for t in toks[1:]:
        if ":" in t:
            k, v = t.split(":", 1)
            params[k] = v
    cfg = Config({"objective": name, **params})
    from . import objectives_ext  # noqa: F401
    if cfg.objective not in _REGISTRY:
        return None
    obj = _REGISTRY[cfg.objective](cfg)
    return obj
