"""Extended objective zoo (filled out in the objectives milestone)."""
