"""Extended objective zoo: regression family, multiclass, cross-entropy,
and learning-to-rank objectives.

Formulas mirror the reference implementations exactly (per-class citations
below); the *structure* is TPU-first: gradients are jnp elementwise programs
that trace into the fused train step where possible.  The L1/quantile/MAPE
family re-fits leaf outputs on host (`renew_tree_output` — per-leaf
percentile sorts are tiny next to histogram work), and the ranking
objectives run per-query pairwise work on host numpy (`host_only`), exactly
as the reference keeps them on CPU threads.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from ..config import Config
from ..io.dataset import Metadata
from .objectives import (BinaryLogloss, Objective, RegressionL2,
                         _apply_weight, register)

K_EPSILON = 1e-15


# ---------------------------------------------------------------------------
# Percentile helpers with reference semantics
# (reference src/objective/regression_objective.hpp:18-73
#  PercentileFun / WeightedPercentileFun)
# ---------------------------------------------------------------------------

def percentile(values: np.ndarray, alpha: float) -> float:
    """Unweighted percentile, reference PercentileFun semantics."""
    cnt = len(values)
    if cnt == 0:
        return 0.0
    if cnt <= 1:
        return float(values[0])
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    if pos < 1:
        return float(values.max())
    if pos >= cnt:
        return float(values.min())
    bias = float_pos - pos
    # descending order: v1 = pos-th largest, v2 = (pos+1)-th largest
    d = np.sort(values)[::-1]
    v1, v2 = float(d[pos - 1]), float(d[pos])
    return v1 - (v1 - v2) * bias


def weighted_percentile(values: np.ndarray, weights: np.ndarray,
                        alpha: float) -> float:
    """Weighted percentile, reference WeightedPercentileFun semantics
    (including its interpolation quirk when the next CDF step is >= 1)."""
    cnt = len(values)
    if cnt == 0:
        return 0.0
    if cnt <= 1:
        return float(values[0])
    order = np.argsort(values, kind="stable")
    cdf = np.cumsum(weights[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, cnt - 1)
    if pos == 0 or pos == cnt - 1:
        return float(values[order[pos]])
    v1 = float(values[order[pos - 1]])
    v2 = float(values[order[pos]])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return (threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1
    return v2


class _RenewMixin:
    """Leaf-output percentile refit shared by L1/quantile/MAPE
    (reference RenewTreeOutput overrides, regression_objective.hpp:235,523,624)."""

    needs_renew = True
    renew_alpha = 0.5

    def _renew_weights(self) -> Optional[np.ndarray]:
        w = self.metadata.weight
        return None if w is None else np.asarray(w, np.float64)

    def renew_tree_output(self, tree, score: np.ndarray,
                          leaf_ids: np.ndarray, row_mask: np.ndarray) -> None:
        label = np.asarray(self.metadata.label, np.float64)
        residual = label - score[:len(label)]
        w = self._renew_weights()
        alpha = self.renew_alpha
        for leaf in range(tree.num_leaves):
            rows = np.flatnonzero((leaf_ids == leaf) & row_mask)
            if rows.size == 0:
                continue
            if w is None:
                val = percentile(residual[rows], alpha)
            else:
                val = weighted_percentile(residual[rows], w[rows], alpha)
            tree.set_leaf_value(leaf, val)


@register
class RegressionL1(_RenewMixin, RegressionL2):
    """reference regression_objective.hpp:189-270."""
    name = "regression_l1"

    def is_constant_hessian(self) -> bool:
        return self.metadata.weight is None

    def get_gradients(self, score):
        g = jnp.sign(score[0] - self.label)
        h = jnp.ones_like(g)
        return _apply_weight(g, h, self.weights)

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label, np.float64)
        w = self._renew_weights()
        if w is None:
            return percentile(label, 0.5)
        return weighted_percentile(label, w, 0.5)

    def to_model_string(self) -> str:
        return self.name


@register
class Huber(RegressionL2):
    """reference regression_objective.hpp:275-333."""
    name = "huber"

    def __init__(self, config: Config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        self.sqrt = False  # sqrt transform unsupported for huber (ref :279)

    def is_constant_hessian(self) -> bool:
        return False

    def get_gradients(self, score):
        diff = score[0] - self.label
        g = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                      jnp.sign(diff) * self.alpha)
        h = jnp.ones_like(g)
        return _apply_weight(g, h, self.weights)

    def to_model_string(self) -> str:
        return self.name


@register
class Fair(RegressionL2):
    """reference regression_objective.hpp:337-378."""
    name = "fair"

    def __init__(self, config: Config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def is_constant_hessian(self) -> bool:
        return False

    def get_gradients(self, score):
        x = score[0] - self.label
        ax = jnp.abs(x)
        c = self.c
        g = c * x / (ax + c)
        h = c * c / ((ax + c) * (ax + c))
        return _apply_weight(g, h, self.weights)

    def to_model_string(self) -> str:
        return self.name


@register
class Poisson(RegressionL2):
    """reference regression_objective.hpp:384-462.  Internal score f is the
    log-rate: grad = exp(f) - y, hess = exp(f + poisson_max_delta_step)."""
    name = "poisson"

    def __init__(self, config: Config):
        super().__init__(config)
        self.max_delta = float(config.poisson_max_delta_step)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.float64)
        if lbl.min() < 0:
            raise ValueError(f"[{self.name}]: at least one target label is negative")
        if lbl.sum() == 0:
            raise ValueError(f"[{self.name}]: sum of labels is zero")

    def is_constant_hessian(self) -> bool:
        return False

    def get_gradients(self, score):
        ef = jnp.exp(score[0])
        g = ef - self.label
        h = jnp.exp(score[0] + self.max_delta)
        return _apply_weight(g, h, self.weights)

    def boost_from_score(self, class_id: int) -> float:
        mean = RegressionL2.boost_from_score(self, class_id)
        return float(np.log(mean)) if mean > 0 else float(np.log(1e-6))

    def convert_output(self, raw):
        return np.exp(raw)

    def to_model_string(self) -> str:
        return self.name


@register
class Quantile(_RenewMixin, RegressionL2):
    """reference regression_objective.hpp:464-556."""
    name = "quantile"

    def __init__(self, config: Config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            raise ValueError("alpha must be in (0, 1) for quantile")
        self.renew_alpha = self.alpha

    def is_constant_hessian(self) -> bool:
        return self.metadata.weight is None

    def get_gradients(self, score):
        delta = score[0] - self.label
        g = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        h = jnp.ones_like(g)
        return _apply_weight(g, h, self.weights)

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label, np.float64)
        w = self._renew_weights()
        if w is None:
            return percentile(label, self.alpha)
        return weighted_percentile(label, w, self.alpha)

    def to_model_string(self) -> str:
        return f"{self.name} alpha:{self.alpha:g}"


@register
class MAPE(_RenewMixin, RegressionL2):
    """reference regression_objective.hpp:562-654.  Uses label weights
    1/max(1,|y|) for both gradients and the percentile refits."""
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.float64)
        lw = 1.0 / np.maximum(1.0, np.abs(lbl))
        if metadata.weight is not None:
            lw = lw * np.asarray(metadata.weight, np.float64)
        self.label_weight = lw
        self._label_weight_dev = jnp.asarray(lw.astype(np.float32))

    def is_constant_hessian(self) -> bool:
        return True

    def get_gradients(self, score):
        diff = score[0] - self.label
        g = jnp.sign(diff) * self._label_weight_dev
        if self.weights is None:
            h = jnp.ones_like(g)
        else:
            h = self.weights
        return g, h  # label weight already folded into g (ref :600-608)

    def _renew_weights(self) -> Optional[np.ndarray]:
        return self.label_weight  # MAPE always refits weighted (ref :628-641)

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label, np.float64)
        return weighted_percentile(label, self.label_weight, 0.5)

    def to_model_string(self) -> str:
        return self.name


@register
class Gamma(Poisson):
    """reference regression_objective.hpp:661-691."""
    name = "gamma"

    def get_gradients(self, score):
        enf = jnp.exp(-score[0])
        if self.weights is None:
            g = 1.0 - self.label * enf
            h = self.label * enf
        else:
            # reference applies the weight inside the subtraction for grad
            # (regression_objective.hpp:682) — replicated verbatim
            g = 1.0 - self.label * enf * self.weights
            h = self.label * enf * self.weights
        return g, h

    def to_model_string(self) -> str:
        return self.name


@register
class Tweedie(Poisson):
    """reference regression_objective.hpp:696-732."""
    name = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        s = score[0]
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * s)
        e2 = jnp.exp((2.0 - rho) * s)
        g = -self.label * e1 + e2
        h = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return _apply_weight(g, h, self.weights)

    def to_model_string(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# Multiclass
# ---------------------------------------------------------------------------

@register
class MulticlassSoftmax(Objective):
    """reference src/objective/multiclass_objective.hpp:24-175."""
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            raise ValueError("num_class must be >= 2 for multiclass")

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        label = np.asarray(metadata.label)
        label_int = label.astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            raise ValueError(
                f"label must be in [0, {self.num_class}) for multiclass")
        w = metadata.weight
        if w is None:
            probs = np.bincount(label_int, minlength=self.num_class).astype(np.float64)
            sum_w = float(num_data)
        else:
            probs = np.bincount(label_int, weights=np.asarray(w, np.float64),
                                minlength=self.num_class)
            sum_w = float(np.asarray(w, np.float64).sum())
        self.class_init_probs = probs / sum_w
        self._onehot = jnp.asarray(
            (label_int[None, :] == np.arange(self.num_class)[:, None])
            .astype(np.float32))

    def num_model_per_iteration(self) -> int:
        return self.num_class

    def get_gradients(self, score):
        # score [k, n] -> softmax over classes
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        g = p - self._onehot
        h = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            g = g * self.weights[None, :]
            h = h * self.weights[None, :]
        return g, h

    def boost_from_score(self, class_id: int) -> float:
        return float(np.log(max(K_EPSILON, self.class_init_probs[class_id])))

    def class_need_train(self, class_id: int) -> bool:
        p = abs(self.class_init_probs[class_id])
        return K_EPSILON < p < 1.0 - K_EPSILON

    def convert_output(self, raw):
        # raw [k, n] -> softmax probabilities [k, n]
        m = np.max(raw, axis=0, keepdims=True)
        e = np.exp(raw - m)
        return e / e.sum(axis=0, keepdims=True)

    def to_model_string(self) -> str:
        return f"multiclass num_class:{self.num_class}"


@register
class MulticlassOVA(Objective):
    """reference multiclass_objective.hpp:180-270: one binary logloss per
    class on the indicator label == k."""
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        if self.num_class < 2:
            raise ValueError("num_class must be >= 2 for multiclassova")
        self.binary_losses = [
            BinaryLogloss(config, is_pos_fn=(lambda lbl, k=k:
                                             lbl.astype(np.int32) == k))
            for k in range(self.num_class)]

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        for bl in self.binary_losses:
            bl.init(metadata, num_data)

    def num_model_per_iteration(self) -> int:
        return self.num_class

    def get_gradients(self, score):
        gs, hs = [], []
        for k, bl in enumerate(self.binary_losses):
            g, h = bl.get_gradients(score[k:k + 1])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs), jnp.stack(hs)

    def boost_from_score(self, class_id: int) -> float:
        return self.binary_losses[class_id].boost_from_score(0)

    def class_need_train(self, class_id: int) -> bool:
        return self.binary_losses[class_id].class_need_train(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_model_string(self) -> str:
        return f"multiclassova num_class:{self.num_class} sigmoid:{self.sigmoid:g}"


# ---------------------------------------------------------------------------
# Cross-entropy family (labels in [0, 1])
# ---------------------------------------------------------------------------

def _check_label_01(label: np.ndarray, name: str) -> None:
    if label.min() < 0.0 or label.max() > 1.0:
        raise ValueError(f"[{name}]: labels must be in [0, 1]")


@register
class CrossEntropy(Objective):
    """reference src/objective/xentropy_objective.hpp:44-143."""
    name = "cross_entropy"

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        _check_label_01(np.asarray(metadata.label, np.float64), self.name)
        if metadata.weight is not None:
            w = np.asarray(metadata.weight, np.float64)
            if w.min() < 0:
                raise ValueError(f"[{self.name}]: at least one weight is negative")
            if w.sum() == 0:
                raise ValueError(f"[{self.name}]: sum of weights is zero")

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score[0]))
        g = z - self.label
        h = z * (1.0 - z)
        return _apply_weight(g, h, self.weights)

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label, np.float64)
        w = self.metadata.weight
        if w is not None:
            w = np.asarray(w, np.float64)
            pavg = float((label * w).sum() / w.sum())
        else:
            pavg = float(label.mean())
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))

    def to_model_string(self) -> str:
        return self.name


@register
class CrossEntropyLambda(Objective):
    """reference xentropy_objective.hpp:148-271: p = 1-exp(-lambda*w),
    lambda = log(1+exp(f)).  ConvertOutput yields lambda, not p."""
    name = "cross_entropy_lambda"

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        _check_label_01(np.asarray(metadata.label, np.float64), self.name)
        if metadata.weight is not None:
            w = np.asarray(metadata.weight, np.float64)
            if w.min() <= 0:
                raise ValueError(
                    f"[{self.name}]: at least one weight is non-positive")

    def get_gradients(self, score):
        s = score[0]
        if self.weights is None:
            z = 1.0 / (1.0 + jnp.exp(-s))
            return z - self.label, z * (1.0 - z)
        w = self.weights
        y = self.label
        epf = jnp.exp(s)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        g = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id: int) -> float:
        label = np.asarray(self.metadata.label, np.float64)
        w = self.metadata.weight
        if w is not None:
            w = np.asarray(w, np.float64)
            havg = float((label * w).sum() / w.sum())
        else:
            havg = float(label.mean())
        return float(np.log(np.expm1(havg)))

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))

    def to_model_string(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# Learning to rank
# ---------------------------------------------------------------------------

def default_label_gain() -> List[float]:
    """2^i - 1 gains, 31 levels (reference dcg_calculator.cpp:32-40)."""
    return [0.0] + [float((1 << i) - 1) for i in range(1, 31)]


class _RankBase(Objective):
    host_only = True  # per-query sorts + host RNG stay off the jit path

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError(f"{self.name} tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.query_boundaries) - 1
        self.label_np = np.asarray(metadata.label, np.float64)
        self.weight_np = (None if metadata.weight is None
                          else np.asarray(metadata.weight, np.float64))


@register
class LambdarankNDCG(_RankBase):
    """reference src/objective/rank_objective.hpp:23-254.

    Pairwise NDCG lambdas computed per query on host, vectorized over the
    [cnt, cnt] pair matrix per query.  Exact sigmoid replaces the
    reference's 1M-entry lookup table (rank_objective.hpp:196-209)."""
    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            raise ValueError("sigmoid must be > 0")
        self.norm = bool(config.lambdamart_norm)
        self.optimize_pos_at = int(config.max_position)
        gains = list(config.label_gain) or default_label_gain()
        self.label_gain = np.asarray(gains, np.float64)

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        lbl = self.label_np
        if np.abs(lbl - lbl.astype(np.int64)).max() > K_EPSILON:
            raise ValueError("label must be int type for ranking task")
        if lbl.min() < 0:
            raise ValueError("label must be non-negative for ranking task")
        if int(lbl.max()) >= len(self.label_gain):
            raise ValueError("label exceeds label_gain size")
        # cache 1/maxDCG@k per query (reference rank_objective.hpp:60-70)
        self.inverse_max_dcgs = np.zeros(self.num_queries)
        for q in range(self.num_queries):
            a, b = self.query_boundaries[q], self.query_boundaries[q + 1]
            mdcg = self._max_dcg_at_k(self.optimize_pos_at, lbl[a:b])
            self.inverse_max_dcgs[q] = 1.0 / mdcg if mdcg > 0 else 0.0

    def _max_dcg_at_k(self, k: int, label: np.ndarray) -> float:
        k = min(k, len(label))
        top = np.sort(label)[::-1][:k].astype(np.int64)
        disc = 1.0 / np.log2(2.0 + np.arange(k))
        return float((self.label_gain[top] * disc).sum())

    def get_gradients(self, score):
        s = np.asarray(score, np.float64).reshape(-1)[:self.num_data]
        lambdas = np.zeros(self.num_data)
        hessians = np.zeros(self.num_data)
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            self._one_query(s[a:b], self.label_np[a:b],
                            self.inverse_max_dcgs[q],
                            lambdas[a:b], hessians[a:b])
        if self.weight_np is not None:
            lambdas *= self.weight_np
            hessians *= self.weight_np
        return (lambdas.astype(np.float32)[None, :],
                hessians.astype(np.float32)[None, :])

    def _one_query(self, s, label, inv_max_dcg, out_l, out_h):
        cnt = len(s)
        if cnt <= 1 or inv_max_dcg <= 0:
            return
        # sorted positions by descending score (stable)
        order = np.argsort(-s, kind="stable")
        ss = s[order]
        ll = label[order].astype(np.int64)
        gains = self.label_gain[ll]
        disc = 1.0 / np.log2(2.0 + np.arange(cnt))
        best_score, worst_score = ss[0], ss[-1]
        # pair (i=high rank pos, j=low): valid iff label[i] > label[j]
        valid = ll[:, None] > ll[None, :]
        delta_score = ss[:, None] - ss[None, :]
        dcg_gap = gains[:, None] - gains[None, :]
        paired_disc = np.abs(disc[:, None] - disc[None, :])
        delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
        if self.norm and best_score != worst_score:
            delta_ndcg = delta_ndcg / (0.01 + np.abs(delta_score))
        with np.errstate(over="ignore"):
            p = 1.0 / (1.0 + np.exp(np.clip(delta_score * self.sigmoid,
                                            -88.0, 88.0)))
        p_lambda = np.where(valid, -self.sigmoid * delta_ndcg * p, 0.0)
        p_hess = np.where(valid,
                          self.sigmoid * self.sigmoid * delta_ndcg
                          * p * (1.0 - p), 0.0)
        lam_sorted = p_lambda.sum(axis=1) - p_lambda.sum(axis=0)
        hes_sorted = p_hess.sum(axis=1) + p_hess.sum(axis=0)
        sum_lambdas = -2.0 * p_lambda.sum()
        if self.norm and sum_lambdas > 0:
            factor = np.log2(1 + sum_lambdas) / sum_lambdas
            lam_sorted *= factor
            hes_sorted *= factor
        out_l[order] += lam_sorted
        out_h[order] += hes_sorted

    def to_model_string(self) -> str:
        return self.name


@register
class RankXENDCG(_RankBase):
    """reference src/objective/rank_xendcg_objective.hpp:19-138
    (XE_NDCG, arxiv.org/abs/1911.09798).  Stochastic (per-doc gamma draws),
    hence host_only."""
    name = "rank_xendcg"

    def __init__(self, config: Config):
        super().__init__(config)
        self._rng = np.random.default_rng(int(config.objective_seed))

    def get_gradients(self, score):
        s = np.asarray(score, np.float64).reshape(-1)[:self.num_data]
        lambdas = np.zeros(self.num_data)
        hessians = np.zeros(self.num_data)
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            self._one_query(s[a:b], self.label_np[a:b],
                            lambdas[a:b], hessians[a:b])
        return (lambdas.astype(np.float32)[None, :],
                hessians.astype(np.float32)[None, :])

    def _one_query(self, s, label, out_l, out_h):
        cnt = len(s)
        if cnt == 0:
            return
        e = np.exp(s - s.max())
        rho = e / e.sum()
        gammas = self._rng.random(cnt)
        phi = np.power(2.0, label) - gammas
        sum_labels = phi.sum()
        if sum_labels == 0:
            return
        l1 = -phi / sum_labels + rho
        # the reference's j!=i loops never evaluate 1/(1-rho) for
        # single-doc queries (rho=1); guard the vectorized form
        denom = 1.0 - rho
        inv = np.where(denom > 1e-300, 1.0 / np.where(denom > 1e-300,
                                                      denom, 1.0), 0.0)
        a = l1 * inv
        l2 = a.sum() - a
        b = rho * l2 * inv
        l3 = b.sum() - b
        out_l[:] = l1 + rho * l2 + rho * l3
        out_h[:] = rho * (1.0 - rho)

    def to_model_string(self) -> str:
        return self.name
