"""Decision tree model: flattened array-of-nodes + serialization.

Re-implements the reference `Tree` (reference include/LightGBM/tree.h:25,
src/io/tree.cpp) with numpy arrays:

* node numbering: internal node k is created by the k-th split; leaves are
  referenced as `~leaf_idx` in child arrays (negative),
* `decision_type` bit flags: bit0 categorical, bit1 default-left,
  bits 2-3 missing type (0 none / 1 zero / 2 nan)  (tree.h:19-20,210-229),
* text serialization matches the reference v3 model block (tree.cpp ToString)
  so models interchange with the reference,
* vectorized batch prediction (the analog of AddPredictionToScore,
  tree.h:106-119) via a level-by-level gather loop instead of per-row
  pointer chasing.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
K_ZERO_THRESHOLD = 1e-35


def _is_zero(v: float) -> bool:
    return -K_ZERO_THRESHOLD <= v <= K_ZERO_THRESHOLD


def _fmt(v: float) -> str:
    """Format a double like the reference (up to 17 significant digits)."""
    s = repr(float(v))
    if s.endswith(".0"):
        s = s[:-2]
    return s


def _fmt_float(v: float) -> str:
    """Format split gains / shrinkage (float precision in reference)."""
    return f"{v:g}"


class Tree:
    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        n = max(max_leaves, 1)
        ni = max(n - 1, 1)
        self.num_leaves = 1
        self.num_cat = 0
        self.shrinkage = 1.0
        self.split_feature_inner = np.zeros(ni, dtype=np.int32)
        self.split_feature = np.zeros(ni, dtype=np.int32)
        self.split_gain = np.zeros(ni, dtype=np.float32)
        self.threshold_in_bin = np.zeros(ni, dtype=np.int32)
        self.threshold = np.zeros(ni, dtype=np.float64)
        self.decision_type = np.zeros(ni, dtype=np.int8)
        self.left_child = np.zeros(ni, dtype=np.int32)
        self.right_child = np.zeros(ni, dtype=np.int32)
        self.leaf_value = np.zeros(n, dtype=np.float64)
        self.leaf_weight = np.zeros(n, dtype=np.float64)
        self.leaf_count = np.zeros(n, dtype=np.int32)
        self.leaf_parent = np.full(n, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(n, dtype=np.int32)
        self.internal_value = np.zeros(ni, dtype=np.float64)
        self.internal_weight = np.zeros(ni, dtype=np.float64)
        self.internal_count = np.zeros(ni, dtype=np.int32)
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []

    # ------------------------------------------------------------------
    def _split_common(self, leaf: int, feature_inner: int, real_feature: int,
                      left_value: float, right_value: float, left_cnt: int,
                      right_cnt: int, left_weight: float, right_weight: float,
                      gain: float) -> int:
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature_inner
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = gain
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.internal_weight[new_node] = self.leaf_weight[leaf]
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if math.isnan(left_value) else left_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = 0.0 if math.isnan(right_value) else right_value
        self.leaf_weight[self.num_leaves] = right_weight
        self.leaf_count[self.num_leaves] = right_cnt
        self.leaf_depth[self.num_leaves] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        return new_node

    def split(self, leaf: int, feature_inner: int, real_feature: int,
              threshold_bin: int, threshold_double: float, left_value: float,
              right_value: float, left_cnt: int, right_cnt: int,
              left_weight: float, right_weight: float, gain: float,
              missing_type: int, default_left: bool) -> int:
        """Numerical split; returns the new (right) leaf index."""
        new_node = self._split_common(leaf, feature_inner, real_feature,
                                      left_value, right_value, left_cnt,
                                      right_cnt, left_weight, right_weight, gain)
        dt = 0
        if default_left:
            dt |= K_DEFAULT_LEFT_MASK
        dt |= (int(missing_type) & 3) << 2
        self.decision_type[new_node] = dt
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = threshold_double
        self.num_leaves += 1
        return self.num_leaves - 1

    def split_categorical(self, leaf: int, feature_inner: int, real_feature: int,
                          threshold_bins: Sequence[int], thresholds: Sequence[int],
                          left_value: float, right_value: float, left_cnt: int,
                          right_cnt: int, left_weight: float, right_weight: float,
                          gain: float, missing_type: int) -> int:
        """Categorical split: `thresholds` are bitset words of raw categories
        going LEFT; `threshold_bins` the same in bin space."""
        new_node = self._split_common(leaf, feature_inner, real_feature,
                                      left_value, right_value, left_cnt,
                                      right_cnt, left_weight, right_weight, gain)
        dt = K_CATEGORICAL_MASK | ((int(missing_type) & 3) << 2)
        self.decision_type[new_node] = dt
        self.threshold_in_bin[new_node] = self.num_cat
        self.threshold[new_node] = self.num_cat
        self.num_cat += 1
        self.cat_boundaries.append(self.cat_boundaries[-1] + len(thresholds))
        self.cat_threshold.extend(int(x) for x in thresholds)
        self.cat_boundaries_inner.append(
            self.cat_boundaries_inner[-1] + len(threshold_bins))
        self.cat_threshold_inner.extend(int(x) for x in threshold_bins)
        self.num_leaves += 1
        return self.num_leaves - 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        lv = self.leaf_value[:self.num_leaves] * rate
        lv[np.abs(lv) <= K_ZERO_THRESHOLD] = 0.0
        self.leaf_value[:self.num_leaves] = lv
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        lv = val + self.leaf_value[:self.num_leaves]
        lv[np.abs(lv) <= K_ZERO_THRESHOLD] = 0.0
        self.leaf_value[:self.num_leaves] = lv
        self.shrinkage = 1.0

    def as_constant_tree(self, val: float) -> None:
        self.num_leaves = 1
        self.shrinkage = 1.0
        self.leaf_value[0] = val

    def set_leaf_value(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = 0.0 if _is_zero(value) else value

    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        return int(self.leaf_depth[:self.num_leaves].max())

    # ------------------------------------------------------------------
    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Leaf index per row; X is the raw feature matrix [n, num_features]."""
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)  # >=0: internal node, <0: ~leaf
        for _ in range(self.max_depth()):
            active = node >= 0
            if not active.any():
                break
            nid = node[active]
            feat = self.split_feature[nid]
            fval = X[active, feat]
            dt = self.decision_type[nid]
            is_cat = (dt & K_CATEGORICAL_MASK) != 0
            missing = (dt.astype(np.int32) >> 2) & 3
            default_left = (dt & K_DEFAULT_LEFT_MASK) != 0
            lc = self.left_child[nid]
            rc = self.right_child[nid]

            nan_mask = np.isnan(fval)
            # numerical path
            fv = np.where(nan_mask & (missing != 2), 0.0, fval)
            is_default = ((missing == 1) & (np.abs(fv) <= K_ZERO_THRESHOLD) |
                          (missing == 2) & nan_mask)
            go_left_num = np.where(is_default, default_left,
                                   fv <= self.threshold[nid])
            if is_cat.any():
                go_left = np.where(is_cat,
                                   self._categorical_go_left(fval, nid, missing),
                                   go_left_num)
            else:
                go_left = go_left_num
            node[active] = np.where(go_left, lc, rc).astype(np.int32)
        return (~node).astype(np.int32)

    def _categorical_go_left(self, fval: np.ndarray, nid: np.ndarray,
                             missing: np.ndarray) -> np.ndarray:
        """Vectorized CategoricalDecision (tree.h:307-318)."""
        cat_threshold = np.asarray(self.cat_threshold, dtype=np.uint32)
        cat_boundaries = np.asarray(self.cat_boundaries, dtype=np.int64)
        nan_mask = np.isnan(fval)
        int_fval = np.where(nan_mask, 0, np.nan_to_num(fval, nan=0.0)).astype(np.int64)
        neg = int_fval < 0
        # nid covers ALL active nodes (numerical ones too, masked by the
        # caller); their thresholds are raw doubles — clip before indexing
        cat_idx = np.clip(self.threshold[nid].astype(np.int64), 0,
                          max(len(cat_boundaries) - 2, 0))
        start = cat_boundaries[cat_idx]
        width = cat_boundaries[cat_idx + 1] - start
        word_idx = int_fval // 32
        in_range = word_idx < width
        word = cat_threshold[np.clip(start + word_idx, 0, len(cat_threshold) - 1)] \
            if len(cat_threshold) else np.zeros(len(nid), dtype=np.uint32)
        bit = (word >> (int_fval % 32).astype(np.uint32)) & 1
        go_left = in_range & (bit == 1)
        go_left[neg] = False
        go_left[nan_mask & (missing == 2)] = False
        return go_left

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaves = self.predict_leaf(X)
        return self.leaf_value[leaves]

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        nl = self.num_leaves
        ni = nl - 1
        parts = [
            f"num_leaves={nl}",
            f"num_cat={self.num_cat}",
            "split_feature=" + " ".join(str(int(x)) for x in self.split_feature[:ni]),
            "split_gain=" + " ".join(_fmt_float(x) for x in self.split_gain[:ni]),
            "threshold=" + " ".join(_fmt(x) for x in self.threshold[:ni]),
            "decision_type=" + " ".join(str(int(x)) for x in self.decision_type[:ni]),
            "left_child=" + " ".join(str(int(x)) for x in self.left_child[:ni]),
            "right_child=" + " ".join(str(int(x)) for x in self.right_child[:ni]),
            "leaf_value=" + " ".join(_fmt(x) for x in self.leaf_value[:nl]),
            "leaf_weight=" + " ".join(_fmt(x) for x in self.leaf_weight[:nl]),
            "leaf_count=" + " ".join(str(int(x)) for x in self.leaf_count[:nl]),
            "internal_value=" + " ".join(_fmt_float(x) for x in self.internal_value[:ni]),
            "internal_weight=" + " ".join(_fmt_float(x) for x in self.internal_weight[:ni]),
            "internal_count=" + " ".join(str(int(x)) for x in self.internal_count[:ni]),
        ]
        if self.num_cat > 0:
            parts.append("cat_boundaries=" +
                         " ".join(str(x) for x in self.cat_boundaries))
            parts.append("cat_threshold=" +
                         " ".join(str(x) for x in self.cat_threshold))
        parts.append(f"shrinkage={_fmt_float(self.shrinkage)}")
        return "\n".join(parts) + "\n\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.strip().split("\n"):
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        nl = int(kv["num_leaves"])
        t = cls(max(nl, 2))
        t.num_leaves = nl
        t.num_cat = int(kv.get("num_cat", "0"))
        t.shrinkage = float(kv.get("shrinkage", "1"))

        def arr(key, dtype, size):
            if size <= 0 or key not in kv or kv[key] == "":
                return np.zeros(max(size, 0), dtype=dtype)
            vals = kv[key].split()
            return np.asarray([float(x) for x in vals], dtype=dtype)

        ni = nl - 1
        t.split_feature = arr("split_feature", np.int32, ni)
        t.split_feature_inner = t.split_feature.copy()
        t.split_gain = arr("split_gain", np.float32, ni)
        t.threshold = arr("threshold", np.float64, ni)
        t.threshold_in_bin = np.zeros(ni, dtype=np.int32)
        t.decision_type = arr("decision_type", np.int8, ni)
        t.left_child = arr("left_child", np.int32, ni)
        t.right_child = arr("right_child", np.int32, ni)
        t.leaf_value = arr("leaf_value", np.float64, nl)
        t.leaf_weight = arr("leaf_weight", np.float64, nl)
        t.leaf_count = arr("leaf_count", np.int32, nl)
        t.internal_value = arr("internal_value", np.float64, ni)
        t.internal_weight = arr("internal_weight", np.float64, ni)
        t.internal_count = arr("internal_count", np.int32, ni)
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
            t.cat_boundaries_inner = list(t.cat_boundaries)
            t.cat_threshold_inner = list(t.cat_threshold)
        # recompute leaf depths/parents from children arrays
        t.leaf_parent = np.full(max(nl, 1), -1, dtype=np.int32)
        t.leaf_depth = np.zeros(max(nl, 1), dtype=np.int32)
        if nl > 1:
            t._recompute_depths(0, 0)
        return t

    def _recompute_depths(self, node: int, depth: int) -> None:
        stack = [(node, depth)]
        while stack:
            nd, dp = stack.pop()
            for child in (self.left_child[nd], self.right_child[nd]):
                if child >= 0:
                    stack.append((int(child), dp + 1))
                else:
                    leaf = ~int(child)
                    self.leaf_depth[leaf] = dp + 1
                    self.leaf_parent[leaf] = nd
