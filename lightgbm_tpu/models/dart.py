"""DART boosting (reference src/boosting/dart.hpp).

Each iteration: drop a random subset of existing trees (weighted by tree
weight unless uniform_drop), compute gradients on the dropped score, grow
the new tree with shrinkage lr/(1+k), then rescale the dropped trees by
k/(k+1) (or k/(k+lr) in xgboost_dart_mode) and restore their contribution
(reference dart.hpp:58-139 DroppingTrees, :97 Normalize).

The drop/restore bookkeeping is host-side score arithmetic (one binned
traversal per dropped tree per dataset); gradient + tree growth still run
on device via the synchronous driver path.
"""

from __future__ import annotations

from typing import List

import numpy as np

import jax.numpy as jnp

from .gbdt import GBDT


class DART(GBDT):
    def init(self, config, train_data) -> None:
        super().init(config, train_data)
        self._train_step = None  # drop bookkeeping varies per iter: sync path
        self._drop_rng = np.random.default_rng(int(config.drop_seed))
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self._drop_idx: List[int] = []

    def reset_config(self, config) -> None:
        super().reset_config(config)
        self._train_step = None
        self._drop_rng = np.random.default_rng(int(config.drop_seed))
        self.sum_weight = 0.0

    # ------------------------------------------------------------------
    def _apply_iters_to_scores(self, iters, sign: float) -> None:
        """Add sign * (all listed iterations' trees) to every score vector
        — ONE native binned pass per (class, dataset) for the whole drop
        set instead of a python loop per tree (reference dart.hpp:97-139
        drop / :152-196 restore)."""
        if not iters:
            return
        K = self.num_tree_per_iteration
        for k in range(K):
            trees = [self.models[i * K + k] for i in iters
                     if self.models[i * K + k].num_leaves > 1]
            if not trees:
                continue
            scales = [sign] * len(trees)
            self.train_scores.add(k, jnp.asarray(
                self._score_trees_binned(self.train_data.bins, trees,
                                         scales).astype(np.float32)))
            for vs, vd in zip(self.valid_scores, self.valid_sets):
                vs.add(k, jnp.asarray(
                    self._score_trees_binned(vd.bins, trees,
                                             scales).astype(np.float32)))

    def _dropping_trees(self) -> None:
        """Select and remove dropped trees from the scores
        (reference dart.hpp:97-139)."""
        cfg = self.config
        self._drop_idx = []
        if self._drop_rng.random() >= float(cfg.skip_drop):
            drop_rate = float(cfg.drop_rate)
            max_drop = int(cfg.max_drop)
            if not cfg.uniform_drop:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if max_drop > 0:
                        drop_rate = min(drop_rate,
                                        max_drop * inv_avg / self.sum_weight)
                    for i in range(self.iter_):
                        if self._drop_rng.random() < \
                                drop_rate * self.tree_weight[i] * inv_avg:
                            self._drop_idx.append(self.num_init_iteration + i)
                            if max_drop > 0 and len(self._drop_idx) >= max_drop:
                                break
            else:
                if max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, max_drop / float(self.iter_))
                for i in range(self.iter_):
                    if self._drop_rng.random() < drop_rate:
                        self._drop_idx.append(self.num_init_iteration + i)
                        if max_drop > 0 and len(self._drop_idx) >= max_drop:
                            break
        self._apply_iters_to_scores(self._drop_idx, -1.0)
        k = float(len(self._drop_idx))
        lr = float(cfg.learning_rate)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = lr / (1.0 + k)
        else:
            self.shrinkage_rate = lr if not self._drop_idx else lr / (lr + k)

    def _normalize(self) -> None:
        """Rescale dropped trees and restore their contribution
        (reference dart.hpp:152-196)."""
        cfg = self.config
        k = float(len(self._drop_idx))
        if k == 0:
            return
        scale = k / (k + 1.0) if not cfg.xgboost_dart_mode \
            else k / (k + float(cfg.learning_rate))
        K = self.num_tree_per_iteration
        undo = getattr(self, "_dart_undo", None)
        for i in self._drop_idx:
            for c in range(K):
                tree = self.models[i * K + c]
                if undo is not None:
                    # copy-undo record for atomic-iteration rollback:
                    # apply_shrinkage zero-clamps, so scaling back is lossy
                    undo.append((tree,
                                 tree.leaf_value[:tree.num_leaves].copy(),
                                 tree.shrinkage, None, None))
                tree.apply_shrinkage(scale)
            if not cfg.uniform_drop:
                j = i - self.num_init_iteration
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[j] / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[j] / \
                        (k + float(cfg.learning_rate))
                if undo is not None:
                    undo.append((None, (), None, j, self.tree_weight[j]))
                self.tree_weight[j] *= scale
        # leaf values changed in place: the RAW-value predictor tables
        # are stale (the binned walker packs per call and cannot be)
        self._invalidate_tables()
        self._apply_iters_to_scores(self._drop_idx, 1.0)

    # -- atomic-iteration rollback / checkpoint hooks ------------------
    def _snapshot_extra(self):
        # _normalize mutates EXISTING trees in place (apply_shrinkage
        # clamps tiny values to zero, so scaling is not invertible); it
        # appends copy-undo records to this ledger, which _restore_extra
        # replays on rollback
        self._dart_undo = []
        return {"dart": (list(self._drop_idx), len(self.tree_weight),
                         float(self.sum_weight),
                         self._drop_rng.bit_generator.state)}

    def _restore_extra(self, snap):
        drop_idx, n_weights, sum_weight, rng_state = snap["dart"]
        for tree, leaf_values, shrinkage, j, weight in \
                reversed(self._dart_undo):
            if tree is not None:
                tree.leaf_value[:len(leaf_values)] = leaf_values
                tree.shrinkage = shrinkage
            if weight is not None and j is not None \
                    and j < len(self.tree_weight):
                self.tree_weight[j] = weight
        self._dart_undo = []
        self._drop_idx = drop_idx
        del self.tree_weight[n_weights:]
        self.sum_weight = sum_weight
        self._drop_rng.bit_generator.state = rng_state

    def _has_skip_lever(self):
        return True  # the drop selection stream always varies the retry

    def _advance_streams_for_skip(self):
        super()._advance_streams_for_skip()
        # _iter_restore rewound the drop stream with everything else;
        # burn one draw so the retry selects a different drop set
        self._drop_rng.random()

    def _capture_extra_state(self):
        return {"dart": {"tree_weight": [float(w) for w in self.tree_weight],
                         "sum_weight": float(self.sum_weight),
                         "drop_rng": self._drop_rng.bit_generator.state}}

    def _restore_extra_state(self, extra):
        d = (extra or {}).get("dart")
        if not d:
            return
        self.tree_weight = [float(w) for w in d["tree_weight"]]
        self.sum_weight = float(d["sum_weight"])
        rng = np.random.default_rng(0)
        rng.bit_generator.state = d["drop_rng"]
        self._drop_rng = rng

    # ------------------------------------------------------------------
    def _train_one_iter_impl(self, grad, hess, snap) -> bool:
        # base-class wrapper (train_one_iter) owns the stall check,
        # rollback snapshot, fault point, and numeric guard
        self._materialize()
        self._dropping_trees()
        ret = self._train_one_iter_sync(grad, hess)
        if ret:
            # stalled: restore dropped contributions unscaled so eval on the
            # final (unchanged) model stays consistent
            self._apply_iters_to_scores(self._drop_idx, 1.0)
            self._drop_idx = []
            return True
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
