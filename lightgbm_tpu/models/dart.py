"""DART boosting (reference src/boosting/dart.hpp) — full logic in M4."""

from .gbdt import GBDT


class DART(GBDT):
    pass
