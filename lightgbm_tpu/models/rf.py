"""Random forest mode (reference src/boosting/rf.hpp) — full logic in M4."""

from .gbdt import GBDT


class RF(GBDT):
    pass
