"""Random forest mode (reference src/boosting/rf.hpp).

`average_output_=true`: scores are maintained as the running average of
tree outputs, bagging is mandatory, there is no shrinkage, and gradients
are computed ONCE from the constant boost-from-average scores
(reference rf.hpp:84-103 Boosting, :105-168 TrainOneIter).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .gbdt import GBDT, K_EPSILON
from .tree import Tree


class RF(GBDT):
    def init(self, config, train_data) -> None:
        if not (int(config.bagging_freq) > 0
                and 0.0 < float(config.bagging_fraction) < 1.0):
            raise ValueError(
                "random forest requires bagging "
                "(bagging_freq > 0 and bagging_fraction in (0, 1))")
        if not (0.0 < float(config.feature_fraction) <= 1.0):
            raise ValueError("feature_fraction must be in (0, 1] for RF")
        super().init(config, train_data)
        if self.objective is None:
            raise ValueError("RF mode does not support custom objectives")
        self.average_output = True
        self.shrinkage_rate = 1.0
        self._train_step = None  # running-average updates: sync driver path
        # boost once from constant init scores (reference rf.hpp Boosting)
        K = self.num_tree_per_iteration
        self._rf_init_scores = np.zeros(K)
        if self.config.boost_from_average:
            for k in range(K):
                self._rf_init_scores[k] = self.objective.boost_from_score(k)
        tmp = jnp.asarray(
            np.repeat(self._rf_init_scores[:, None],
                      self.train_data.num_data, axis=1).astype(np.float32))
        g, h = self.objective.get_gradients(tmp)
        if g.ndim == 1:
            g, h = g[None, :], h[None, :]
        self._rf_grad = np.asarray(jax.device_get(g), np.float32)
        self._rf_hess = np.asarray(jax.device_get(h), np.float32)

    def _replay_scale(self) -> float:
        it = max(self.iter_ + self.num_init_iteration, 1)
        return 1.0 / it

    def reset_training_data(self, data) -> None:
        super().reset_training_data(data)
        # RF keeps FIXED gradients from the constant init scores; they are
        # per-row and must be re-derived for the new rows (rf.hpp
        # ResetTrainingData -> Boosting)
        K = self.num_tree_per_iteration
        tmp = jnp.asarray(
            np.repeat(self._rf_init_scores[:, None],
                      self.train_data.num_data, axis=1).astype(np.float32))
        g, h = self.objective.get_gradients(tmp)
        if g.ndim == 1:
            g, h = g[None, :], h[None, :]
        self._rf_grad = np.asarray(jax.device_get(g), np.float32)
        self._rf_hess = np.asarray(jax.device_get(h), np.float32)
        self._train_step = None  # running-average updates: sync path

    def _train_one_iter_impl(self, grad, hess, snap) -> bool:
        # base-class wrapper (train_one_iter) owns the stall check,
        # rollback snapshot, fault point, and numeric guard
        if grad is not None or hess is not None:
            raise ValueError("RF mode does not support custom gradients")
        mask = self.bagging_mask(self.iter_)
        K = self.num_tree_per_iteration
        it = self.iter_ + self.num_init_iteration
        for k in range(K):
            need = self.objective.class_need_train(k)
            tree = None
            if need:
                tree, leaf_ids, _ = self.learner.train(
                    jnp.asarray(self._rf_grad[k]),
                    jnp.asarray(self._rf_hess[k]), mask)
            if tree is not None and tree.num_leaves > 1:
                init = self._rf_init_scores[k]
                if self.objective.needs_renew:
                    leaf_np = np.asarray(jax.device_get(leaf_ids))
                    score_np = np.full(self.train_data.num_data, init)
                    mask_np = (np.ones(len(leaf_np), bool) if mask is None
                               else np.asarray(jax.device_get(mask))
                               [:len(leaf_np)] > 0)
                    self.objective.renew_tree_output(
                        tree, score_np, leaf_np, mask_np)
                if abs(init) > K_EPSILON:
                    tree.add_bias(init)
                self._update_average_score(tree, k, it)
            else:
                tree = Tree(2)
                if len(self.models) < K:
                    output = (self.objective.boost_from_score(k)
                              if not need else self._rf_init_scores[k])
                    tree.as_constant_tree(output)
                    self._update_average_score(tree, k, it)
            self.models.append(tree)
        self.iter_ += 1
        return False

    def _update_average_score(self, tree: Tree, class_id: int, it: int):
        """score = (score * it + tree_pred) / (it + 1)
        (reference rf.hpp MultiplyScore sandwich, :146-149)."""
        meta = self.learner.meta_np
        from .gbdt import _predict_binned
        delta = _predict_binned(tree, self.train_data.bins, meta) \
            .astype(np.float32)
        self.train_scores.multiply(class_id, float(it))
        self.train_scores.add(class_id, jnp.asarray(delta))
        self.train_scores.multiply(class_id, 1.0 / (it + 1))
        for vs, vd in zip(self.valid_scores, self.valid_sets):
            d = _predict_binned(tree, vd.bins, meta).astype(np.float32)
            vs.multiply(class_id, float(it))
            vs.add(class_id, jnp.asarray(d))
            vs.multiply(class_id, 1.0 / (it + 1))

    def rollback_one_iter(self) -> None:
        if self.iter_ <= 0:
            return
        K = self.num_tree_per_iteration
        it = self.iter_ + self.num_init_iteration - 1
        meta = self.learner.meta_np
        from .gbdt import _predict_binned
        for k in range(K):
            tree = self.models.pop()
            k_id = K - 1 - k
            if it >= 0:
                self.train_scores.multiply(k_id, float(it + 1))
                self.train_scores.add(k_id, jnp.asarray(
                    -_predict_binned(tree, self.train_data.bins, meta)
                    .astype(np.float32)))
                for vs, vd in zip(self.valid_scores, self.valid_sets):
                    vs.multiply(k_id, float(it + 1))
                    vs.add(k_id, jnp.asarray(
                        -_predict_binned(tree, vd.bins, meta)
                        .astype(np.float32)))
                if it > 0:
                    self.train_scores.multiply(k_id, 1.0 / it)
                    for vs in self.valid_scores:
                        vs.multiply(k_id, 1.0 / it)
        self.iter_ -= 1
