"""GBDT boosting driver (reference src/boosting/gbdt.cpp:368-449).

Owns the tree models, per-dataset raw-score vectors, the objective/metrics,
and the TPU tree learner.  One `train_one_iter` =
boost-from-average -> GetGradients (device) -> bagging mask -> per-class
grow-tree (device) -> RenewTreeOutput -> Shrinkage -> score update
(device gather for train, binned traversal for valids) — the same contract
as the reference driver, with mask-based bagging instead of index-subset
copies (SURVEY.md §7 M4).
"""

from __future__ import annotations

import io
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import Config
from ..io.bin_mapper import BinMapper, MissingType
from ..io.dataset import TrainingData
from ..utils import faultline, membudget
from ..ops.predict import (PackedForest, feature_meta_dev, device_tables,
                           forest_class_scores, forest_leaf_values,
                           pack_trees, row_bucket)
from ..utils import timer
from .learner import TPUTreeLearner, make_tree_learner
from .metrics import Metric, create_metrics
from .objectives import (Objective, create_objective,
                         create_objective_from_model_string)
from .tree import Tree

K_EPSILON = 1e-15


def quant_headroom_check(precision: str, total_rows: int, mode: str) -> int:
    """int32 histogram-accumulator headroom sentinel (quantized mode).

    `quant_limit` already narrows the gradient grid so a worst-case bin
    cannot overflow int32, which means overflow is impossible but the
    effective quantization mantissa silently shrinks with the global row
    count.  The sentinel makes that visible: warn when the grid has
    narrowed below the dtype's own range, raise (under
    tpu_guard_numerics=raise) once the grid has lost two bits of the
    dtype's range (floor capped at 128, i.e. 7 effective bits, for wide
    dtypes) — at that point quantized split decisions are mostly noise.
    The floor is precision-relative: a flat 128 would make int8 (dtype
    max 127) raise on ANY narrowing."""
    from ..ops.histogram import _INT_TYPE_MAX, quant_limit
    from ..utils.log import LightGBMError, Log

    q = quant_limit(precision, total_rows)
    full = _INT_TYPE_MAX[precision]
    if q < full:
        msg = (f"int32 histogram headroom: {total_rows} rows narrow the "
               f"{precision} gradient grid to +-{q} (dtype max +-{full})")
        if mode == "raise" and q < min(128, full // 4):
            raise LightGBMError(
                msg + "; use a wider precision or fewer global rows")
        Log.warning(msg)
    return q

# model-string trailer carrying the bin-mapper snapshot (written by
# save_model_to_string, parsed back by from_model_string)
_MAPPER_MARKER = "tpu_bin_mappers:"

# training-quality histogram ladders (obs registry): leaf counts and
# tree depths are small ints; powers-of-two-ish bounds keep the
# distributions readable at any num_leaves
_LEAF_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
                 48.0, 64.0, 96.0, 128.0, 192.0, 256.0, 384.0, 512.0,
                 768.0, 1024.0)
_DEPTH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 10.0, 12.0, 16.0,
                  20.0, 24.0, 32.0, 48.0, 64.0)


def _predict_binned(tree: Tree, bins: np.ndarray,
                    meta: Dict[str, np.ndarray]) -> np.ndarray:
    """Leaf values via bin-space traversal (NumericalDecisionInner,
    reference tree.h:252-270) — used for validation-score updates."""
    n = bins.shape[0]
    if tree.num_leaves == 1:
        return np.full(n, tree.leaf_value[0])
    node = np.zeros(n, dtype=np.int32)
    num_bin = meta["num_bin"]
    default_bin = meta["default_bin"]
    missing = meta["missing_type"]
    for _ in range(tree.max_depth()):
        active = node >= 0
        if not active.any():
            break
        nid = node[active]
        f = tree.split_feature_inner[nid]
        fbin = bins[active, f].astype(np.int64)
        mt = missing[f]
        is_missing = np.where(
            mt == int(MissingType.NAN), fbin == num_bin[f] - 1,
            np.where(mt == int(MissingType.ZERO), fbin == default_bin[f], False))
        dt = tree.decision_type[nid]
        default_left = (dt & 2) != 0
        go_left = np.where(is_missing, default_left,
                           fbin <= tree.threshold_in_bin[nid])
        is_cat = (dt & 1) != 0
        if is_cat.any():
            # bin-space bitset membership (CategoricalDecisionInner,
            # reference tree.h:307-318): bins in the set go left
            cat_words = np.asarray(tree.cat_threshold_inner, dtype=np.uint32)
            cat_bounds = np.asarray(tree.cat_boundaries_inner, dtype=np.int64)
            cat_idx = tree.threshold_in_bin[nid].astype(np.int64)
            cat_idx = np.clip(cat_idx, 0, len(cat_bounds) - 2)
            start = cat_bounds[cat_idx]
            width = cat_bounds[cat_idx + 1] - start
            word_idx = fbin // 32
            in_range = word_idx < width
            word = (cat_words[np.clip(start + word_idx, 0,
                                      len(cat_words) - 1)]
                    if len(cat_words) else np.zeros(len(nid), np.uint32))
            bit = (word >> (fbin % 32).astype(np.uint32)) & 1
            go_left = np.where(is_cat, in_range & (bit == 1), go_left)
        node[active] = np.where(go_left, tree.left_child[nid],
                                tree.right_child[nid]).astype(np.int32)
    return tree.leaf_value[~node]


def _split_mapper_snapshot(text: str):
    """Split a model string into (model_text, _PredictContext | None) —
    the `tpu_bin_mappers:` analog of Booster's pandas_categorical
    split."""
    import json

    marker = "\n" + _MAPPER_MARKER
    pos = text.rfind(marker)
    if pos < 0:
        return text, None
    line_end = text.find("\n", pos + 1)
    payload = text[pos + len(marker): len(text) if line_end < 0
                   else line_end].strip()
    rest = "" if line_end < 0 else text[line_end:]
    try:
        ctx = _PredictContext.from_payload(json.loads(payload))
    except (json.JSONDecodeError, KeyError, ValueError, TypeError) as exc:
        raise ValueError(
            f"corrupt tpu_bin_mappers line in model: {payload[:80]!r}"
        ) from exc
    return text[:pos] + rest, ctx


def _rebind_tree_to_mappers(tree: Tree, mappers: List[BinMapper],
                            used_pos: Dict[int, int]) -> None:
    """Map a tree's real-feature splits into the given mappers' bin
    space (split_feature_inner / threshold_in_bin / *_inner bitsets) —
    shared by init_model continuation and model-string reload."""
    cat_nodes: Dict[int, List[int]] = {}  # cat_idx -> bin words
    for j in range(tree.num_leaves - 1):
        real_f = int(tree.split_feature[j])
        if real_f not in used_pos:
            raise ValueError(
                f"model splits on feature {real_f} which is trivial/"
                "unused in the binning context")
        tree.split_feature_inner[j] = used_pos[real_f]
        mapper = mappers[real_f]
        if int(tree.decision_type[j]) & 1:
            # categorical: decode the raw-category value bitset, re-map
            # each category to its bin under these mappers, re-encode
            cat_idx = int(tree.threshold[j])
            start = tree.cat_boundaries[cat_idx]
            end = tree.cat_boundaries[cat_idx + 1]
            words = tree.cat_threshold[start:end]
            cats = [w * 32 + b for w, word in enumerate(words)
                    for b in range(32) if (int(word) >> b) & 1]
            bins = [mapper.categorical_2_bin[c] for c in cats
                    if c in mapper.categorical_2_bin]
            bw = [0] * (max(bins) // 32 + 1 if bins else 1)
            for b in bins:
                bw[b // 32] |= 1 << (b % 32)
            cat_nodes[cat_idx] = bw
        else:
            tree.threshold_in_bin[j] = mapper.value_to_bin(
                float(tree.threshold[j]))
    if cat_nodes:
        bounds, words = [0], []
        for ci in range(tree.num_cat):
            bw = cat_nodes.get(ci, [0])
            words.extend(bw)
            bounds.append(bounds[-1] + len(bw))
        tree.cat_boundaries_inner = bounds
        tree.cat_threshold_inner = words


class _ScoreState:
    """Per-dataset raw scores [k, n], device-resident for train."""

    def __init__(self, num_class: int, num_data: int,
                 init_score: Optional[np.ndarray] = None):
        scores = np.zeros((num_class, num_data), np.float32)
        self.has_init_score = init_score is not None
        if init_score is not None:
            s = np.asarray(init_score, np.float64)
            if s.size == num_data * num_class:
                scores += s.reshape(num_class, num_data) if s.ndim == 1 \
                    else s.T.astype(np.float32)
            else:
                scores += s.reshape(1, -1)
        # .copy() forces an XLA-owned buffer: on CPU, asarray of
        # aligned host memory is zero-copy, and this buffer is later
        # DONATED by the train step — donating a numpy-aliased buffer
        # corrupts the heap (XLA rewrites memory numpy owns)
        self.scores = jnp.asarray(scores).copy()

    def add_constant(self, val: float, class_id: int):
        self.scores = self.scores.at[class_id].add(np.float32(val))

    def add(self, class_id: int, delta):
        self.scores = self.scores.at[class_id].add(delta)

    def multiply(self, class_id: int, val: float):
        """Scale one class's scores (RF running average,
        reference score_updater.hpp MultiplyScore)."""
        self.scores = self.scores.at[class_id].multiply(np.float32(val))

    def numpy(self) -> np.ndarray:
        return np.asarray(jax.device_get(self.scores), np.float64)


class GBDT:
    """The gradient boosting driver."""

    def __init__(self):
        self.models: List[Tree] = []
        self.iter_ = 0
        self.num_init_iteration = 0
        self.config: Optional[Config] = None
        self.objective: Optional[Objective] = None
        self.train_data: Optional[TrainingData] = None
        self.learner: Optional[TPUTreeLearner] = None
        self.metrics: List[Metric] = []
        self.valid_sets: List[TrainingData] = []
        self.valid_names: List[str] = []
        self.valid_metrics: List[List[Metric]] = []
        self.valid_scores: List[_ScoreState] = []
        self.train_scores: Optional[_ScoreState] = None
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.shrinkage_rate = 0.1
        self.feature_names: List[str] = []
        self.max_feature_idx = 0
        self.loaded_params: Dict = {}
        self.label_index = 0
        self._bag_rng: Optional[np.random.Generator] = None
        self._pending: List[Tuple] = []
        self._stopped = False
        self._train_step = None
        self._bag_cfg = None
        self._goss_cfg = None          # set by GOSS subclass
        self.average_output = False    # set by RF subclass / model load
        # training reference profile (obs/modelhealth.py): parsed from
        # a loaded model's tpu_feature_profile: trailer, or snapshotted
        # by free_dataset; live training boosters rebuild it per save
        self._profile = None
        # sync-path trees awaiting telemetry until the numerics guard
        # accepts the iteration (train_one_iter)
        self._note_after_guard = None
        # OOM degradation ladder (ISSUE 15): position persists across
        # recoveries so repeated OOMs keep descending, never loop
        self._mem_ladder = membudget.DegradationLadder()
        # cross-iteration learner state (feature RNG, CEGB planes) held
        # across a ladder rebuild: set when the old learner's device
        # buffers are dropped, cleared when a rebuild succeeds — so a
        # FAILED rebuild followed by a further descent still restores
        # the stream state onto the eventual replacement (bitwise)
        self._ladder_carry = None

    # ------------------------------------------------------------------
    def init(self, config: Config, train_data: TrainingData) -> None:
        self.config = config
        self.train_data = train_data
        # cap (or restore: the native side maps n<=0 back to the captured
        # startup default) the walker's OpenMP pool unconditionally, so a
        # cap from a previous Booster never leaks into this training
        # (reference honors num_threads process-wide via
        # omp_set_num_threads)
        from ..native import set_num_threads

        set_num_threads(int(config.num_threads))
        self.num_class = int(config.num_class)
        self.shrinkage_rate = float(config.learning_rate)
        self.objective = create_objective(config)
        if self.objective is not None:
            self.objective.init(train_data.metadata, train_data.num_data)
            self.num_tree_per_iteration = self.objective.num_model_per_iteration()
        else:
            self.num_tree_per_iteration = self.num_class
        self.learner = make_tree_learner(config, train_data)
        self.metrics = create_metrics(
            config, self.objective.name if self.objective else "")
        for m in self.metrics:
            m.init(train_data.metadata, train_data.num_data)
        self.train_scores = _ScoreState(self.num_tree_per_iteration,
                                        train_data.num_data,
                                        train_data.metadata.init_score)
        self.feature_names = list(train_data.feature_names)
        self.max_feature_idx = train_data.num_total_features - 1
        self._bag_rng = np.random.default_rng(int(config.bagging_seed))
        self._boosted_from_average = [False] * self.num_tree_per_iteration
        # async fast path: fused device step + lazily materialized trees
        self._pending: List[Tuple] = []
        self._stopped = False
        self._key = jax.random.PRNGKey(int(config.seed))
        self._bag_key = jax.random.PRNGKey(int(config.bagging_seed))
        self._train_step = None
        self._bag_cfg = self._bagging_config()
        # numeric guardrails (tpu_guard_numerics=off|warn|raise|skip):
        # validated here so a typo fails at init, not mid-run; the
        # quantized headroom sentinel is a one-time init check
        self._guard = str(config.tpu_guard_numerics).strip().lower()
        if self._guard not in ("off", "warn", "raise", "skip"):
            raise ValueError("tpu_guard_numerics must be off|warn|raise|"
                             f"skip, got {self._guard!r}")
        self._guard_streak = 0
        self._guard_skips_total = 0
        # collective watchdog defaults (Network::Init analog): armed
        # process-wide so metric sync / checkpoint barriers / binning
        # allgathers all share one deadline policy; no-clobber rule
        # lives in configure_from_config
        from ..parallel.collective import configure_from_config

        configure_from_config(config)
        # telemetry policy (tpu_telemetry / tpu_trace_dir) is process-
        # global under the same no-clobber convention
        obs.configure_from_config(config)
        if self._guard != "off" \
                and str(config.tpu_hist_precision) in ("int8", "int16"):
            quant_headroom_check(str(config.tpu_hist_precision),
                                 train_data.num_data, self._guard)
        if self.learner.params.has_cegb and self._goss_cfg is not None:
            raise NotImplementedError(
                "CEGB penalties do not compose with GOSS yet")
        # pre-partitioned rows: every statistic that must be GLOBAL
        # either reduces (metrics, boost-from-average, the renew leaf
        # averaging in _renew_and_update) or is local by the reference's
        # own distributed semantics (GOSS sampling, per-query ranking
        # lambdas, per-machine percentile renew)
            # GOSS composes: its threshold/sample run over LOCAL rows,
            # which is the reference's distributed behavior too (each
            # machine subsets its own data, goss.hpp Bagging override)
        self._maybe_make_train_step()
        # HBM preflight (ISSUE 15): predict peak device bytes from the
        # live buffers + closed-form models and enforce the budget
        # BEFORE iteration 0 burns a compile on a doomed configuration
        self._run_preflight()

    def _maybe_make_train_step(self) -> None:
        """(Re)build the fused async step when the configuration supports
        it — the ONE place that owns the eligibility rule, so every
        rebuild site (init / reset_training_data / reset_config) applies
        identical conditions."""
        self._train_step = None
        if (self.objective is not None and not self.objective.needs_renew
                and not self.objective.host_only
                # CEGB threads cross-tree used/paid state through
                # learner.train (the sync path); the fused step's meta is
                # closure-captured and cannot carry it
                and not self.learner.params.has_cegb
                # multi-host meshes need learner.train's global array
                # placement (put_global); the fused step mixes local
                # score state into the global-mesh program
                and not self.learner._multiproc
                # the streamed layout has no device-resident bins_t for
                # the fused step to close over: its train() drives the
                # per-block host loop (ops/stream.py) — sync path only
                and not self.learner.stream_layout
                and all(self.objective.class_need_train(k)
                        for k in range(self.num_tree_per_iteration))):
            self._train_step = self.learner.make_train_step(
                self.objective.get_gradients, self.shrinkage_rate,
                self._bag_cfg, self._goss_cfg)

    def _bagging_config(self) -> Optional[Dict]:
        cfg = self.config
        frac = float(cfg.bagging_fraction)
        freq = int(cfg.bagging_freq)
        pos_frac = float(cfg.pos_bagging_fraction)
        neg_frac = float(cfg.neg_bagging_fraction)
        balanced = (pos_frac < 1.0 or neg_frac < 1.0)
        if freq <= 0 or (frac >= 1.0 and not balanced):
            return None
        out = {"fraction": frac, "pos_fraction": pos_frac,
               "neg_fraction": neg_frac, "freq": freq}
        if balanced:
            label = np.asarray(self.train_data.metadata.label)
            is_pos = np.zeros(self.learner.n_pad, bool)
            is_pos[:len(label)] = label > 0
            out["is_pos"] = is_pos
        return out

    def reset_training_data(self, data: TrainingData) -> None:
        """Swap the training dataset, replaying the existing model onto the
        new rows (reference GBDT::ResetTrainingData via
        LGBM_BoosterResetTrainingData, c_api.h:436): bins must come from
        the same mappers (created with reference=old dataset)."""
        if self.train_data is None or self.config is None:
            # file-loaded boosters carry no training context, and their
            # trees are not bound to bin space — a clear error beats a
            # late AttributeError (continuation uses init_model instead)
            raise ValueError(
                "reset_training_data needs a booster constructed with a "
                "training dataset; load continuation goes through "
                "init_model")
        if data.mappers is not self.train_data.mappers:
            raise ValueError("new training data must be created with "
                             "reference=the original dataset")
        self._materialize()
        self.train_data = data
        self.learner = make_tree_learner(self.config, data)
        if self.objective is not None:
            self.objective.init(data.metadata, data.num_data)
        self.metrics = create_metrics(
            self.config, self.objective.name if self.objective else "")
        for m in self.metrics:
            m.init(data.metadata, data.num_data)
        self.train_scores = _ScoreState(self.num_tree_per_iteration,
                                        data.num_data,
                                        data.metadata.init_score)
        # replay the whole model onto the new rows: one device pass over
        # the packed forest (class = position % K), host walker fallback
        if not self._replay_scores_device(self.train_scores, data,
                                          self.models,
                                          scale=self._replay_scale(),
                                          cache_bins=False):
            K = max(self.num_tree_per_iteration, 1)
            for k in range(K):
                trees = [t for i, t in enumerate(self.models)
                         if i % K == k and t.num_leaves >= 1]
                if trees:
                    self.train_scores.add(k, jnp.asarray(
                        (self._replay_scale() * self._score_trees_binned(
                            data.bins, trees, [1.0] * len(trees)))
                        .astype(np.float32)))
        # stale per-dataset state: bagging mask and the fused step close
        # over the old row count (reference ResetTrainingData rebuilds its
        # bagging buffers too)
        self._cached_bag_mask = None
        self._pending = []
        self._stopped = False
        self._bag_cfg = self._bagging_config()
        self._maybe_make_train_step()

    def _replay_scale(self) -> float:
        """Scale applied when replaying stored trees onto new data
        (RF overrides: scores are a running AVERAGE of tree outputs)."""
        return 1.0

    def add_valid(self, data: TrainingData, name: str) -> None:
        if data.mappers is not self.train_data.mappers:
            raise ValueError("validation set must be created with "
                             "reference=train dataset")
        self.valid_sets.append(data)
        self.valid_names.append(name)
        ms = create_metrics(self.config,
                            self.objective.name if self.objective else "")
        for m in ms:
            m.init(data.metadata, data.num_data)
        self.valid_metrics.append(ms)
        self.valid_scores.append(_ScoreState(
            self.num_tree_per_iteration, data.num_data,
            data.metadata.init_score))
        # replay existing model onto the new valid set: one packed-forest
        # device pass when eligible, host walker otherwise
        if not self._replay_scores_device(self.valid_scores[-1], data,
                                          self.models):
            meta = self.learner.meta_np
            for i, tree in enumerate(self.models):
                k = i % self.num_tree_per_iteration
                self.valid_scores[-1].add(
                    k, jnp.asarray(_predict_binned(tree, data.bins, meta)
                                   .astype(np.float32)))

    # ------------------------------------------------------------------
    def _boost_from_average(self, class_id: int) -> float:
        if (self.models or self._boosted_from_average[class_id]
                or self.objective is None
                or self.train_scores.has_init_score):
            return 0.0
        self._boosted_from_average[class_id] = True
        if not self.config.boost_from_average:
            return 0.0
        init = self.objective.boost_from_score(class_id)
        if self.learner is not None and self.learner._multiproc:
            # every rank's init comes from its LOCAL rows; agree on the
            # cross-machine mean like the reference
            # (ObtainAutomaticInitialScore -> GlobalSyncUpByMean,
            # gbdt.cpp:333-342).  Identity in the replicated-data mode.
            from ..parallel.metric_sync import process_count, sync_sums

            init = float(sync_sums([init])[0] / process_count())
        if abs(init) > K_EPSILON:
            self.train_scores.add_constant(init, class_id)
            for vs in self.valid_scores:
                vs.add_constant(init, class_id)
            return init
        return 0.0

    def bagging_mask(self, it: int) -> Optional[jnp.ndarray]:
        """Row mask for this iteration (None = all rows). Mask-based analog
        of reference GBDT::Bagging (gbdt.cpp:210-276)."""
        cfg = self.config
        frac = float(cfg.bagging_fraction)
        freq = int(cfg.bagging_freq)
        pos_frac = float(cfg.pos_bagging_fraction)
        neg_frac = float(cfg.neg_bagging_fraction)
        balanced = (pos_frac < 1.0 or neg_frac < 1.0)
        if freq <= 0 or (frac >= 1.0 and not balanced):
            return None
        if it % freq != 0 and self._cached_bag_mask is not None:
            return self._cached_bag_mask
        n = self.train_data.num_data
        if balanced:
            label = np.asarray(self.train_data.metadata.label)
            is_pos = label > 0
            r = self._bag_rng.random(n)
            keep = np.where(is_pos, r < pos_frac, r < neg_frac)
        else:
            cnt = int(n * frac)
            idx = self._bag_rng.choice(n, size=cnt, replace=False)
            keep = np.zeros(n, bool)
            keep[idx] = True
        mask = jnp.asarray(keep.astype(np.float32))
        self._cached_bag_mask = mask
        return mask

    _cached_bag_mask = None
    # guardrail defaults for drivers that never ran init() (file-loaded
    # predict-only boosters)
    _guard = "off"
    _guard_streak = 0
    _guard_skips_total = 0
    _GUARD_MAX_STREAK = 5
    # set by a skip-mode rollback: the retry must draw a FRESH bagging
    # mask even off the bagging_freq boundary, or it would replay the
    # poisoned iteration bit-identically
    _force_bag_refresh = False

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[jnp.ndarray] = None,
                       hess: Optional[jnp.ndarray] = None) -> bool:
        """One boosting iteration; True when training has stalled.

        The iteration applies ATOMICALLY: SIGTERM / KeyboardInterrupt /
        an XLA runtime error (or an armed `grow_step` fault) anywhere
        inside rolls the partial iteration back — scores, PRNG streams,
        pending trees and counters return to their pre-iteration state
        before the exception re-raises — so the booster stays usable
        (predict / continue-training / checkpoint-flush) after an
        interrupt.  tpu_guard_numerics adds a per-iteration isfinite
        check on the updated scores (warn | raise | skip; skip =
        rollback + re-bag).

        A classified device OOM (membudget.DeviceOutOfMemory from any
        guarded site inside the iteration) rides the same rollback,
        then descends one deterministic, bitwise-invisible degradation-
        ladder step and RETRIES the iteration — ladder exhaustion
        raises a structured MemoryLadderExhausted instead (ISSUE 15)."""
        while True:
            try:
                return self._train_one_iter_guarded(grad, hess)
            except membudget.DeviceOutOfMemory as exc:
                # the partial iteration was already rolled back by the
                # guarded body; recover (one ladder step) or re-raise
                # structured
                self._recover_from_oom(exc)

    def _train_one_iter_guarded(self, grad, hess) -> bool:
        if self._stopped:
            return True
        if self.learner is None and self._ladder_carry is not None:
            # a ladder rebuild OOMed and the run ended exhausted;
            # pressure may have subsided since — retry the rebuild
            # (classified on failure, riding the same recovery loop) so
            # continue-training stays possible after an exhaustion
            self._rebuild_learner()
        self._note_after_guard = None
        snap = self._iter_snapshot()
        try:
            with obs.span("train/iteration", iteration=self.iter_), \
                    membudget.oom_guard("train_step",
                                        iteration=self.iter_):
                action = faultline.fire("grow_step", iteration=self.iter_)
                ret = self._train_one_iter_impl(grad, hess, snap)
        except BaseException:
            self._iter_restore(snap)
            raise
        if action == "poison":
            # fault harness: NaN-poison this iteration's scores so the
            # guardrail modes below are exercised deterministically
            self.train_scores.scores = (self.train_scores.scores
                                        + jnp.float32(np.nan))
        if self._guard != "off" and not ret and not self._scores_finite():
            return self._poisoned_iteration(snap)
        self._guard_streak = 0
        self._force_bag_refresh = False  # the skip retry (if any) is done
        # sync-path trees survived the guard: record their telemetry now
        if self._note_after_guard:
            for t in self._note_after_guard:
                self._note_tree_telemetry(t)
            self._note_after_guard = None
        return ret

    def _train_one_iter_impl(self, grad, hess, snap) -> bool:
        if (grad is None or hess is None) and self._train_step is not None:
            return self._train_one_iter_fused(snap)
        return self._train_one_iter_sync(grad, hess)

    def _train_one_iter_fused(self, snap) -> bool:
        """Fast path: one fused async device dispatch per class and NO
        host<->device sync; host Tree objects materialize lazily at
        eval/predict/save time (`_materialize`)."""
        # the fused step is one async dispatch holding the histogram
        # pool + score buffers; its watermark is tagged hist_build (the
        # grow program owns the [L, G/P, B, 3] pool, the dominant HBM
        # consumer).  Async means the bracket reads allocation, not
        # execution — an under-estimate on accelerators, never an
        # over-estimate
        with timer.PHASE("train_dispatch"), \
                obs.resources.phase_peak("hist_build"):
            bag = self._bag_cfg
            extra = {}
            if self._goss_cfg is not None:
                extra["goss_on"] = self.iter_ >= self._goss_cfg["warmup"]
            inits = [self._boost_from_average(k)
                     for k in range(self.num_tree_per_iteration)]
            base_scores = self.train_scores.scores
            if getattr(self.learner, "_donate", False):
                # the step donates the scores buffer (arg 1); at class 0
                # base_scores IS that buffer, so snapshot a copy — a
                # donated-then-read alias would either spam copy warnings
                # or (multiclass) read a deleted buffer at class 1
                base_scores = jnp.copy(base_scores)
                if snap is not None \
                        and snap["scores"] is self.train_scores.scores:
                    # the pre-iteration buffer is about to be DONATED;
                    # the copy (bitwise equal — no boost-from-average
                    # constant was added this iteration, or the buffers
                    # would already differ) becomes the live rollback
                    # snapshot
                    snap["scores"] = base_scores
            pool = getattr(self.learner, "_pool", None)
            for k in range(self.num_tree_per_iteration):
                refresh = bag is not None and (
                    self.iter_ % bag["freq"] == 0
                    or self._force_bag_refresh)
                (records, scores, leaf_ids, leaf_out, self._key,
                 self._bag_key, pool) = self._train_step(
                    base_scores, self.train_scores.scores,
                    self._key, self._bag_key, pool, k, refresh, **extra)
                self.train_scores.scores = scores
                if pool is not None:
                    # write the donated pool back IMMEDIATELY: the step
                    # deleted the previous buffer, so deferring this past
                    # a raising later class would leave learner._pool
                    # pointing at a deleted array and break every
                    # subsequent update()
                    self.learner._pool = pool
                # quantized leaf refit: the host Tree must take its leaf
                # values from the refitted device vector, not the records
                self._pending.append((
                    records,
                    leaf_out if self.learner.refits_leaves else None,
                    k, inits[k]))
            self.iter_ += 1
        return False

    # -- atomic-iteration rollback -------------------------------------
    def _iter_snapshot(self) -> Dict:
        """Cheap pre-iteration capture for atomic rollback: array
        REFERENCES (jax arrays are immutable; the one donation hazard is
        patched inside the fused path) plus host RNG/counter state."""
        snap = {
            "scores": (self.train_scores.scores
                       if self.train_scores is not None else None),
            "valid": [vs.scores for vs in self.valid_scores],
            "key": getattr(self, "_key", None),
            "bag_key": getattr(self, "_bag_key", None),
            "pending": len(self._pending),
            "models": len(self.models),
            "bfa": list(getattr(self, "_boosted_from_average", [])),
            "bag_mask": self._cached_bag_mask,
            "bag_rng": (self._bag_rng.bit_generator.state
                        if self._bag_rng is not None else None),
            "feature_rng": (self.learner._feature_rng.bit_generator.state
                            if self.learner is not None and
                            getattr(self.learner, "_feature_rng", None)
                            is not None else None),
            "iter": self.iter_,
            "stopped": self._stopped,
            "shrinkage": self.shrinkage_rate,
        }
        snap.update(self._snapshot_extra())
        return snap

    def _snapshot_extra(self) -> Dict:
        return {}

    def _restore_extra(self, snap: Dict) -> None:
        pass

    def _iter_restore(self, snap: Dict) -> None:
        """Roll a partially-applied iteration back to its snapshot."""
        if self.train_scores is not None and snap["scores"] is not None:
            self.train_scores.scores = snap["scores"]
        for vs, s in zip(self.valid_scores, snap["valid"]):
            vs.scores = s
        if snap["key"] is not None:
            self._key = snap["key"]
        if snap["bag_key"] is not None:
            self._bag_key = snap["bag_key"]
        del self._pending[snap["pending"]:]
        del self.models[snap["models"]:]
        if snap["bfa"]:
            self._boosted_from_average = snap["bfa"]
        self._cached_bag_mask = snap["bag_mask"]
        if snap["bag_rng"] is not None:
            self._bag_rng.bit_generator.state = snap["bag_rng"]
        if snap["feature_rng"] is not None:
            self.learner._feature_rng.bit_generator.state = \
                snap["feature_rng"]
        self.iter_ = snap["iter"]
        self._stopped = snap["stopped"]
        self.shrinkage_rate = snap["shrinkage"]
        # a failed DONATING dispatch may have consumed the threaded
        # histogram pool; it is per-iteration scratch, so zeros restore
        # it bit-equivalently
        pool = (getattr(self.learner, "_pool", None)
                if self.learner is not None else None)
        try:
            deleted = pool is not None and pool.is_deleted()
        except AttributeError:  # pragma: no cover - old jaxlib
            deleted = False
        if deleted:
            self.learner.reset_pool()
        self._invalidate_tables()
        self._restore_extra(snap)

    # -- memory-pressure recovery (membudget, ISSUE 15) ----------------
    def _oom_recoverable(self) -> bool:
        """May a classified OOM descend the degradation ladder here?
        Needs a live training context and tpu_oom_recovery=true; multi-
        process groups always propagate instead — a one-sided retry
        would desynchronize the collective streams."""
        if (self.config is None or self.train_data is None
                or not bool(self.config.tpu_oom_recovery)):
            return False
        if self.learner is None:
            # mid-rebuild (the ladder dropped the old learner and the
            # replacement OOMed): the parked carry marks a live context
            return self._ladder_carry is not None
        return not getattr(self.learner, "_multiproc", False)

    def _recover_from_oom(self, exc: "membudget.DeviceOutOfMemory",
                          in_recovery: bool = False) -> None:
        """One ladder descent after a rolled-back OOM iteration, or the
        structured exhaustion error (blackbox dumped WITH the memory
        snapshot; engine.train then flushes the final checkpoint).

        `in_recovery` marks re-entry from a failed ladder REBUILD:
        recoverability was already established for this episode, and the
        learner reference is legitimately None mid-rebuild."""
        from ..utils.log import Log

        if not (in_recovery or self._oom_recoverable()):
            # recovery disabled (or a multi-host group): the classified
            # OOM propagates AS ITSELF — labeling it ladder exhaustion
            # would send the postmortem reader chasing a ladder that
            # was never tried
            obs.flightrecorder.note(
                "oom", "oom_propagated", site=exc.site,
                recovery="off",
                **{k: v for k, v in membudget.memory_snapshot().items()
                   if v is not None})
            obs.flightrecorder.dump("oom_unrecovered", exc=exc)
            raise exc
        step = self._mem_ladder.next_step(self.config)
        if step is None:
            taken = self._mem_ladder.describe()
            obs.flightrecorder.note(
                "oom", "ladder_exhausted", site=exc.site,
                steps_taken=",".join(taken) or "none",
                **{k: v for k, v in membudget.memory_snapshot().items()
                   if v is not None})
            err = membudget.MemoryLadderExhausted(
                f"device out of memory at {exc.site!r} and the "
                "degradation ladder is exhausted "
                f"(steps taken: {taken or 'none'}); the failed "
                "iteration was rolled back — the booster is usable and "
                "a final checkpoint covers the last complete iteration",
                site=exc.site, info=dict(exc.info))
            obs.flightrecorder.dump("oom_ladder_exhausted", exc=err)
            raise err from exc
        name, overrides = step
        membudget.note_ladder_step(exc.site, name, overrides)
        Log.warning(
            f"device OOM at {exc.site!r} (iteration {self.iter_}): "
            f"rolled back; degradation ladder step {name!r} applies "
            f"{overrides} — retrying (bitwise-invisible: the settled "
            "model is byte-identical to an undisturbed run at this "
            "config)")
        try:
            self.apply_memory_degradation(overrides)
        except membudget.DeviceOutOfMemory as rebuild_exc:
            # the learner rebuild itself OOMed on the still-full device:
            # descend again (no new rollback needed — no iteration is in
            # flight), so persistent pressure still ends in the
            # structured exhaustion contract, not a mid-recovery abort
            self._recover_from_oom(rebuild_exc, in_recovery=True)

    def apply_memory_degradation(self, overrides: Dict) -> None:
        """Apply ladder-step param overrides to the LIVE training run.

        Chunk-size overrides take effect at the next launch; the
        aggregation / bucket-policy overrides rebuild the learner (and
        the fused step) in place — cross-iteration learner state
        (feature-fraction RNG, CEGB used/paid planes) carries over so
        the retry stays bitwise vs an undisturbed run at the settled
        configuration."""
        if not overrides:
            return
        self.config.update(overrides)
        if not ({"tpu_hist_agg", "tpu_bucket_policy", "tpu_stream_mode"}
                & set(overrides)):
            return  # chunk-only: nothing compiled closes over it
        if self.train_data is None or (self.learner is None
                                       and self._ladder_carry is None):
            return  # no live training context (and not mid-rebuild)
        if self.learner is not None:
            self._materialize()  # pending records belong to the OLD grower
            old = self.learner
            # carry the cross-iteration learner state out first: the
            # feature-fraction RNG stream and the CEGB used/paid planes
            # (cross-tree — a rebuild must not reset what earlier trees
            # already paid for); held on self until a rebuild SUCCEEDS,
            # so a failed rebuild + further descent still restores it
            rng_state = None
            if getattr(old, "_feature_rng", None) is not None:
                rng_state = old._feature_rng.bit_generator.state
            self._ladder_carry = (rng_state, [
                (attr, key, getattr(old, attr, None))
                for attr, key in (("_cegb_used", "cegb_used"),
                                  ("_cegb_paid", "cegb_paid"))])
            # ...then drop the old generation's device residency
            # (histogram pool + transposed bins + the step closure
            # holding both) BEFORE the replacement re-allocates them:
            # this runs on a device that just OOMed, and holding two
            # generations of the largest buffers would transiently
            # double residency and OOM the rebuild itself
            self._train_step = None
            self.learner = None
            old._pool = None
            old._pool_spec = None
            if hasattr(old, "bins_t"):
                old.bins_t = None
            del old
        self._rebuild_learner()

    def _rebuild_learner(self) -> None:
        """(Re)construct the learner for the CURRENT config, restoring
        the parked cross-iteration state (`_ladder_carry`).  Runs under
        `oom_guard`: a rebuild-time allocation failure is still an OOM
        at the train step — classified (counted + blackboxed), never a
        raw XlaRuntimeError escaping the recovery path unnamed."""
        with membudget.oom_guard("train_step", stage="ladder_rebuild"):
            self.learner = make_tree_learner(self.config, self.train_data)
        rng_state, cegb_vals = self._ladder_carry or (None, [])
        self._ladder_carry = None
        if rng_state is not None and \
                getattr(self.learner, "_feature_rng", None) is not None:
            self.learner._feature_rng.bit_generator.state = rng_state
        for attr, key, val in cegb_vals:
            if val is not None and hasattr(self.learner, attr):
                setattr(self.learner, attr, val)
                self.learner.meta[key] = val
        self._invalidate_tables()
        self._maybe_make_train_step()

    def _run_preflight(self) -> None:
        """tpu_hbm_preflight before iteration 0: itemized plan vs the
        budget — warn, refuse with the named plan, or auto-degrade
        down the same bitwise-invisible ladder mid-train OOMs use."""
        from ..utils.log import LightGBMError, Log

        mode = str(self.config.tpu_hbm_preflight).strip().lower()
        if mode not in ("off", "warn", "raise", "degrade"):
            raise ValueError("tpu_hbm_preflight must be off|warn|raise|"
                             f"degrade, got {mode!r}")
        if mode == "off":
            return
        plan = membudget.plan_training(self.config, self.learner,
                                       self.num_tree_per_iteration)
        membudget.publish_budget_gauge(plan.budget, "training")
        if plan.fits is not False:
            return  # fits, or no budget resolves (nothing to enforce)
        if mode == "degrade":
            pending: Dict = {}
            while plan.fits is False:
                step = self._mem_ladder.next_step(self.config)
                if step is None:
                    break
                name, overrides = step
                membudget.note_ladder_step("preflight", name, overrides,
                                           recovery=False)
                # stage config-only so one learner rebuild covers all
                self.config.update(overrides)
                pending.update(overrides)
                plan = membudget.plan_training(
                    self.config, self.learner,
                    self.num_tree_per_iteration)
            if plan.fits is not False:
                Log.warning(
                    "HBM preflight degraded the configuration to fit "
                    f"the budget: {pending} (bitwise-invisible); "
                    f"headroom now {plan.headroom:,d} bytes")
                if {"tpu_hist_agg", "tpu_bucket_policy",
                        "tpu_stream_mode"} & set(pending):
                    self.apply_memory_degradation(
                        {k: pending[k] for k in
                         ("tpu_hist_agg", "tpu_bucket_policy",
                          "tpu_stream_mode")
                         if k in pending})
                return
        if mode == "warn":
            Log.warning("HBM preflight: predicted peak exceeds the "
                        "budget (tpu_hbm_preflight=warn):\n"
                        + plan.format_table())
            return
        obs.flightrecorder.note("oom", "preflight_refused",
                                total=plan.total, budget=plan.budget)
        raise LightGBMError(plan.refuse_message(
            "training preflight (tpu_hbm_preflight="
            f"{mode}): this configuration"))

    # -- numeric guardrails (tpu_guard_numerics) -----------------------
    def _scores_finite(self) -> bool:
        """One all-isfinite reduction over the train scores, piggybacked
        after the iteration's own device pass.  Forces one device sync
        per iteration — the cost of guarding, paid only when armed."""
        if self.train_scores is None:
            return True
        return bool(jax.device_get(
            jnp.isfinite(self.train_scores.scores).all()))

    def _poisoned_iteration(self, snap: Dict) -> bool:
        from ..utils.log import LightGBMError, Log

        it = snap["iter"]
        # guard firings are rare and vital: count unconditionally, and
        # leave a narrative event in the trace stream when one is open
        obs.REGISTRY.inc("lgbm_guard_poisoned_total", mode=self._guard,
                         help="non-finite-score iterations caught by "
                              "tpu_guard_numerics")
        obs.event("guard_poisoned", iteration=it, mode=self._guard)
        obs.flightrecorder.note("guard", "guard_poisoned",
                                iteration=it, mode=self._guard)
        if self._guard == "warn":
            Log.warning(f"non-finite training scores after iteration {it} "
                        "(tpu_guard_numerics=warn): continuing")
            return False
        if self._guard == "raise":
            self._iter_restore(snap)  # leave the booster usable
            exc = LightGBMError(
                f"non-finite training scores after iteration {it} "
                "(tpu_guard_numerics=raise); the poisoned iteration was "
                "rolled back")
            # the blackbox is the postmortem for exactly this death
            obs.flightrecorder.dump("guard_raise", exc=exc)
            raise exc
        # skip: drop the iteration but KEEP the advanced PRNG streams so
        # the retry re-bags instead of replaying the same poison.  With
        # no stochastic lever at all the retry would be a bit-identical
        # replay — raise immediately instead of burning the streak.
        if not self._has_skip_lever():
            self._iter_restore(snap)
            raise LightGBMError(
                f"non-finite training scores after iteration {it} and no "
                "stochastic lever to re-bag (tpu_guard_numerics=skip "
                "needs bagging/GOSS/feature_fraction/quantized rounding "
                "to vary the retry)")
        keys = (getattr(self, "_key", None), getattr(self, "_bag_key", None))
        bag_rng = (self._bag_rng.bit_generator.state
                   if self._bag_rng is not None else None)
        feat_rng = (self.learner._feature_rng.bit_generator.state
                    if self.learner is not None and
                    getattr(self.learner, "_feature_rng", None) is not None
                    else None)
        self._iter_restore(snap)
        if keys[0] is not None:
            self._key = keys[0]
        if keys[1] is not None:
            self._bag_key = keys[1]
        if bag_rng is not None:
            self._bag_rng.bit_generator.state = bag_rng
        if feat_rng is not None:
            self.learner._feature_rng.bit_generator.state = feat_rng
        self._advance_streams_for_skip()
        self._guard_streak += 1
        self._guard_skips_total += 1
        if self._guard_streak > self._GUARD_MAX_STREAK:
            raise LightGBMError(
                f"{self._guard_streak} consecutive poisoned iterations "
                "under tpu_guard_numerics=skip; giving up")
        Log.warning(f"dropped poisoned iteration {it} "
                    "(tpu_guard_numerics=skip): rolled back, re-bagging")
        return False

    def _has_skip_lever(self) -> bool:
        """Does a skip-mode retry differ at all from the dropped
        iteration?  Without a stochastic lever the replay is
        bit-identical and skipping is pointless."""
        if self._bag_cfg is not None or self._goss_cfg is not None:
            return True
        if self.config is not None \
                and float(self.config.feature_fraction) < 1.0:
            return True
        return (self.learner is not None
                and getattr(self.learner, "params", None) is not None
                and self.learner.params.precision in ("int8", "int16"))

    def _advance_streams_for_skip(self) -> None:
        """Make the skip retry actually differ: force a fresh bagging
        mask even off the bagging_freq boundary (the fused step only
        consumes _bag_key on refresh; the sync path only redraws when
        the cached mask is gone)."""
        self._cached_bag_mask = None
        self._force_bag_refresh = True

    def _train_one_iter_sync(self, grad=None, hess=None) -> bool:
        """Synchronous path: custom fobj gradients or renew objectives."""
        init_scores = [0.0] * self.num_tree_per_iteration
        if grad is None or hess is None:
            for k in range(self.num_tree_per_iteration):
                init_scores[k] = self._boost_from_average(k)
            grad, hess = self.objective.get_gradients(self.train_scores.scores)
            if grad.ndim == 1:
                grad, hess = grad[None, :], hess[None, :]
        else:
            grad = jnp.asarray(grad, jnp.float32).reshape(
                self.num_tree_per_iteration, -1)
            hess = jnp.asarray(hess, jnp.float32).reshape(
                self.num_tree_per_iteration, -1)

        self._materialize()
        with obs.span("bagging"):
            mask = self.bagging_mask(self.iter_)
        should_continue = False
        for k in range(self.num_tree_per_iteration):
            need = (self.objective is None
                    or self.objective.class_need_train(k))
            tree = None
            if need:
                with obs.span("grow", class_id=k), \
                        obs.resources.phase_peak("hist_build"):
                    tree, leaf_ids, out = self.learner.train(
                        grad[k], hess[k], mask)
            if tree is not None and tree.num_leaves > 1:
                should_continue = True
                with obs.span("score_update", class_id=k), \
                        obs.resources.phase_peak("score_update"):
                    self._renew_and_update(tree, leaf_ids, k, mask)
                if abs(init_scores[k]) > K_EPSILON:
                    tree.add_bias(init_scores[k])
            else:
                tree = Tree(2)
                if len(self.models) < self.num_tree_per_iteration:
                    if not need and self.objective is not None:
                        output = self.objective.boost_from_score(k)
                    else:
                        output = init_scores[k]
                    tree.as_constant_tree(output)
                    self.train_scores.add_constant(output, k)
                    for vs in self.valid_scores:
                        vs.add_constant(output, k)
            self.models.append(tree)

        if not should_continue:
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            self._stopped = True
            return True
        # telemetry defers to train_one_iter AFTER the numerics guard:
        # a tpu_guard_numerics=skip rollback deletes these trees again,
        # and noting them would break the counter <-> feature_importance
        # bit-equality (the fused path gets this for free — rolled-back
        # pending records never materialize)
        self._note_after_guard = self.models[-self.num_tree_per_iteration:]
        self.iter_ += 1
        return False

    def _materialize(self) -> None:
        """Fetch pending device records and build host Tree models."""
        if not self._pending:
            return
        ctx = timer.PHASE("tree_materialize")
        ctx.__enter__()
        try:
            self._materialize_inner()
        finally:
            ctx.__exit__(None, None, None)

    def _materialize_inner(self) -> None:
        pending, self._pending = self._pending, []
        # one batched fetch for all pending trees (None leaf-out entries
        # are empty pytrees and fetch as None)
        fetched = jax.device_get([(p[0], p[1]) for p in pending])
        meta = self.learner.meta_np
        for (_, _, class_id, init), (rec, leaf_out) in zip(pending, fetched):
            if self._stopped:
                break  # drop queued post-stall iterations (reference pops them)
            tree = self.learner.build_tree_from_records(
                np.asarray(rec),
                None if leaf_out is None else np.asarray(leaf_out))
            if tree.num_leaves > 1:
                tree.apply_shrinkage(self.shrinkage_rate)
                # valid scores stay device-resident: the new tree's packed
                # table traverses all rows on device (zero device_get per
                # tree — the async train pipeline never stalls on eval)
                pc: Dict = {}
                for vs, vd in zip(self.valid_scores, self.valid_sets):
                    delta = self._tree_delta_device(vd, tree, pack_cache=pc)
                    if delta is None:
                        delta = jnp.asarray(
                            self._score_trees_binned(vd.bins, [tree], [1.0])
                            .astype(np.float32))
                    vs.add(class_id, delta)
                if abs(init) > K_EPSILON:
                    tree.add_bias(init)
                self.models.append(tree)
                self._note_tree_telemetry(tree)
            else:
                # no split happened: device scores were not changed; stop
                # training like the reference ("no more leaves that meet the
                # split requirements", gbdt.cpp:434-442). A first-iteration
                # stall still records the constant boost-from-average tree.
                self._stopped = True
                if len(self.models) < self.num_tree_per_iteration:
                    tree.as_constant_tree(init)
                    self.models.append(tree)
        # iter_ counts NEW boosting rounds (the index bagging refresh,
        # GOSS warmup, and DART's drop bookkeeping key on) — init_model
        # trees live in models but not in iter_, or a mid-train
        # materialize (checkpoint, eval) would shift the bagging
        # schedule of a continuation run
        self.iter_ = (len(self.models) // max(self.num_tree_per_iteration, 1)
                      - self.num_init_iteration)

    def train_one_iter_custom(self, grad: np.ndarray, hess: np.ndarray) -> bool:
        return self.train_one_iter(jnp.asarray(grad), jnp.asarray(hess))

    def _renew_and_update(self, tree: Tree, leaf_ids, class_id: int, mask):
        # RenewTreeOutput (objective-specific percentile refits)
        if self.objective is not None and self.objective.needs_renew:
            leaf_np = np.asarray(jax.device_get(leaf_ids))
            score_np = np.asarray(
                jax.device_get(self.train_scores.scores[class_id]), np.float64)
            mask_np = (np.ones(len(leaf_np), bool) if mask is None
                       else np.asarray(jax.device_get(mask)) > 0)
            self.objective.renew_tree_output(tree, score_np, leaf_np, mask_np)
            if getattr(self.learner, "_partitioned", False):
                # distributed renew averages each leaf's PER-MACHINE
                # local-percentile output over the machines that had
                # rows on that leaf — the reference's exact scheme
                # (serial_tree_learner.cpp:865-891: GlobalSum of
                # outputs / GlobalSum of nonzero-worker counts)
                from ..parallel.metric_sync import sync_sums

                L = tree.num_leaves
                cnt = np.bincount(leaf_np[mask_np], minlength=L)[:L]
                has = (cnt > 0).astype(np.float64)
                outs = np.asarray(tree.leaf_value[:L], np.float64) * has
                g = sync_sums(np.concatenate([outs, has]))
                tree.leaf_value[:L] = g[:L] / np.maximum(g[L:], 1.0)
        tree.apply_shrinkage(self.shrinkage_rate)
        # train scores: leaf-partition gather (ScoreUpdater::AddScore train path)
        leaf_vals = jnp.asarray(tree.leaf_value[:tree.num_leaves]
                                .astype(np.float32))
        self.train_scores.add(class_id, leaf_vals[leaf_ids])
        # valid scores: binned traversal (device kernel, host fallback)
        pc: Dict = {}
        for vs, vd in zip(self.valid_scores, self.valid_sets):
            delta = self._tree_delta_device(vd, tree, pack_cache=pc)
            if delta is None:
                delta = jnp.asarray(
                    self._score_trees_binned(vd.bins, [tree], [1.0])
                    .astype(np.float32))
            vs.add(class_id, delta)

    def rollback_one_iter(self) -> None:
        self._materialize()
        self._invalidate_tables()
        if self.iter_ <= 0:
            return
        train_bins = None
        if (self.train_data.has_bins and self.learner is not None
                and self._device_replay_ok(self.train_data.num_data)):
            # one upload shared by every popped tree this call
            train_bins = self._device_bins_for(self.train_data, cache=False)
        for k in range(self.num_tree_per_iteration):
            tree = self.models.pop()
            k_id = self.num_tree_per_iteration - 1 - k
            pc: Dict = {}
            delta = self._tree_delta_device(self.train_data, tree,
                                            bins_dev=train_bins,
                                            pack_cache=pc)
            self.train_scores.add(k_id, -delta if delta is not None
                                  else jnp.asarray(self._score_trees_binned(
                                      self.train_data.bins, [tree], [-1.0])
                                      .astype(np.float32)))
            for vs, vd in zip(self.valid_scores, self.valid_sets):
                delta = self._tree_delta_device(vd, tree, pack_cache=pc)
                vs.add(k_id, -delta if delta is not None
                       else jnp.asarray(self._score_trees_binned(
                           vd.bins, [tree], [-1.0]).astype(np.float32)))
        self.iter_ -= 1

    # ------------------------------------------------------------------
    def current_iteration(self) -> int:
        self._materialize()
        return len(self.models) // max(self.num_tree_per_iteration, 1)

    def num_total_model(self) -> int:
        self._materialize()
        return len(self.models)

    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def current_score_for_fobj(self) -> np.ndarray:
        return self.train_scores.numpy()

    # ------------------------------------------------------------------
    # checkpoint/resume (utils/checkpoint.py): the driver-level bundle
    # ------------------------------------------------------------------
    @staticmethod
    def _key_words(key) -> List[int]:
        """PRNG key -> raw uint32 words (JSON-able)."""
        arr = key
        try:
            if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
                arr = jax.random.key_data(arr)
        except (AttributeError, TypeError):  # pragma: no cover - old jax
            pass
        return [int(w) for w in
                np.ravel(np.asarray(jax.device_get(arr))).astype(np.uint32)]

    @staticmethod
    def _words_to_key(words, like):
        """uint32 words -> a key matching `like`'s representation."""
        arr = jnp.asarray(np.asarray(words, np.uint32).reshape(-1))
        try:
            if jnp.issubdtype(like.dtype, jax.dtypes.prng_key):
                return jax.random.wrap_key_data(arr)
        except (AttributeError, TypeError):  # pragma: no cover - old jax
            pass
        return arr

    def topology_snapshot(self) -> Dict:
        """What the multihost group manifest records and elastic resume
        validates/re-shards against (ISSUE 8).  "rows" is THIS process's
        local row count — the global count under replicated/single-
        process ingest.  Pure host metadata: NO device transfer, so the
        flush path's global-commit retry can call it for free."""
        if self.train_data is None or self.learner is None:
            raise ValueError("topology snapshot needs a live training "
                             "context")
        return {
            "rows": int(self.train_data.num_data),
            "host_count": int(jax.process_count()),
            "host_index": int(jax.process_index()),
            "partitioned": bool(getattr(self.learner, "_partitioned",
                                        False)),
            "data_shards": int(getattr(self.learner, "d_shards", 1)),
            "feature_shards": int(getattr(self.learner, "f_shards", 1)),
            "hosts": int(getattr(self.learner, "hosts", 1)),
            "tree_learner": str(self.config.tree_learner),
        }

    def capture_train_state(self) -> Tuple[Dict, Dict]:
        """The restart bundle's driver half: a JSON-able state dict plus
        the f32 score arrays.  Pairs with `restore_train_state`; the
        model string (trees + mapper trailer) travels separately."""
        if self.train_data is None or self.learner is None \
                or self.train_scores is None:
            raise ValueError("checkpointing needs a live training context "
                             "(predict-only/file-loaded boosters have "
                             "nothing to resume)")
        self._materialize()
        state = {
            "iteration": int(self.current_iteration()),
            "num_init_iteration": int(self.num_init_iteration),
            "stopped": bool(self._stopped),
            "boosted_from_average": [
                bool(b) for b in getattr(self, "_boosted_from_average", [])],
            "key": self._key_words(self._key),
            "bag_key": self._key_words(self._bag_key),
            "bag_rng": self._bag_rng.bit_generator.state,
            "feature_rng": (self.learner._feature_rng.bit_generator.state
                            if getattr(self.learner, "_feature_rng", None)
                            is not None else None),
            "valid_names": list(self.valid_names),
            "guard_skips": int(self._guard_skips_total),
            "topology": self.topology_snapshot(),
        }
        arrays = {"train_scores": np.asarray(
            jax.device_get(self.train_scores.scores), np.float32)}
        for name, vs in zip(self.valid_names, self.valid_scores):
            arrays[f"valid_scores/{name}"] = np.asarray(
                jax.device_get(vs.scores), np.float32)
        if self._cached_bag_mask is not None:
            arrays["bag_mask"] = np.asarray(
                jax.device_get(self._cached_bag_mask), np.float32)
        extra = self._capture_extra_state()
        if extra:
            state["extra"] = extra
        return state, arrays

    def _capture_extra_state(self) -> Dict:
        return {}

    def _restore_extra_state(self, extra: Dict) -> None:
        pass

    def restore_train_state(self, model_text: str, state: Dict,
                            arrays: Dict) -> None:
        """Rebuild this (freshly-initialized) driver to the checkpointed
        iteration: trees rebind through the bitwise `from_model_string`
        path onto the LIVE training mappers, the f32 score buffers
        restore byte-for-byte (replaying trees through the forest kernel
        would re-round the f32 accumulation in a different order), and
        every PRNG stream resumes mid-sequence — so continued training
        is bit-identical to a never-interrupted run."""
        if self.train_data is None or self.learner is None:
            raise ValueError("restore needs a booster constructed with "
                             "the training dataset")
        self._materialize()
        other = GBDT.from_model_string(model_text)
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            raise ValueError(
                "checkpoint has different num_tree_per_iteration")
        for tree in other.models:
            if tree.num_leaves > 1:
                self._rebind_tree(tree)
        self.models = list(other.models)
        self._pending = []
        k = max(self.num_tree_per_iteration, 1)
        total = len(self.models) // k
        if int(state.get("iteration", total)) != total:
            raise ValueError(
                f"checkpoint iteration {state.get('iteration')} does not "
                f"match its model ({total} iterations)")
        self.num_init_iteration = int(state.get("num_init_iteration", 0))
        # iter_ counts NEW rounds only (see _materialize_inner)
        self.iter_ = total - self.num_init_iteration
        ts = np.asarray(arrays["train_scores"], np.float32)
        want = (max(self.num_tree_per_iteration, 1),
                int(self.train_data.num_data))
        if tuple(ts.shape) != want:
            raise ValueError(
                f"checkpoint train-score buffer has shape {ts.shape} but "
                f"the live training context needs {want}; the checkpoint "
                "was taken over different data (elastic topology changes "
                "are re-sharded upstream — this is a data mismatch)")
        # .copy() forces an XLA-owned buffer (the fused step DONATES the
        # scores; donating a numpy-aliased zero-copy upload corrupts the
        # heap — same rule as _ScoreState)
        self.train_scores.scores = jnp.asarray(ts).copy()
        meta = self.learner.meta_np
        for name, vs, vd in zip(self.valid_names, self.valid_scores,
                                self.valid_sets):
            a = arrays.get(f"valid_scores/{name}")
            if a is not None and tuple(np.asarray(a).shape) \
                    != tuple(np.asarray(vs.scores.shape)):
                # an elastic resume re-partitioned the valid rows: the
                # stored slice no longer matches — replay instead
                a = None
            if a is not None:
                vs.scores = jnp.asarray(np.asarray(a, np.float32)).copy()
                continue
            # a valid set the checkpointed run did not have: replay the
            # restored model onto it (bitwise matters for TRAIN state;
            # eval-only scores may take the batched path)
            if not self._replay_scores_device(vs, vd, self.models):
                for i, tree in enumerate(self.models):
                    vs.add(i % k, jnp.asarray(
                        _predict_binned(tree, vd.bins, meta)
                        .astype(np.float32)))
        self._key = self._words_to_key(state["key"], self._key)
        self._bag_key = self._words_to_key(state["bag_key"], self._bag_key)
        if state.get("bag_rng") is not None:
            rng = np.random.default_rng(0)
            rng.bit_generator.state = state["bag_rng"]
            self._bag_rng = rng
        if state.get("feature_rng") is not None and \
                getattr(self.learner, "_feature_rng", None) is not None:
            rng = np.random.default_rng(0)
            rng.bit_generator.state = state["feature_rng"]
            self.learner._feature_rng = rng
        bfa = state.get("boosted_from_average")
        if bfa:
            self._boosted_from_average = [bool(b) for b in bfa]
        self._stopped = bool(state.get("stopped", False))
        self._guard_skips_total = int(state.get("guard_skips", 0))
        mask = arrays.get("bag_mask")
        self._cached_bag_mask = (
            None if mask is None
            else jnp.asarray(np.asarray(mask, np.float32)))
        self._invalidate_tables()
        self._restore_extra_state(state.get("extra") or {})

    # ------------------------------------------------------------------
    def eval(self, name: str, valid_idx: int, feval=None, booster=None
             ) -> List[Tuple]:
        with obs.span("metric_eval", dataset=name):
            self._materialize()
            out = []
            if valid_idx < 0:
                scores = self.train_scores.numpy()
                metrics = self.metrics
            else:
                scores = self.valid_scores[valid_idx].numpy()
                metrics = self.valid_metrics[valid_idx]
            for m in metrics:
                for metric_name, val in m.eval_all(scores, self.objective):
                    out.append((name, metric_name, val, m.higher_is_better))
            if feval is not None:
                ds = (self.train_data if valid_idx < 0
                      else self.valid_sets[valid_idx])
                res = feval(scores.reshape(-1), _FevalData(ds))
                for item in (res if isinstance(res, list) else [res]):
                    out.append((name, item[0], item[1], item[2]))
            return out

    def eval_for_data(self, data: TrainingData, name: str, feval=None):
        """Metrics on an AD-HOC dataset without registering it as a valid
        set (reference c_api.cpp:207-230's AddValidData + Eval pair, but
        transient: nothing is appended to valid_sets, so repeated calls
        do not accumulate score state).  The dataset must share the
        training mappers (created with reference=the train set) — same
        alignment contract as add_valid; scores replay through the binned
        walker exactly like add_valid's model replay."""
        self._materialize()
        if self.config is None:
            raise ValueError("eval on data needs a booster constructed "
                             "with a training dataset (file-loaded "
                             "boosters carry no metric config)")
        # alignment contract: bin-space traversal silently produces
        # garbage on foreign mappers.  train() frees train_data by
        # default (free_dataset), so identity can only be checked while
        # the training context is still alive; afterwards the
        # adopted_reference flag (set by reference= construction) is the
        # remaining guard — the reference keeps its C++ train set alive
        # inside the handle and needs neither
        ref_td = (self.train_data if self.train_data is not None
                  else self.learner.td if self.learner is not None else None)
        if ref_td is not None:
            if data.mappers is not ref_td.mappers:
                raise ValueError("eval data must be created with "
                                 "reference=the training dataset")
        elif not getattr(data, "adopted_reference", False):
            raise ValueError("eval data must be created with "
                             "reference=the training dataset")
        ms = create_metrics(self.config,
                            self.objective.name if self.objective else "")
        for m in ms:
            m.init(data.metadata, data.num_data)
        state = _ScoreState(self.num_tree_per_iteration, data.num_data,
                            data.metadata.init_score)
        # per-feature bin metadata comes from the shared mappers, so the
        # eval dataset's own arrays equal the training ones
        meta = data.feature_arrays()
        if not self._replay_scores_device(state, data, self.models,
                                          meta=meta, cache_bins=False):
            for i, tree in enumerate(self.models):
                k = i % self.num_tree_per_iteration
                state.add(k, jnp.asarray(
                    _predict_binned(tree, data.bins, meta)
                    .astype(np.float32)))
        scores = state.numpy()
        out = []
        for m in ms:
            for metric_name, val in m.eval_all(scores, self.objective):
                out.append((name, metric_name, val, m.higher_is_better))
        if feval is not None:
            res = feval(scores.reshape(-1), _FevalData(data))
            for item in (res if isinstance(res, list) else [res]):
                out.append((name, item[0], item[1], item[2]))
        return out

    # ------------------------------------------------------------------
    def _invalidate_tables(self) -> None:
        """Drop the cached raw-value node tables.  The cache keys on model
        COUNT, so any in-place leaf mutation (DART shrinkage, refit,
        set_leaf_value) must invalidate explicitly.  (The binned walker
        packs its tables per call and has no cache to go stale.)"""
        self._ft_key = None
        self._pf = None  # device forest tables share the contract

    def _forest_tables(self):
        """Concatenated node tables for the native predictor, cached per
        model count (models only ever grow or get truncated wholesale)."""
        from ..native import ForestTables

        key = (len(self.models),
               id(self.models[-1]) if self.models else 0)
        if getattr(self, "_ft_key", None) != key:
            self._ft = ForestTables(self.models)
            self._ft_key = key
        return self._ft

    def _score_trees_binned(self, bins: np.ndarray, trees, scales
                            ) -> np.ndarray:
        """sum_i scales[i] * trees[i](binned row) per row.

        One native OMP pass over the listed Tree objects (valid-score
        updates, DART drop/restore, rollback); numpy per-tree level-walk
        fallback when the native lib is unavailable.  The node tables are
        packed PER CALL from just the listed subset — the sets are small,
        and per-call packing cannot go stale when leaf values mutate in
        place (DART shrinkage, refit, set_leaf_value)."""
        from ..native import BinnedForestTables, native_lib

        meta = self.learner.meta_np
        if native_lib() is not None and bins.dtype in (np.uint8, np.uint16):
            tables = BinnedForestTables(list(trees), meta)
            out = tables.predict_subset(
                bins, np.arange(len(trees), dtype=np.int32), scales)
            if out is not None:
                return out
        acc = np.zeros(bins.shape[0], np.float64)
        for tree, sc in zip(trees, scales):
            acc += sc * _predict_binned(tree, bins, meta)
        return acc

    # ------------------------------------------------------------------
    # device-resident prediction (ops/predict.py): jitted bin-space
    # traversal for valid-score updates, score replay, and device='tpu'
    # predict.  The host walker (_predict_binned/_score_trees_binned)
    # stays as the parity oracle and the tiny-data fallback.
    # ------------------------------------------------------------------
    def _device_replay_ok(self, n_rows: int) -> bool:
        """Should score replay for `n_rows` rows run on device?"""
        if self.config is None:
            return False
        from ..config import parse_tristate

        mode = parse_tristate(self.config.tpu_predict_device)
        if mode == "false":
            return False
        if mode == "true":
            return True
        # auto: jit dispatch + compile dominate tiny sets; the host
        # walker stays cheaper there
        return n_rows >= int(self.config.tpu_predict_min_rows)

    def _meta_dev(self):
        """Device (num_bin, default_bin, missing_type) triple, cached per
        learner rebuild (init/reset_training_data/reset_config swap
        meta_np wholesale, never mutate it)."""
        meta = self.learner.meta_np
        # identity held via a strong ref (never a bare id(): a freed dict
        # and its successor can share an address)
        if getattr(self, "_meta_dev_for", None) is not meta:
            self._meta_dev_cache = feature_meta_dev(meta)
            self._meta_dev_for = meta
        return self._meta_dev_cache

    def _device_bins_for(self, data: TrainingData, cache: bool):
        """Device int32 bins for a dataset.  cache=True keeps them on the
        dataset (valid sets: reused every iteration); cache=False ships a
        one-shot copy for replay over the TRAINING bins, which the
        learner already holds in its own layout — caching a second
        full-size copy there would pin 4x-uint8 HBM for one pass."""
        faultline.fire("h2d_copy", rows=data.num_data)
        if cache:
            return data.device_bins()
        if data._device_bins is not None:  # already resident: reuse
            return data._device_bins
        if data._ingest_bins is not None:  # device ingest: widen in place
            return data._ingest_bins.astype(jnp.int32)
        return jnp.asarray(data.bins.astype(np.int32))

    def _tree_delta_device(self, data: TrainingData, tree: Tree,
                           bins_dev=None, pack_cache: Optional[Dict] = None):
        """Device [n] f32 leaf values of ONE tree over a binned dataset;
        None -> caller uses the host walker.  The per-iteration valid-
        score path: packs just the new tree (never the forest) and does
        zero device_get.  `pack_cache` (a per-tree dict) reuses the
        packed device tables across multiple valid sets."""
        if not data.has_bins or tree.num_leaves < 1 \
                or self.learner is None \
                or not self._device_replay_ok(data.num_data):
            return None
        if bins_dev is None:
            bins_dev = data.device_bins()
        if pack_cache is not None and "packed" in pack_cache:
            tables_dev, depth = pack_cache["packed"]
        else:
            # pinned leaf width + pow2-padded bitset pool: every tree of
            # a training run packs to ONE table shape, so the jitted
            # kernel compiles a handful of programs instead of one per
            # tree shape
            tables, depth = pack_trees(
                [tree], leaf_width=int(self.config.num_leaves),
                pad_cat_words=True)
            tables_dev = device_tables(tables)
            if pack_cache is not None:
                pack_cache["packed"] = (tables_dev, depth)
        with membudget.oom_guard("score_replay", rows=data.num_data):
            vals = forest_leaf_values(tables_dev, bins_dev,
                                      self._meta_dev(), depth,
                                      policy=self.bucket_policy())
        return vals[0]

    def _replay_scores_device(self, state: "_ScoreState", data: TrainingData,
                              trees, scale: float = 1.0, meta=None,
                              cache_bins: bool = True) -> bool:
        """Batch-replay `trees` (class = position % k) into a score state
        on device; False -> caller must use the host walker."""
        if not trees or not data.has_bins \
                or not self._device_replay_ok(data.num_data):
            return False
        if meta is not None:
            md = feature_meta_dev(meta)
        elif self.learner is not None:
            md = self._meta_dev()
        else:
            return False
        bins_dev = self._device_bins_for(data, cache_bins)
        k = max(self.num_tree_per_iteration, 1)
        # bound the kernel's [T, rows] node-state intermediates: trees in
        # blocks of ~128 (multiples of k so position % k stays the global
        # class id), rows in device-sliced chunks — a 2000-tree forest on
        # a multi-million-row set must not become one O(T*n) launch
        t_block = k * max(128 // k, 1)
        chunk = max(int(self.config.tpu_predict_chunk_rows), 1024)
        n = data.num_data
        for s in range(0, len(trees), t_block):
            tables, depth = pack_trees(list(trees[s:s + t_block]))
            tables_dev = device_tables(tables)
            with membudget.oom_guard("score_replay", rows=n,
                                     trees=len(trees)):
                if n > chunk:
                    parts = []
                    for lo in range(0, n, chunk):
                        hi = min(lo + chunk, n)
                        sub = bins_dev[lo:hi]
                        if hi - lo < chunk:
                            # pad the tail: every launch = ONE program
                            sub = jnp.concatenate(
                                [sub, jnp.zeros((chunk - (hi - lo),
                                                 sub.shape[1]),
                                                sub.dtype)])
                        parts.append(forest_class_scores(
                            tables_dev, sub, md, k, depth, scale,
                            policy=self.bucket_policy())[:, :hi - lo])
                    scores = jnp.concatenate(parts, axis=1)
                else:
                    scores = forest_class_scores(
                        tables_dev, bins_dev, md, k, depth, scale,
                        policy=self.bucket_policy())
            for kk in range(k):
                state.add(kk, scores[kk])
        return True

    def snapshot_predict_context(self) -> None:
        """Capture the bin mappers + per-feature metadata so device
        predict survives free_dataset (the training data itself is
        dropped; the mappers are small host objects)."""
        td = (self.train_data if self.train_data is not None
              else self.learner.td if self.learner is not None else None)
        if td is not None:
            self._pred_ctx = _PredictContext.from_training_data(td)
        # the health profile needs the training data too: capture it now
        # so a freed (predict-only) booster still writes the trailer
        self._profile = self.health_profile()

    def _pred_context(self) -> Optional["_PredictContext"]:
        td = (self.train_data if self.train_data is not None
              else self.learner.td if self.learner is not None else None)
        if td is not None:
            # cache per dataset object (strong ref, compared by identity:
            # mappers/meta only change when the dataset itself is swapped
            # by reset_training_data, which replaces the ref here too)
            if getattr(self, "_pred_ctx_for", None) is not td:
                self._pred_ctx_live = _PredictContext.from_training_data(td)
                self._pred_ctx_for = td
            return self._pred_ctx_live
        return getattr(self, "_pred_ctx", None)

    def _packed_forest(self) -> PackedForest:
        """Appendable device forest tables, cached across predict calls;
        append-only between invalidations (truncation or a reordering
        rebuilds, in-place leaf mutation goes through
        _invalidate_tables)."""
        pf = getattr(self, "_pf", None)
        if pf is not None and pf._count > 0 and (
                pf._count > len(self.models)
                or id(self.models[pf._count - 1]) != self._pf_last):
            pf = None  # truncated or reordered: rebuild from scratch
        if pf is None:
            pf = PackedForest()
            self._pf = pf
        pf.sync(self.models)
        self._pf_last = id(self.models[pf._count - 1]) if pf._count else 0
        return pf

    def _model_subset(self, num_iteration: int) -> Tuple[int, float]:
        """(tree count, RF-averaging divisor) for a num_iteration subset
        — the ONE place the slicing + average_output rules live, shared
        by every predict path."""
        k = max(self.num_tree_per_iteration, 1)
        total = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            total = min(total, num_iteration * k)
        div = (float(max(total // k, 1))
               if self.average_output and total > 0 else 1.0)
        return total, div

    def predict_raw_device(self, X: np.ndarray, num_iteration: int = -1
                           ) -> Optional[np.ndarray]:
        """[k, n] raw scores via the jitted bin-space predictor: bin the
        raw rows with the training mappers, then traverse the packed
        forest on device in fixed row chunks.  None when the booster has
        no binning context (file-loaded model) or no trees."""
        self._materialize()
        ctx = self._pred_context()
        if ctx is None or not self.models:
            return None
        total, div = self._model_subset(num_iteration)
        if total == 0:
            return None
        pf = self._packed_forest()
        out = self._chunked_device_scores(
            pf.device(total),  # num_iteration subset = table slice
            ctx.meta_dev(), self.num_tree_per_iteration, pf.depth,
            X.shape[0], lambda lo, hi: ctx.bin_rows(X[lo:hi]))
        return out / div

    def predict_chunk_rows(self) -> int:
        """Rows per device-predict launch (file-loaded boosters carry no
        Config; they use the registry default) — the chunk every predict
        row bucket is computed against.  A chunked-predict OOM shrinks
        this (config param, or the local override for config-less
        boosters) down to the membudget floor."""
        if self.config is not None:
            return max(int(self.config.tpu_predict_chunk_rows), 1024)
        ov = getattr(self, "_predict_chunk_override", None)
        return max(int(ov) if ov is not None else 65536, 1024)

    def _shrink_predict_chunk(self) -> bool:
        """Halve the predict chunk after a classified predict-path OOM;
        False at the floor (the caller re-raises the structured error).
        Bitwise-invisible: traversal is row-independent, so chunking
        never changes an output byte (the PR-3/PR-6 chunk contracts)."""
        from ..utils.log import Log

        cur = self.predict_chunk_rows()
        if cur <= membudget.CHUNK_FLOOR:
            return False
        new = max(cur // 2, membudget.CHUNK_FLOOR)
        if self.config is not None:
            self.config.update({"tpu_predict_chunk_rows": new})
        else:
            self._predict_chunk_override = new
        membudget.note_ladder_step("predict_chunk", "shrink_chunk_rows",
                                   {"tpu_predict_chunk_rows": new})
        Log.warning(f"device OOM in chunked predict: shrinking "
                    f"tpu_predict_chunk_rows {cur} -> {new} and "
                    "re-running (outputs are chunk-invariant)")
        return True

    def bucket_policy(self) -> str:
        """Launch-shape bucket policy (tpu_bucket_policy) — the ONE
        quantization ladder shared by score replay, chunked predict, and
        the serving warmup enumeration (ops/predict.py
        BUCKET_POLICIES)."""
        return (str(self.config.tpu_bucket_policy)
                if self.config is not None else "wide")

    def _chunked_device_scores(self, tables, meta_dev, k: int, depth: int,
                               n: int, get_bins) -> np.ndarray:
        """[k, n] f64 host scores from the packed device forest, chunked
        over rows: one bounded [chunk, F] int32 upload per launch, tail
        chunks padded so every launch reuses ONE compiled program.
        `get_bins(lo, hi)` supplies host bins per chunk.

        A classified device OOM shrinks the predict chunk (floor 4096)
        and resumes AT THE FAILED CHUNK — completed chunks are kept
        (outputs are chunk-invariant, so the recovered result is
        byte-identical and no finished device work is re-paid); at the
        floor the structured DeviceOutOfMemory propagates to the
        caller (the serving layer then fails the batch over to the
        native walker)."""
        out = np.zeros((k, n), np.float64)
        lo = 0
        with obs.resources.phase_peak("predict"):
            while True:
                # the chunk re-reads per launch: a shrink mid-predict
                # applies from the failed chunk onward
                chunk = self.predict_chunk_rows()
                hi = min(lo + chunk, n)
                rows = hi - lo
                try:
                    faultline.fire("h2d_copy", rows=rows)
                    bins = get_bins(lo, hi)
                    # pad every launch to a bucketed row count
                    # (row_bucket: full chunks for multi-chunk
                    # predicts, the policy's geometric ladder below
                    # that) so repeated predicts of varying batch
                    # sizes reuse a handful of compiled programs
                    # instead of one per distinct n
                    policy = self.bucket_policy()
                    target = (chunk if n > chunk
                              else row_bucket(rows, chunk,
                                              policy=policy))
                    if rows < target:
                        bins = np.concatenate(
                            [bins,
                             np.zeros((target - rows, bins.shape[1]),
                                      np.int32)])
                    with membudget.oom_guard("predict_chunk",
                                             rows=rows):
                        scores = forest_class_scores(
                            tables, jnp.asarray(bins), meta_dev, k,
                            depth, policy=policy)
                        out[:, lo:hi] = np.asarray(
                            jax.device_get(scores),
                            np.float64)[:, :rows]
                except membudget.DeviceOutOfMemory:
                    if not self._shrink_predict_chunk():
                        raise
                    continue  # retry THIS chunk at the smaller size
                lo = hi
                if lo >= n:
                    break
        return out

    def predict_binned_device(self, data: TrainingData,
                              num_iteration: int = -1,
                              raw_score: bool = False) -> np.ndarray:
        """Device predict on an ALREADY-BINNED dataset sharing the
        training mappers (the pre-binned half of the device='tpu'
        predict path — no host binning pass at all)."""
        self._materialize()
        ctx = self._pred_context()
        if ctx is None:
            raise ValueError("device predict on binned data needs a booster "
                             "with a training context (file-loaded boosters "
                             "carry no bin mappers)")
        if not data.has_bins:
            raise ValueError("dataset has no binned representation")
        # strict identity: the mapper list survives free_dataset inside
        # the snapshot, so unlike eval_for_data there is no freed-booster
        # gap to bridge — a looser check would silently traverse foreign
        # bin space
        if data.mappers is not ctx.mappers:
            raise ValueError("predict data must be created with "
                             "reference=the training dataset")
        k = self.num_tree_per_iteration
        total, div = self._model_subset(num_iteration)
        n = data.num_data
        raw = np.zeros((k, n), np.float64)
        if total > 0:
            pf = self._packed_forest()
            # chunk straight off the HOST bins: one bounded [chunk, F]
            # upload per launch, nothing cached on the caller's dataset
            raw = self._chunked_device_scores(
                pf.device(total), ctx.meta_dev(), k, pf.depth, n,
                lambda lo, hi: np.ascontiguousarray(
                    data.bins[lo:hi].astype(np.int32))) / div
        return self._finish_predict(raw, raw_score)

    def _finish_predict(self, raw: np.ndarray, raw_score: bool) -> np.ndarray:
        if not raw_score and self.objective is not None:
            raw = self.objective.convert_output(raw)
        if raw.shape[0] == 1:
            return raw[0]
        return raw.T  # [n, k] multiclass

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    early_stop_freq: int = 0,
                    early_stop_margin: float = 0.0) -> np.ndarray:
        """[k, n] raw scores from raw feature matrix.

        early_stop_freq > 0 enables prediction early stopping (reference
        src/boosting/prediction_early_stop.cpp:75-81): rows whose margin
        already exceeds early_stop_margin skip the remaining trees.
        """
        self._materialize()
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        k = self.num_tree_per_iteration
        total, rf_div = self._model_subset(num_iteration)
        # native OpenMP walker over all trees at once (the per-tree Python
        # loop dominated wall-clock at hundreds of trees); numpy fallback
        # when the native lib is unavailable
        out = self._forest_tables().predict(X, total, k, early_stop_freq,
                                            early_stop_margin)
        if out is None:
            out = np.zeros((k, X.shape[0]), np.float64)
            active = np.ones(X.shape[0], bool)
            for i in range(total):
                if early_stop_freq > 0 and not active.any():
                    break
                Xa = X[active] if early_stop_freq > 0 else X
                if early_stop_freq > 0:
                    out[i % k, active] += self.models[i].predict(Xa)
                else:
                    out[i % k] += self.models[i].predict(X)
                if (early_stop_freq > 0 and i % k == k - 1
                        and (i // k + 1) % early_stop_freq == 0):
                    if k == 1:
                        margin = np.abs(out[0])
                    else:
                        top2 = np.sort(out, axis=0)[-2:]
                        margin = top2[1] - top2[0]
                    active &= margin < early_stop_margin
        out /= rf_div  # RF averaging (gbdt_prediction.cpp:55)
        return out

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                device_predict: bool = False) -> np.ndarray:
        self._materialize()
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if pred_leaf:
            k = self.num_tree_per_iteration
            total = len(self.models)
            if num_iteration is not None and num_iteration > 0:
                total = min(total, num_iteration * k)
            leaves = self._forest_tables().predict_leaf(X, total)
            if leaves is None:
                leaves = np.stack([self.models[i].predict_leaf(X)
                                   for i in range(total)], axis=1)
            return leaves
        if pred_contrib:
            from .shap import forest_contribs

            k = self.num_tree_per_iteration
            total = len(self.models)
            if num_iteration is not None and num_iteration > 0:
                total = min(total, num_iteration * k)
            out = forest_contribs(self.models, X, total, k)
            if k == 1:
                return out[:, 0, :]                      # [n, F+1]
            return out.reshape(X.shape[0], -1)           # [n, k*(F+1)]
        raw = None
        if device_predict and not pred_early_stop:
            # device bin-space traversal; prediction early stopping keeps
            # the native walker (its per-row margin bailout is inherently
            # row-sequential)
            raw = self.predict_raw_device(X, num_iteration)
        if raw is None:
            raw = self.predict_raw(
                X, num_iteration,
                early_stop_freq=(int(pred_early_stop_freq)
                                 if pred_early_stop else 0),
                early_stop_margin=float(pred_early_stop_margin))
        return self._finish_predict(raw, raw_score)

    # ------------------------------------------------------------------
    def refit(self, X: np.ndarray, label: np.ndarray,
              decay_rate: float = 0.9,
              config: Optional[Config] = None) -> None:
        """Re-fit leaf values on new data, keeping every tree's structure.

        The analog of GBDT::RefitTree (reference src/boosting/gbdt.cpp:298)
        + FitByExistingTree (serial_tree_learner.cpp:239-270): per
        iteration, gradients are taken at the running refit scores; each
        tree's rows are grouped by the OLD tree's leaf assignment on the
        new data, the regularized leaf output is recomputed from the new
        sums, and blended as decay*old + (1-decay)*new*shrinkage.
        """
        self._materialize()
        cfg = config or self.config or Config({})
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        n = X.shape[0]
        leaf_preds = self.predict(X, pred_leaf=True)       # [n, T]

        from ..io.dataset import Metadata

        md = Metadata(num_data=n, label=np.asarray(label, np.float32))
        # a fresh objective instance bound to the NEW labels (never re-init
        # the live training objective)
        try:
            obj = create_objective(cfg)
        except ValueError:
            # a loaded booster's params carry the MODEL-STRING objective
            # format ('binary sigmoid:1'), which the config-side factory
            # rejects — fall through to the model-string parser
            obj = None
        if obj is None:
            obj = create_objective_from_model_string(
                self.loaded_params.get("objective", "")
                or str(cfg.objective or ""))
        if obj is None:
            raise ValueError("cannot refit without an objective")
        obj.init(md, n)

        scores = self._refit_by_leaf_preds(leaf_preds, obj, decay_rate, cfg)
        self._recapture_profile_scores(scores, np.asarray(label, np.float64))

    def _refit_by_leaf_preds(self, leaf_preds: np.ndarray, obj,
                             decay_rate: float, cfg: Config) -> None:
        """Shared RefitTree core: per iteration take gradients at the
        running refit scores and re-fit each tree's leaf values from the
        given [n, T] leaf assignment (reference gbdt.cpp:298 +
        FitByExistingTree)."""
        n = leaf_preds.shape[0]
        k = self.num_tree_per_iteration
        l1 = float(cfg.lambda_l1)
        l2 = float(cfg.lambda_l2)
        mds = float(cfg.max_delta_step)
        decay = float(decay_rate)
        scores = np.zeros((k, n), np.float64)
        grad = hess = None
        for i, tree in enumerate(self.models):
            cid = i % k
            if cid == 0:
                g, h = obj.get_gradients(jnp.asarray(scores, jnp.float32))
                grad = np.asarray(g, np.float64).reshape(k, n)
                hess = np.asarray(h, np.float64).reshape(k, n)
            leaves = leaf_preds[:, i].astype(np.int64)
            nl = tree.num_leaves
            sum_g = np.bincount(leaves, weights=grad[cid], minlength=nl)
            sum_h = np.bincount(leaves, weights=hess[cid], minlength=nl) \
                + K_EPSILON
            # CalculateSplittedLeafOutput (feature_histogram.hpp:449-456)
            reg = np.maximum(np.abs(sum_g) - l1, 0.0) * np.sign(sum_g)
            new_out = -reg / (sum_h + l2)
            if mds > 0.0:
                new_out = np.clip(new_out, -mds, mds)
            old = tree.leaf_value[:nl]
            tree.leaf_value[:nl] = (decay * old
                                    + (1.0 - decay) * new_out * tree.shrinkage)
            scores[cid] += tree.leaf_value[leaves]
        self._invalidate_tables()  # leaf values changed in place
        return scores

    def _recapture_profile_scores(self, scores: np.ndarray,
                                  label: np.ndarray) -> None:
        """Carry the model-health profile through refit: tree structure
        and the per-feature bin occupancy stay the TRAINING reference,
        but the raw-score histogram (and label stats) must describe the
        REFIT scores — a drift monitor comparing the stale histogram
        against post-refit traffic would flag the refit itself as a
        score shift."""
        base = self.health_profile()
        if base is None:
            return
        from ..obs import modelhealth

        s = np.asarray(scores, np.float64)
        if s.ndim == 1:
            s = s[None, :]
        fin = s[np.isfinite(s)]
        lo = float(fin.min()) if fin.size else 0.0
        hi = float(fin.max()) if fin.size else 1.0
        if hi <= lo:
            hi = lo + 1.0
        nb = max(len(base.score_edges) - 1, 2)
        edges = [float(x) for x in np.linspace(lo, hi, nb + 1)]
        counts = [[int(x) for x in
                   modelhealth.score_hist_counts(edges, row)]
                  for row in s]
        y = np.asarray(label, np.float64)
        lab = {"n": int(y.size),
               "mean": float(y.mean()) if y.size else 0.0,
               "std": float(y.std()) if y.size else 0.0,
               "min": float(y.min()) if y.size else 0.0,
               "max": float(y.max()) if y.size else 0.0}
        self._profile = modelhealth.FeatureProfile(
            {c: dict(f) for c, f in base.features.items()},
            lab, edges, counts)

    def reset_config(self, config: Config) -> None:
        self._materialize()
        self.config = config
        self.shrinkage_rate = float(config.learning_rate)
        if self.learner is not None:
            self.learner = make_tree_learner(config, self.train_data)
            self._bag_cfg = self._bagging_config()
            self._maybe_make_train_step()

    def shuffle_models(self, start: int = 0, end: int = -1) -> None:
        self._materialize()
        # reordering invalidates both node-table caches: their staleness
        # keys sample only (count, last tree), which a shuffle can leave
        # untouched
        self._invalidate_tables()
        if end < 0:
            end = len(self.models)
        rng = np.random.default_rng(0)
        seg = self.models[start:end]
        rng.shuffle(seg)
        self.models[start:end] = seg

    def _note_tree_telemetry(self, tree: Tree) -> None:
        """Training-quality telemetry for one NEWLY-TRAINED tree (ISSUE
        14): per-feature split/gain counters plus leaf-count and depth
        distributions into the process-global registry.  Gated on
        `obs.metrics_on()` (one bool check per tree when off).  The
        per-split inc order matches `feature_importance`'s flat
        (tree, node) walk exactly, so the f64 counter totals are
        BIT-EQUAL to feature_importance('gain')/('split') over the same
        trees (tests/test_modelhealth.py cross-checks both, including
        after a model-string reload).  Counters are monotonic: a
        rolled-back iteration's trees are not subtracted."""
        if not obs.metrics_on():
            return
        names = self.feature_names
        for j in range(tree.num_leaves - 1):
            f = int(tree.split_feature[j])
            fname = names[f] if f < len(names) else f"Column_{f}"
            obs.REGISTRY.inc(
                "lgbm_train_splits_total", 1,
                help="splits per feature across trained trees",
                feature=fname)
            obs.REGISTRY.inc(
                "lgbm_train_split_gain_total",
                max(float(tree.split_gain[j]), 0.0),
                help="summed split gain per feature", feature=fname)
        obs.REGISTRY.observe(
            "lgbm_train_leaf_count", float(tree.num_leaves),
            buckets=_LEAF_BUCKETS,
            help="leaves per trained tree")
        obs.REGISTRY.observe(
            "lgbm_train_tree_depth", float(tree.max_depth()),
            buckets=_DEPTH_BUCKETS,
            help="depth per trained tree")

    def health_profile(self):
        """The model-health reference profile (obs/modelhealth.py
        FeatureProfile) this booster serializes as its
        ``tpu_feature_profile:`` trailer.  A LIVE training booster
        rebuilds it per call (scores move every iteration); a loaded or
        freed booster returns the parsed/snapshotted one unchanged —
        which is what makes the trailer byte-identical through
        save -> load -> save.  None when capture is disabled
        (tpu_profile_capture=false) and nothing was loaded."""
        td = self.train_data
        if td is not None and self.train_scores is not None:
            if self.config is not None and \
                    not bool(self.config.tpu_profile_capture):
                return self._profile
            from ..obs import modelhealth

            score_bins = (int(self.config.tpu_profile_score_bins)
                          if self.config is not None
                          else modelhealth.DEFAULT_SCORE_BINS)
            prof = modelhealth.FeatureProfile.from_training(
                td, self.feature_names, self.train_scores.numpy(),
                score_bins)
            # nothing capturable (e.g. count-less mappers from an old
            # snapshot): a profile loaded from the trailer must still
            # round-trip rather than silently vanish on re-save
            return prof if prof is not None else self._profile
        return self._profile

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        self._materialize()
        imp = np.zeros(self.max_feature_idx + 1, np.float64)
        for tree in self.models:
            ni = tree.num_leaves - 1
            for j in range(ni):
                f = int(tree.split_feature[j])
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(float(tree.split_gain[j]), 0.0)
        if importance_type == "split":
            return imp.astype(np.int64).astype(np.float64)
        return imp

    # ------------------------------------------------------------------
    # model IO (reference src/boosting/gbdt_model_text.cpp)
    # ------------------------------------------------------------------
    def _feature_infos(self) -> List[str]:
        infos = []
        td = self.train_data
        if td is None:
            return list(self.loaded_params.get("feature_infos", []))
        used = set(td.used_feature_idx)
        for i, m in enumerate(td.mappers):
            if i not in used or m.is_trivial:
                infos.append("none")
            elif m.bin_type.name == "CATEGORICAL":
                cats = sorted(m.bin_2_categorical)
                infos.append(f"{':'.join(str(c) for c in cats)}")
            else:
                infos.append(f"[{m.min_val!r}:{m.max_val!r}]")
        return infos

    def save_model_to_string(self, num_iteration: int = -1,
                             start_iteration: int = 0) -> str:
        self._materialize()
        buf = io.StringIO()
        buf.write("tree\n")
        buf.write("version=v3\n")
        buf.write(f"num_class={self.num_class}\n")
        buf.write(f"num_tree_per_iteration={self.num_tree_per_iteration}\n")
        buf.write(f"label_index={self.label_index}\n")
        buf.write(f"max_feature_idx={self.max_feature_idx}\n")
        if self.objective is not None:
            buf.write(f"objective={self.objective.to_model_string()}\n")
        if self.average_output:
            buf.write("average_output\n")  # bare flag (gbdt_model_text.cpp:289)
        buf.write("feature_names=" + " ".join(self.feature_names) + "\n")
        buf.write("feature_infos=" + " ".join(self._feature_infos()) + "\n")

        total = len(self.models)
        k = self.num_tree_per_iteration
        start = start_iteration * k
        end = total
        if num_iteration is not None and num_iteration > 0:
            end = min(total, start + num_iteration * k)
        tree_strs = []
        for i in range(start, end):
            s = f"Tree={i - start}\n" + self.models[i].to_string()
            tree_strs.append(s)
        buf.write("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs) + "\n")
        buf.write("\n")
        for s in tree_strs:
            buf.write(s)
        buf.write("\nend of trees\n")
        # feature importances (split counts, descending)
        imp = self.feature_importance("split")
        pairs = [(int(v), self.feature_names[i]) for i, v in enumerate(imp) if v > 0]
        pairs.sort(key=lambda t: -t[0])
        buf.write("\nfeature_importances:\n")
        for v, name in pairs:
            buf.write(f"{name}={v}\n")
        buf.write("\nparameters:\n")
        if self.config is not None:
            for key, val in self.config.params.items():
                if isinstance(val, list):
                    val = ",".join(str(x) for x in val)
                if isinstance(val, bool):
                    val = int(val)
                buf.write(f"[{key}: {val}]\n")
        buf.write("\nend of parameters\n")
        # Python-layer trailer (like `pandas_categorical:` below it): the
        # bin-mapper snapshot that lets a RELOADED model keep the device
        # predict path.  The reference parser ignores trailing lines, so
        # files stay interchange-compatible.
        ctx = self._pred_context()
        if ctx is not None:
            import json

            buf.write(_MAPPER_MARKER + json.dumps(ctx.to_payload()) + "\n")
        # model-health trailer (ISSUE 14): the training reference
        # profile, same round-trip contract as the mapper snapshot —
        # the reference parser ignores trailing lines either way
        prof = self.health_profile()
        if prof is not None:
            buf.write(prof.to_line())
        return buf.getvalue()

    @classmethod
    def from_model_string(cls, text: str) -> "GBDT":
        self = cls()
        # Python-layer files end with one `pandas_categorical:<json>` line
        # (both here and in the reference package); the model parser
        # ignores it — Booster extracts its value separately
        pos = text.rfind("\npandas_categorical:")
        if pos >= 0:
            text = text[:pos]
        from ..obs.modelhealth import split_profile_trailer

        text, profile = split_profile_trailer(text)
        self._profile = profile
        text, ctx = _split_mapper_snapshot(text)
        lines = text.split("\n")
        kv: Dict[str, str] = {}
        tree_blocks: List[str] = []
        i = 0
        while i < len(lines):
            line = lines[i]
            if line.startswith("Tree="):
                block = [line]
                i += 1
                while i < len(lines) and not lines[i].startswith("Tree=") \
                        and not lines[i].startswith("end of trees"):
                    block.append(lines[i])
                    i += 1
                tree_blocks.append("\n".join(block))
                continue
            if line.startswith("end of trees"):
                break
            if line.strip() == "average_output":
                kv["average_output"] = "1"
            elif "=" in line:
                key, v = line.split("=", 1)
                kv[key] = v
            i += 1
        self.num_class = int(kv.get("num_class", "1"))
        self.average_output = "average_output" in kv
        self.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", "1"))
        self.label_index = int(kv.get("label_index", "0"))
        self.max_feature_idx = int(kv.get("max_feature_idx", "0"))
        self.feature_names = kv.get("feature_names", "").split()
        self.loaded_params = {"feature_infos": kv.get("feature_infos", "").split(),
                              "objective": kv.get("objective", "")}
        if "objective" in kv:
            self.objective = create_objective_from_model_string(kv["objective"])
        for block in tree_blocks:
            self.models.append(Tree.from_string(
                block.split("\n", 1)[1] if "\n" in block else ""))
        if ctx is not None:
            # re-enter bin space: loaded trees carry only raw-value
            # thresholds; with the snapshot mappers restored, rebinding
            # is EXACT (each saved threshold is a bin upper bound, and
            # value_to_bin maps it back to the same bin)
            try:
                used_pos = {col: j for j, col
                            in enumerate(ctx.used_feature_idx)}
                for tree in self.models:
                    if tree.num_leaves > 1:
                        _rebind_tree_to_mappers(tree, ctx.mappers, used_pos)
                self._pred_ctx = ctx
            except (KeyError, ValueError, IndexError):
                # a hand-edited model may split on columns the snapshot
                # never binned; the native walker stays available
                self._pred_ctx = None
        self.num_init_iteration = self.current_iteration()
        self.iter_ = 0
        return self

    def _rebind_tree(self, tree: Tree) -> None:
        """Map a loaded tree's real-feature splits back into bin space so the
        binned traversal (_predict_binned) is valid for score replay."""
        used_pos = {col: j for j, col in
                    enumerate(self.train_data.used_feature_idx)}
        _rebind_tree_to_mappers(tree, self.train_data.mappers, used_pos)

    def merge_from_model_string(self, text: str) -> None:
        """Continued training: prepend a loaded model (init_model)."""
        self._materialize()
        other = GBDT.from_model_string(text)
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            raise ValueError("init model has different num_tree_per_iteration")
        for tree in other.models:
            if tree.num_leaves > 1:
                self._rebind_tree(tree)
        self.models = other.models + self.models
        self.num_init_iteration = other.current_iteration()
        # replay loaded trees onto the score states (device batch pass
        # per dataset when eligible, host walker otherwise)
        datasets = [(self.train_scores, self.train_data, False)] + \
            [(vs, vd, True) for vs, vd in zip(self.valid_scores,
                                              self.valid_sets)]
        meta = self.learner.meta_np
        for state, data, cache in datasets:
            if self._replay_scores_device(state, data, other.models,
                                          cache_bins=cache):
                continue
            for i, tree in enumerate(other.models):
                kk = i % self.num_tree_per_iteration
                state.add(kk, jnp.asarray(
                    _predict_binned(tree, data.bins, meta)
                    .astype(np.float32)))

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0) -> Dict:
        self._materialize()
        k = self.num_tree_per_iteration
        start = start_iteration * k
        end = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            end = min(end, start + num_iteration * k)
        out = {
            "name": "tree",
            "version": "v3",
            "average_output": bool(self.average_output),
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_index,
            "max_feature_idx": self.max_feature_idx,
            "objective": (self.objective.to_model_string()
                          if self.objective else "none"),
            "feature_names": list(self.feature_names),
            "tree_info": [self._tree_to_json(i, self.models[i])
                          for i in range(start, end)],
        }
        return out

    def _tree_to_json(self, idx: int, tree: Tree) -> Dict:
        def node(i: int) -> Dict:
            if i < 0:
                leaf = ~i
                return {
                    "leaf_index": int(leaf),
                    "leaf_value": float(tree.leaf_value[leaf]),
                    "leaf_weight": float(tree.leaf_weight[leaf]),
                    "leaf_count": int(tree.leaf_count[leaf]),
                }
            dt = int(tree.decision_type[i])
            if dt & 1:
                # categorical: the reference dump emits the bitset's raw
                # categories joined by "||" (reference src/io/tree.cpp
                # ToJSON categorical branch), not the internal set index
                ci = int(tree.threshold[i])
                lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
                cats = [32 * (w - lo) + b
                        for w in range(lo, hi) for b in range(32)
                        if (tree.cat_threshold[w] >> b) & 1]
                thr = "||".join(str(c) for c in cats)
            else:
                thr = float(tree.threshold[i])
            d = {
                "split_index": int(i),
                "split_feature": int(tree.split_feature[i]),
                "split_gain": float(tree.split_gain[i]),
                "threshold": thr,
                "decision_type": "==" if dt & 1 else "<=",
                "default_left": bool(dt & 2),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "internal_value": float(tree.internal_value[i]),
                "internal_weight": float(tree.internal_weight[i]),
                "internal_count": int(tree.internal_count[i]),
                "left_child": node(int(tree.left_child[i])),
                "right_child": node(int(tree.right_child[i])),
            }
            return d
        return {
            "tree_index": idx,
            "num_leaves": int(tree.num_leaves),
            "num_cat": int(tree.num_cat),
            "shrinkage": float(tree.shrinkage),
            "tree_structure": node(0) if tree.num_leaves > 1 else {
                "leaf_value": float(tree.leaf_value[0])},
        }


class _PredictContext:
    """The slice of a TrainingData needed to bin + device-predict raw
    rows: mappers, used-column map, per-feature bin metadata.  Snapshot
    by free_dataset so trained boosters keep the device path, and
    round-tripped through the model string (`tpu_bin_mappers:` trailer)
    so SAVED models keep it too — the serving registry depends on
    reloaded models staying on the packed-forest path."""

    def __init__(self, mappers: List[BinMapper], used_feature_idx):
        self.mappers = mappers
        self.used_feature_idx = list(used_feature_idx)
        idx = self.used_feature_idx
        self.meta = {
            "num_bin": np.array([mappers[i].num_bin for i in idx], np.int32),
            "default_bin": np.array([mappers[i].default_bin for i in idx],
                                    np.int32),
            "missing_type": np.array([int(mappers[i].missing_type)
                                      for i in idx], np.int32),
        }
        self._meta_dev = None

    @classmethod
    def from_training_data(cls, td: TrainingData) -> "_PredictContext":
        # keeps the SAME mapper list object: predict_binned_device's
        # strict `data.mappers is ctx.mappers` identity check relies on it
        return cls(td.mappers, td.used_feature_idx)

    # -- model-string round trip ---------------------------------------
    def to_payload(self) -> Dict:
        """JSON-able snapshot: only used columns carry a real mapper
        (trivial columns rebuild as defaults — bin_rows never reads
        them)."""
        return {
            "num_total_features": len(self.mappers),
            "used_feature_idx": self.used_feature_idx,
            "mappers": {str(c): self.mappers[c].to_dict()
                        for c in self.used_feature_idx},
        }

    @classmethod
    def from_payload(cls, payload: Dict) -> "_PredictContext":
        total = int(payload["num_total_features"])
        used = [int(i) for i in payload["used_feature_idx"]]
        mappers = [BinMapper() for _ in range(total)]
        for key, d in payload["mappers"].items():
            mappers[int(key)] = BinMapper.from_dict(d)
        return cls(mappers, used)

    def meta_dev(self):
        """Device (num_bin, default_bin, missing_type) triple, uploaded
        once per context."""
        if self._meta_dev is None:
            from ..ops.predict import feature_meta_dev

            self._meta_dev = feature_meta_dev(self.meta)
        return self._meta_dev

    def bin_rows(self, X: np.ndarray) -> np.ndarray:
        """[n, F_used] int32 bins from raw rows, training-mapper space."""
        bins = np.zeros((X.shape[0], len(self.used_feature_idx)), np.int32)
        for j, col in enumerate(self.used_feature_idx):
            bins[:, j] = self.mappers[col].values_to_bins(X[:, col])
        return bins


class _FevalData:
    """Minimal Dataset-like shim passed to custom feval callbacks."""

    def __init__(self, td: TrainingData):
        self._td = td

    def get_label(self):
        return np.asarray(self._td.metadata.label)

    def get_weight(self):
        w = self._td.metadata.weight
        return None if w is None else np.asarray(w)

    def get_group(self):
        b = self._td.metadata.query_boundaries
        return None if b is None else np.diff(b)
