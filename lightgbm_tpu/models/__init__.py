from .tree import Tree
from .gbdt import GBDT


def create_boosting(config):
    """Boosting factory (reference src/boosting/boosting.cpp:35-68)."""
    from .dart import DART
    from .goss import GOSS
    from .rf import RF
    t = config.boosting
    if t == "gbdt":
        return GBDT()
    if t == "dart":
        return DART()
    if t == "goss":
        return GOSS()
    if t in ("rf", "random_forest"):
        return RF()
    raise ValueError(f"unknown boosting type {t!r}")
