"""TreeSHAP feature contributions (`pred_contrib`).

Plays the role of the reference's `Tree::PredictContrib` path (reference
include/LightGBM/tree.h:133, used by PredictForMat with
C_API_PREDICT_CONTRIB): per-row, per-feature Shapley values such that
`sum(contribs) + expected_value == raw prediction`.

Implements the polynomial-time TreeSHAP algorithm (Lundberg et al.): a
root-to-leaf walk carrying a "unique path" of (feature, zero_fraction,
one_fraction, pweight) entries, EXTENDed at every split and UNWOUND to
attribute each leaf's value to the features on its path.  Node covers
(training row counts) weight the "cold" branches, exactly like the
reference's count-based weighting.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tree import (K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK,
                   K_ZERO_THRESHOLD, Tree)


class _Path:
    """Unique feature path: parallel arrays grown/shrunk in place."""

    __slots__ = ("feature", "zero", "one", "pweight", "length")

    def __init__(self, max_depth: int):
        cap = max_depth + 2
        self.feature = np.full(cap, -1, np.int64)
        self.zero = np.zeros(cap, np.float64)
        self.one = np.zeros(cap, np.float64)
        self.pweight = np.zeros(cap, np.float64)
        self.length = 0

    def copy_of(self) -> "_Path":
        p = _Path(len(self.feature) - 2)
        p.feature[:] = self.feature
        p.zero[:] = self.zero
        p.one[:] = self.one
        p.pweight[:] = self.pweight
        p.length = self.length
        return p

    def extend(self, zero_fraction: float, one_fraction: float,
               feature: int) -> None:
        i = self.length
        self.feature[i] = feature
        self.zero[i] = zero_fraction
        self.one[i] = one_fraction
        self.pweight[i] = 1.0 if i == 0 else 0.0
        self.length += 1
        l = self.length
        for j in range(l - 2, -1, -1):
            self.pweight[j + 1] += one_fraction * self.pweight[j] * (j + 1) / l
            self.pweight[j] = zero_fraction * self.pweight[j] * (l - j - 1) / l

    def unwind(self, i: int) -> None:
        l = self.length
        one = self.one[i]
        zero = self.zero[i]
        n = self.pweight[l - 1]
        for j in range(l - 2, -1, -1):
            if one != 0.0:
                t = self.pweight[j]
                self.pweight[j] = n * l / ((j + 1) * one)
                n = t - self.pweight[j] * zero * (l - j - 1) / l
            else:
                self.pweight[j] = self.pweight[j] * l / (zero * (l - j - 1))
        for j in range(i, l - 1):
            self.feature[j] = self.feature[j + 1]
            self.zero[j] = self.zero[j + 1]
            self.one[j] = self.one[j + 1]
        self.length -= 1

    def unwound_sum(self, i: int) -> float:
        """Sum of pweights as if entry i were unwound (without mutating)."""
        l = self.length
        one = self.one[i]
        zero = self.zero[i]
        n = self.pweight[l - 1]
        total = 0.0
        for j in range(l - 2, -1, -1):
            if one != 0.0:
                tmp = n * l / ((j + 1) * one)
                total += tmp
                n = self.pweight[j] - tmp * zero * (l - j - 1) / l
            else:
                total += self.pweight[j] * l / (zero * (l - j - 1))
        return total


def _node_decision(tree: Tree, node: int, row: np.ndarray) -> bool:
    """go-left for one row at one internal node (Tree.predict semantics)."""
    v = row[tree.split_feature[node]]
    dt = int(tree.decision_type[node])
    mt = (dt >> 2) & 3
    if dt & K_CATEGORICAL_MASK:
        # NaN folds to category 0 unless missing_type is NaN; truncation
        # happens BEFORE the negative test so (-1, 0) folds to 0 as well
        # (Tree._categorical_go_left, models/tree.py:216-233)
        if np.isnan(v):
            if mt == 2:
                return False
            cat = 0
        else:
            cat = int(v)
        if cat < 0:
            return False
        cidx = int(tree.threshold[node])
        lo = tree.cat_boundaries[cidx]
        hi = tree.cat_boundaries[cidx + 1]
        w = cat // 32
        if w >= hi - lo:
            return False
        return bool((tree.cat_threshold[lo + w] >> (cat % 32)) & 1)
    if mt == 2:
        if np.isnan(v):
            return (dt & K_DEFAULT_LEFT_MASK) != 0
        fv = v
    else:
        fv = 0.0 if np.isnan(v) else v
        if mt == 1 and abs(fv) <= K_ZERO_THRESHOLD:
            return (dt & K_DEFAULT_LEFT_MASK) != 0
    return fv <= tree.threshold[node]


def _covers(tree: Tree):
    """(internal_cover, leaf_cover) row counts per node."""
    return (tree.internal_count.astype(np.float64),
            tree.leaf_count.astype(np.float64))


def tree_expected_value(tree: Tree) -> float:
    """Cover-weighted mean leaf value (reference ExpectedValue)."""
    nl = tree.num_leaves
    if nl == 1:
        return float(tree.leaf_value[0])
    w = tree.leaf_count[:nl].astype(np.float64)
    tot = w.sum()
    if tot <= 0:
        return 0.0
    return float((w * tree.leaf_value[:nl]).sum() / tot)


def tree_shap_row(tree: Tree, row: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate one tree's contributions for one row into phi [F+1]."""
    if tree.num_leaves == 1:
        return
    icov, lcov = _covers(tree)

    def recurse(node: int, path: _Path, zero_fraction: float,
                one_fraction: float, feature: int) -> None:
        path = path.copy_of()
        path.extend(zero_fraction, one_fraction, feature)
        if node < 0:  # leaf
            leaf = ~node
            for i in range(1, path.length):
                w = path.unwound_sum(i)
                phi[path.feature[i]] += (
                    w * (path.one[i] - path.zero[i])
                    * tree.leaf_value[leaf])
            return
        go_left = _node_decision(tree, node, row)
        hot = tree.left_child[node] if go_left else tree.right_child[node]
        cold = tree.right_child[node] if go_left else tree.left_child[node]
        cover = icov[node]
        hot_cover = (icov[hot] if hot >= 0 else lcov[~hot])
        cold_cover = (icov[cold] if cold >= 0 else lcov[~cold])
        incoming_zero, incoming_one = 1.0, 1.0
        split_f = int(tree.split_feature[node])
        # if this feature already appears on the path, undo its previous
        # extension first (unique-path invariant)
        prev = -1
        for i in range(path.length):
            if path.feature[i] == split_f:
                prev = i
                break
        if prev >= 0:
            incoming_zero = path.zero[prev]
            incoming_one = path.one[prev]
            path.unwind(prev)
        denom = cover if cover > 0 else 1.0
        recurse(hot, path, incoming_zero * hot_cover / denom,
                incoming_one, split_f)
        recurse(cold, path, incoming_zero * cold_cover / denom,
                0.0, split_f)

    recurse(0, _Path(tree.max_depth()), 1.0, 1.0, -1)


def forest_contribs(models: List[Tree], X: np.ndarray, num_trees: int,
                    num_class: int) -> np.ndarray:
    """[n, num_class, F+1] contributions (last slot = expected value).

    Matches the reference layout for PredictForMat with
    C_API_PREDICT_CONTRIB: per class, per-feature SHAP values plus the
    model's expected value so rows sum to the raw prediction.
    """
    n, F = X.shape
    out = np.zeros((n, num_class, F + 1), np.float64)
    expected = np.zeros(num_class, np.float64)
    for t in range(num_trees):
        expected[t % num_class] += tree_expected_value(models[t])
    out[:, :, F] = expected[None, :]
    for r in range(n):
        row = X[r]
        for t in range(num_trees):
            phi = out[r, t % num_class]
            tree_shap_row(models[t], row, phi)
    return out
