"""Extended metric zoo: regression family, multiclass, cross-entropy,
and ranking metrics (reference src/metric/*.hpp).

All metrics evaluate on host numpy — scores come off-device once per
`metric_freq` iterations.  Formulas cite the reference per class.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from .metrics import Metric, register_metric, _avg, _METRIC_ALIASES

K_EPSILON = 1e-15


def _convert(score0: np.ndarray, objective) -> np.ndarray:
    """Per-point ConvertOutput for single-score metrics
    (reference regression_metric.hpp:77-90)."""
    if objective is not None:
        return np.asarray(objective.convert_output(score0))
    return score0


# ---------------------------------------------------------------------------
# Regression family (reference src/metric/regression_metric.hpp)
# ---------------------------------------------------------------------------

@register_metric
class QuantileMetric(Metric):
    """reference regression_metric.hpp:152-170."""
    name = "quantile"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        delta = self.label - pred
        alpha = float(self.config.alpha)
        loss = np.where(delta < 0, (alpha - 1.0) * delta, alpha * delta)
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class HuberMetric(Metric):
    """reference regression_metric.hpp:186-204."""
    name = "huber"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        diff = pred - self.label
        a = float(self.config.alpha)
        loss = np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class FairMetric(Metric):
    """reference regression_metric.hpp:207-222."""
    name = "fair"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        x = np.abs(pred - self.label)
        c = float(self.config.fair_c)
        loss = c * x - c * c * np.log1p(x / c)
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class PoissonMetric(Metric):
    """reference regression_metric.hpp:224-239 (score here is exp(f))."""
    name = "poisson"

    def eval(self, score, objective):
        pred = np.maximum(_convert(score[0], objective), 1e-10)
        loss = pred - self.label * np.log(pred)
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class MAPEMetric(Metric):
    """reference regression_metric.hpp:243-254."""
    name = "mape"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        loss = np.abs(self.label - pred) / np.maximum(1.0, np.abs(self.label))
        return _avg(loss, self.weight, self.sum_weights)


def _safe_log(x):
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(x > 0, np.log(np.maximum(x, 1e-300)), -np.inf)


@register_metric
class GammaMetric(Metric):
    """reference regression_metric.hpp:256-276 (negative gamma log-lik,
    psi=1 so the lgamma term vanishes)."""
    name = "gamma"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        theta = -1.0 / pred
        b = -_safe_log(-theta)
        # c = log(label) - log(label) = 0 at psi=1 (reference keeps the
        # cancelled form; replicated as zero)
        loss = -(self.label * theta - b)
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class GammaDevianceMetric(Metric):
    """reference regression_metric.hpp:279-297 (2x summed deviance; its
    AverageLoss ignores sum_weights and returns sum_loss * 2)."""
    name = "gamma_deviance"

    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        tmp = self.label / (pred + 1e-9)
        loss = tmp - _safe_log(tmp) - 1.0
        if self.weight is not None:
            loss = loss * self.weight
        total = float(loss.sum())
        # a global SUM (no denominator): unlike averaged losses, a sum is
        # NOT replication-safe — adding the local sums of P replicated
        # ranks reports P x the true value.  Reduce across ranks only
        # when each rank actually holds a distinct row shard — DERIVED
        # from the live topology's row placement, not the pre_partition
        # config flag (a flag echo desynchronizes from reality the
        # moment a new axis changes what the flag implies).
        from ..parallel.topology import rows_partitioned

        if rows_partitioned():
            from ..parallel.metric_sync import sync_sums

            total = float(sync_sums([total])[0])
        return total * 2.0


@register_metric
class TweedieMetric(Metric):
    """reference regression_metric.hpp:300-318."""
    name = "tweedie"

    def eval(self, score, objective):
        rho = float(self.config.tweedie_variance_power)
        pred = np.maximum(_convert(score[0], objective), 1e-10)
        a = self.label * np.exp((1.0 - rho) * np.log(pred)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(pred)) / (2.0 - rho)
        return _avg(-a + b, self.weight, self.sum_weights)


# ---------------------------------------------------------------------------
# Multiclass (reference src/metric/multiclass_metric.hpp)
# ---------------------------------------------------------------------------

class _MulticlassMetric(Metric):
    def _probs(self, score, objective) -> np.ndarray:
        """[k, n] per-class outputs (softmax/sigmoid when objective known)."""
        if objective is not None:
            return np.asarray(objective.convert_output(score))
        return score


@register_metric
class MultiLoglossMetric(_MulticlassMetric):
    """reference multiclass_metric.hpp MultiSoftmaxLoglossMetric."""
    name = "multi_logloss"

    def eval(self, score, objective):
        p = self._probs(score, objective)
        lbl = self.label.astype(np.int64)
        p_true = p[lbl, np.arange(p.shape[1])]
        loss = np.where(p_true > K_EPSILON,
                        -np.log(np.maximum(p_true, K_EPSILON)),
                        -np.log(K_EPSILON))
        return _avg(loss, self.weight, self.sum_weights)


@register_metric
class MultiErrorMetric(_MulticlassMetric):
    """reference multiclass_metric.hpp MultiErrorMetric: top-k error — a row
    is wrong iff more than top_k classes score >= the true class's score."""
    name = "multi_error"

    def eval(self, score, objective):
        p = self._probs(score, objective)
        lbl = self.label.astype(np.int64)
        top_k = int(self.config.multi_error_top_k)
        p_true = p[lbl, np.arange(p.shape[1])]
        num_larger = (p >= p_true[None, :]).sum(axis=0)
        err = (num_larger > top_k).astype(np.float64)
        return _avg(err, self.weight, self.sum_weights)

    def eval_all(self, score, objective):
        top_k = int(self.config.multi_error_top_k)
        nm = "multi_error" if top_k == 1 else f"multi_error@{top_k}"
        return [(nm, self.eval(score, objective))]


@register_metric
class AucMuMetric(Metric):
    """reference multiclass_metric.hpp AucMuMetric (auc-mu,
    proceedings.mlr.press/v97/kleiman19a): mean over class pairs (i, j) of
    the tie-averaged AUC of the partition-weighted score projection."""
    name = "auc_mu"
    higher_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        nc = int(self.config.num_class)
        w = list(self.config.get("auc_mu_weights", []) or [])
        if w:
            if len(w) != nc * nc:
                raise ValueError("auc_mu_weights must have num_class^2 entries")
            self.class_weights = np.asarray(w, np.float64).reshape(nc, nc)
        else:
            self.class_weights = 1.0 - np.eye(nc)
        self.num_class = nc

    def eval(self, score, objective):
        from ..parallel.metric_sync import process_count, sync_concat

        nc = self.num_class
        lbl = self.label
        if process_count() > 1:
            # pairwise rank statistic across class partitions — like AUC,
            # merge the raw per-rank columns exactly before ranking
            merged = sync_concat(lbl, *[score[k] for k in range(nc)])
            lbl = merged[0]
            score = np.stack(merged[1:])
        lbl = lbl.astype(np.int64)
        sizes = np.bincount(lbl, minlength=nc)
        ans = 0.0
        for i in range(nc):
            for j in range(i + 1, nc):
                if sizes[i] == 0 or sizes[j] == 0:
                    continue
                curr_v = self.class_weights[i] - self.class_weights[j]
                t1 = curr_v[i] - curr_v[j]
                v = t1 * (curr_v @ score)
                vi = v[lbl == i]
                vj_sorted = np.sort(v[lbl == j])
                less = np.searchsorted(vj_sorted, vi, side="left")
                leq = np.searchsorted(vj_sorted, vi, side="right")
                s_ij = float((less + 0.5 * (leq - less)).sum())
                ans += s_ij / (sizes[i] * sizes[j])
        return float(2.0 * ans / (nc * (nc - 1)))


# ---------------------------------------------------------------------------
# Cross-entropy family (reference src/metric/xentropy_metric.hpp)
# ---------------------------------------------------------------------------

def _xent_loss(label, prob):
    eps = 1e-12
    p = np.clip(prob, eps, 1.0 - eps)
    return -label * np.log(p) - (1.0 - label) * np.log(1.0 - p)


@register_metric
class CrossEntropyMetric(Metric):
    """reference xentropy_metric.hpp:71-163."""
    name = "cross_entropy"

    def eval(self, score, objective):
        if objective is not None and objective.name != "cross_entropy_lambda":
            p = np.asarray(objective.convert_output(score[0]))
        else:
            # xentlambda's ConvertOutput yields lambda, not a probability;
            # the metric needs the plain sigmoid (ref :120-126)
            p = 1.0 / (1.0 + np.exp(-score[0]))
        return _avg(_xent_loss(self.label, p), self.weight, self.sum_weights)


@register_metric
class CrossEntropyLambdaMetric(Metric):
    """reference xentropy_metric.hpp:166-240: loss on p = 1-exp(-w*hhat),
    hhat = log(1+exp(f)); averaged over rows (not weights)."""
    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        hhat = np.log1p(np.exp(score[0]))
        w = self.weight if self.weight is not None else 1.0
        p = 1.0 - np.exp(-w * hhat)
        loss = _xent_loss(self.label, p)
        return _avg(loss, None, float(self.num_data))


@register_metric
class KLDivergenceMetric(Metric):
    """reference xentropy_metric.hpp:249-343: xentropy minus the constant
    label-entropy offset."""
    name = "kldiv"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        y = np.clip(self.label, 0.0, 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            ent = np.where((y > 0) & (y < 1),
                           -y * np.log(y) - (1 - y) * np.log(1 - y), 0.0)
        if self.weight is not None:
            ent = ent * self.weight
        # keep the LOCAL sum; the global average forms at eval time (init
        # can run before the process group is the final word on rank
        # membership, eval never does)
        self._local_entropy_sum = float(ent.sum())

    def eval(self, score, objective):
        from ..parallel.metric_sync import sync_sums

        if objective is not None:
            p = np.asarray(objective.convert_output(score[0]))
        else:
            p = score[0]
        xent = _avg(_xent_loss(self.label, p), self.weight, self.sum_weights)
        g_ent, g_w = sync_sums([self._local_entropy_sum, self.sum_weights])
        return xent - float(g_ent / g_w)


# ---------------------------------------------------------------------------
# Ranking metrics (reference rank_metric.hpp / map_metric.hpp)
# ---------------------------------------------------------------------------

def _sync_rank_sums(results: np.ndarray, sum_qw: float):
    """Queries live whole on one rank, so rank metrics reduce as plain
    (per-position weighted sums, query-weight sum) across processes."""
    from ..parallel.metric_sync import sync_sums

    g = sync_sums(np.concatenate([results, [sum_qw]]))
    return g[:-1], float(g[-1])


class _RankMetric(Metric):
    higher_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError(f"The {self.name} metric requires query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.query_boundaries) - 1
        self.query_weights = metadata.query_weights()
        self.sum_query_weights = (float(self.query_weights.sum())
                                  if self.query_weights is not None
                                  else float(self.num_queries))
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]


@register_metric
class NDCGMetric(_RankMetric):
    """reference rank_metric.hpp:20-175 + dcg_calculator.cpp."""
    name = "ndcg"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        from .objectives_ext import default_label_gain
        gains = list(self.config.label_gain) or default_label_gain()
        self.label_gain = np.asarray(gains, np.float64)
        lbl = self.label
        if lbl.min() < 0 or int(lbl.max()) >= len(self.label_gain):
            raise ValueError("label out of range for ndcg label_gain")
        # cache per-query inverse max DCG at each eval position
        # (reference rank_metric.hpp:63-80)
        self.inv_max_dcgs = np.zeros((self.num_queries, len(self.eval_at)))
        for q in range(self.num_queries):
            a, b = self.query_boundaries[q], self.query_boundaries[q + 1]
            top = np.sort(lbl[a:b])[::-1].astype(np.int64)
            disc = 1.0 / np.log2(2.0 + np.arange(len(top)))
            cum = np.cumsum(self.label_gain[top] * disc)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(top))
                m = cum[kk - 1] if kk > 0 else 0.0
                self.inv_max_dcgs[q, ki] = 1.0 / m if m > 0 else -1.0

    def eval_all(self, score, objective):
        s = score[0]
        lbl = self.label.astype(np.int64)
        results = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            qw = (self.query_weights[q] if self.query_weights is not None
                  else 1.0)
            if self.inv_max_dcgs[q, 0] <= 0:
                results += qw  # all-negative query counts as NDCG=1 (ref :104)
                continue
            order = np.argsort(-s[a:b], kind="stable")
            g = self.label_gain[lbl[a:b][order]]
            disc = 1.0 / np.log2(2.0 + np.arange(len(g)))
            cum = np.cumsum(g * disc)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(g))
                results[ki] += cum[kk - 1] * self.inv_max_dcgs[q, ki] * qw
        results, sum_qw = _sync_rank_sums(results, self.sum_query_weights)
        results /= sum_qw
        return [(f"ndcg@{k}", float(v)) for k, v in zip(self.eval_at, results)]

    def eval(self, score, objective):
        return self.eval_all(score, objective)[0][1]


@register_metric
class MapMetric(_RankMetric):
    """reference map_metric.hpp:20-180 (mean average precision @ k)."""
    name = "map"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.npos_per_query = np.zeros(self.num_queries, np.int64)
        for q in range(self.num_queries):
            a, b = self.query_boundaries[q], self.query_boundaries[q + 1]
            self.npos_per_query[q] = int((self.label[a:b] > 0.5).sum())

    def eval_all(self, score, objective):
        s = score[0]
        results = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            qw = (self.query_weights[q] if self.query_weights is not None
                  else 1.0)
            npos = int(self.npos_per_query[q])
            order = np.argsort(-s[a:b], kind="stable")
            hits = (self.label[a:b][order] > 0.5)
            cum_hits = np.cumsum(hits)
            ap_terms = np.where(hits, cum_hits / (np.arange(len(hits)) + 1.0), 0.0)
            cum_ap = np.cumsum(ap_terms)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(hits))
                if npos > 0:
                    results[ki] += (cum_ap[kk - 1] / min(npos, kk)) * qw
                else:
                    results[ki] += 1.0 * qw
        results, sum_qw = _sync_rank_sums(results, self.sum_query_weights)
        results /= sum_qw
        return [(f"map@{k}", float(v)) for k, v in zip(self.eval_at, results)]

    def eval(self, score, objective):
        return self.eval_all(score, objective)[0][1]


_METRIC_ALIASES.update({
    "mean_average_precision": "map",
    "xentropy": "cross_entropy",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv",
    "multiclass": "multi_logloss",
    "softmax": "multi_logloss",
    "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss",
    "ova": "multi_logloss",
    "ovr": "multi_logloss",
    "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
    "xendcg": "ndcg",
    "mean_absolute_percentage_error": "mape",
})
