"""Extended metric zoo (filled out in the objectives/metrics milestone)."""
